"""Benchmark: scheduling-cycle latency at the BASELINE.md north-star scale.

Measures the TPU solves against the strongest honest CPU baseline (the C++
sequential greedy in native/cook_native.cc — identical decisions to the
reference-style Fenzo greedy; numpy fallback when no toolchain):

  * headline: match cycle, 100k pending jobs x 10k nodes (BASELINE config 5
    problem size), p50 over repeated runs, plus packing-efficiency parity;
  * secondary (stderr): DRU ranking 110k tasks (config 2 scaled up) and
    rebalancer victim search 100k x 10k (config 4).

Prints ONE JSON line:
  {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": speedup}

Continuous-harness mode: every run also collects structured per-phase
results ({"schema": "cook-bench/v1", "phases": {match, dru, rebalance,
...}}) and writes them to a BENCH_r*.json record —
`BENCH_r{NN}_phases.json` (next free round index) for full runs,
`BENCH_rsmoke.json` for `python bench.py --smoke` (the tiny fast tier
also exercised by tests/test_bench_smoke.py).  `tools/bench_gate.py`
diffs the last two comparable records and exits non-zero on regression.
"""
import glob
import json
import os
import re
import sys
import time

import numpy as np

BENCH_SCHEMA = "cook-bench/v1"


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def make_problem(j, n, seed=0):
    rng = np.random.default_rng(seed)
    demands = np.stack(
        [
            rng.choice([512, 1024, 2048, 4096, 8192], j).astype(np.float32),
            rng.choice([0.5, 1, 2, 4], j).astype(np.float32),
            np.zeros(j, dtype=np.float32),
        ],
        axis=-1,
    )
    totals = np.stack(
        [np.full(n, 65536.0, dtype=np.float32),
         np.full(n, 32.0, dtype=np.float32)],
        axis=-1,
    )
    frac = rng.uniform(0.2, 1.0, (n, 1)).astype(np.float32)
    avail = np.concatenate([totals * frac, np.zeros((n, 1), np.float32)],
                           axis=-1)
    return demands, avail, totals


def _data_plane():
    from cook_tpu.obs import data_plane

    return data_plane


def byte_mark():
    """Ledger anchor for a phase's byte stamp (obs/data_plane.py)."""
    return _data_plane().LEDGER.byte_totals()


def byte_stamp(mark) -> dict:
    """H2D/D2H byte deltas since `mark` — stamped onto bench phases.
    Logical bytes are backend-stable (a CPU-fallback round moves the
    same bytes as a TPU round), so these are the columns bench_gate can
    diff even across backends."""
    h2d, d2h = _data_plane().LEDGER.byte_totals()
    return {"h2d_bytes": h2d - mark[0], "d2h_bytes": d2h - mark[1]}


def note_problem_bytes(tree, family=None):
    """Account a hand-built device problem's H2D (bench constructs its
    tensors with raw jnp.asarray, outside the scheduler's instrumented
    builds)."""
    dp = _data_plane()
    dp.note_h2d(dp.tree_nbytes(tree), family=family or dp.FAM_NODE_ENCODE)


def time_fn(fn, repeats=5):
    """Each fn MUST end in `cook_tpu.ops.common.fetch_result` (the one
    shared definition of "the solve finished": a device-to-host fetch,
    since block_until_ready returns early over remote-device tunnels and
    the scheduler consumes results host-side anyway)."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1000)
    return float(np.percentile(times, 50)), times


def cpu_greedy(demands, avail, totals):
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops import native

    if native.available():
        return native.greedy_match(demands.astype(np.float64),
                                   avail.astype(np.float64),
                                   totals.astype(np.float64)), "c++"
    return ref.np_greedy_match(demands, avail, totals), "numpy"


def load_tuned():
    """Hardware-measured best config written by tools/pick_tuned.py from
    the sweep results; falls back to the r2 sweep's efficient-frontier
    config when absent."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tuned_match.json")
    tuned = {"backend": "xla", "chunk": 1024, "rounds": 3, "passes": 2,
             "kc": 128,
             # hierarchical (match_xl) knobs a sweep may promote; the
             # QualityMonitor + parity tests guard any promoted value
             "hier_nodes_per_block": 512, "hier_coarse_backend": "xla"}
    try:
        with open(path) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            tuned.update({k: loaded[k] for k in tuned if k in loaded})
            log(f"using tuned config from tuned_match.json: {tuned}")
    except (OSError, ValueError):
        pass
    return tuned


def bench_match(jax, jnp, platform):
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.match import MatchProblem, backend_flags, chunked_match

    if platform == "cpu":
        # fallback sizing: keep the bench finishing in minutes on CPU XLA
        J, N = 16384, 2048
        j_real, n_real = 16_000, 2_000
    else:
        J, N = 131072, 16384  # padded buckets over 100k x 10k
        j_real, n_real = 100_000, 10_000
    demands, avail, totals = make_problem(J, N, seed=2)
    job_valid = np.zeros(J, dtype=bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n_real] = True
    mark = byte_mark()
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid),
        feasible=None,
    )
    note_problem_bytes(problem)

    tuned = load_tuned()
    # chunk and J are both powers of two, so min() keeps j % chunk == 0
    # on the reduced CPU-fallback sizing
    chunk = min(tuned["chunk"], J)
    if platform == "cpu" and tuned["backend"] == "pallas":
        # the Pallas kernel only compiles on real TPUs; interpret mode at
        # this problem size would run for hours
        log("cpu fallback: overriding tuned backend pallas -> xla")
        tuned = dict(tuned, backend="xla")

    def make_solve(cfg, cfg_chunk):
        def solve():
            result = chunked_match(problem, chunk=cfg_chunk,
                                   rounds=cfg["rounds"], kc=cfg["kc"],
                                   passes=cfg["passes"],
                                   **backend_flags(cfg["backend"]))
            return fetch_result(result.assignment)
        return solve

    solve = make_solve(tuned, chunk)
    t0 = time.perf_counter()
    try:
        assignment = solve()
    except Exception as e:  # noqa: BLE001 — a promoted tuned config (e.g.
        # a Pallas/Mosaic compile on this exact chip generation) must
        # never cost us the round's measurement; fall back to defaults
        log(f"tuned config failed to run ({type(e).__name__}: "
            f"{str(e)[:200]}); falling back to the default config")
        tuned = {"backend": "xla", "chunk": 1024, "rounds": 3,
                 "passes": 2, "kc": 128}
        chunk = min(tuned["chunk"], J)
        solve = make_solve(tuned, chunk)
        t0 = time.perf_counter()
        assignment = solve()
    log(f"match compile+first run: {(time.perf_counter()-t0)*1000:.0f} ms")
    # byte stamp: problem build + ONE solve's fetch — deterministic, so
    # the gate can diff it exactly record-to-record
    match_bytes = byte_stamp(mark)
    p50, times = time_fn(solve)
    tpu_assign = assignment[:j_real]

    t0 = time.perf_counter()
    cpu_assign, baseline_kind = cpu_greedy(
        demands[:j_real], avail[:n_real], totals[:n_real]
    )
    cpu_ms = (time.perf_counter() - t0) * 1000
    q_cpu = ref.packing_quality(demands[:j_real], cpu_assign)
    q_tpu = ref.packing_quality(demands[:j_real], tpu_assign)
    eff = (q_tpu["cpus_placed"] / q_cpu["cpus_placed"]
           if q_cpu["cpus_placed"] else 1.0)
    log(f"match {j_real} x {n_real}: device p50 {p50:.1f} ms "
        f"(all {[f'{t:.0f}' for t in times]}); cpu[{baseline_kind}] "
        f"{cpu_ms:.0f} ms; placed device {q_tpu['num_placed']} vs cpu "
        f"{q_cpu['num_placed']}; packing efficiency {eff:.4f}")
    return p50, cpu_ms, eff, (j_real, n_real), match_bytes


def make_dru_problem(jnp, t, u, t_real=None, seed=3):
    """DruTasks + divisors at any size — ONE construction for the full
    and smoke tiers (same field semantics; a new DruTasks column changes
    both or neither).  Returns (tasks, div, host) where `host` holds the
    raw numpy columns for the C++ baseline."""
    from cook_tpu.ops.dru import DruTasks

    rng = np.random.default_rng(seed)
    user = rng.integers(0, u, t).astype(np.int32)
    mem = rng.uniform(100, 8000, t).astype(np.float32)
    cpus = rng.uniform(0.5, 8, t).astype(np.float32)
    order = rng.permutation(t).astype(np.float32)
    valid = (np.ones(t, bool) if t_real is None
             else np.arange(t) < t_real)
    tasks = DruTasks(
        user=jnp.asarray(user), mem=jnp.asarray(mem), cpus=jnp.asarray(cpus),
        gpus=jnp.zeros(t, jnp.float32), order_key=jnp.asarray(order),
        valid=jnp.asarray(valid),
    )
    div = jnp.asarray(rng.uniform(100, 1000, u).astype(np.float32))
    host = {"user": user, "mem": mem, "cpus": cpus, "order": order}
    return tasks, div, host


def make_rebalance_state(jnp, t, h, t_real=None, h_real=None, seed=4):
    """RebalanceState at any size — shared by the full and smoke tiers.
    t_real/h_real mask the padded tail (None = everything live)."""
    from cook_tpu.ops.rebalance import RebalanceState

    rng = np.random.default_rng(seed)
    h_live = h if h_real is None else h_real
    task_host = rng.integers(0, h_live, t).astype(np.int32)
    task_dru = rng.uniform(0, 5, t).astype(np.float32)
    task_res = np.stack([rng.uniform(100, 8000, t),
                         rng.uniform(0.5, 8, t),
                         np.zeros(t)], axis=-1).astype(np.float32)
    live = np.ones(t, bool) if t_real is None else np.arange(t) < t_real
    task_eligible = live & (rng.uniform(size=t) > 0.2)
    spare = np.stack([rng.uniform(0, 4000, h), rng.uniform(0, 4, h),
                      np.zeros(h)], axis=-1).astype(np.float32)
    host_ok = np.ones(h, bool) if h_real is None else np.arange(h) < h_real
    return RebalanceState(
        task_host=jnp.asarray(task_host), task_dru=jnp.asarray(task_dru),
        task_res=jnp.asarray(task_res),
        task_eligible=jnp.asarray(task_eligible),
        spare=jnp.asarray(spare), host_ok=jnp.asarray(host_ok),
    )


def bench_match_xl(jax, jnp, platform, *, smoke=False, repeats=3) -> dict:
    """`match_xl` tier: the hierarchical two-level matcher
    (ops/hierarchical.py) at the SNIPPETS.md north-star scale — one pool
    of 100k jobs x 10k nodes (padded 131072 x 16384), decomposed into
    topology blocks whose fine problems solve as one batched kernel
    sharded over the mesh.  The smoke variant (2k x 256) runs in seconds
    and is diffed by bench_gate in ci_checks, so the trajectory toward
    the <200 ms/cycle target is measured every round.  Per-phase p50s
    (coarse/fine/refine) ride along as their own gate-visible phases."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.hierarchical import HierParams, hierarchical_match
    from cook_tpu.ops.match import MatchProblem

    if smoke:
        J, N = 2048, 256
        j_real, n_real = 2000, 256
        params = HierParams(nodes_per_block=64, chunk=256, kc=32)
    else:
        J, N = 131072, 16384  # padded buckets over 100k x 10k
        j_real, n_real = 100_000, 10_000
        tuned = load_tuned()
        # default nodes_per_block=512 -> 32 blocks: measured the best
        # wall/quality point on the CPU fallback and plenty of mesh
        # lanes on real hardware; the fine solve reuses the tuned
        # chunked-matcher knobs, and a sweep can promote the block
        # width / coarse backend via tuned_match.json
        params = HierParams(nodes_per_block=tuned["hier_nodes_per_block"],
                            chunk=min(tuned["chunk"], 8192),
                            rounds=tuned["rounds"], passes=tuned["passes"],
                            kc=tuned["kc"],
                            coarse_backend=tuned["hier_coarse_backend"])
    demands, avail, totals = make_problem(J, N, seed=2)
    job_valid = np.zeros(J, dtype=bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n_real] = True
    mark = byte_mark()
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid), feasible=None,
    )
    note_problem_bytes(problem)
    mesh = None
    if len(jax.devices()) > 1:
        from cook_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

    runs = []

    def solve():
        result, stats = hierarchical_match(problem, params=params,
                                           mesh=mesh)
        runs.append(stats)
        return np.asarray(result.assignment)

    t0 = time.perf_counter()
    assignment = solve()
    log(f"match_xl compile+first run: "
        f"{(time.perf_counter() - t0) * 1000:.0f} ms "
        f"(blocks {runs[-1]['blocks']}, fine {runs[-1]['fine_shape']})")
    # problem build + one full coarse/fine/refine solve's traffic
    xl_bytes = byte_stamp(mark)
    p50, times = time_fn(solve, repeats=repeats)
    timed = runs[-repeats:]

    def phase_p50(key):
        return float(np.percentile([s[key] * 1000 for s in timed], 50))

    # packing-efficiency parity vs the strongest honest CPU baseline —
    # cheap at smoke size; at full size only when the C++ greedy is
    # available (the pure-python reference would take longer than the
    # whole tier)
    from cook_tpu.ops import native

    eff = None
    if smoke or native.available():
        cpu_assign, kind = cpu_greedy(demands[:j_real], avail[:n_real],
                                      totals[:n_real])
        q_cpu = ref.packing_quality(demands[:j_real], cpu_assign)
        q_dev = ref.packing_quality(demands[:j_real], assignment[:j_real])
        eff = (q_dev["cpus_placed"] / q_cpu["cpus_placed"]
               if q_cpu["cpus_placed"] else 1.0)
        log(f"match_xl {j_real} x {n_real} [{platform}]: p50 {p50:.1f} ms "
            f"(all {[f'{t:.0f}' for t in times]}); "
            f"cpu[{kind}] placed {q_cpu['num_placed']} vs device "
            f"{q_dev['num_placed']}; packing efficiency {eff:.4f}")
    else:
        log(f"match_xl {j_real} x {n_real} [{platform}]: p50 {p50:.1f} ms "
            f"(all {[f'{t:.0f}' for t in times]}); no C++ baseline — "
            f"packing efficiency skipped")
    stats = timed[-1]
    out = {
        "match_xl": {"p50_ms": p50, "jobs": j_real, "nodes": n_real,
                     "blocks": stats["blocks"],
                     "nodes_per_block": stats["nodes_per_block"],
                     "spilled": stats["spilled"], **xl_bytes,
                     **({"packing_eff": eff} if eff is not None else {})},
        "match_xl_coarse": {"p50_ms": phase_p50("coarse_s")},
        "match_xl_fine": {"p50_ms": phase_p50("fine_s")},
        "match_xl_refine": {"p50_ms": phase_p50("refine_s")},
    }
    return out


def bench_match_xxl(jax, jnp, platform, *, smoke=False, repeats=1) -> dict:
    """`match_xxl` tier: the SUPERBLOCK mega-matcher — 1M jobs x 100k
    nodes through the two-level DCN x ICI decomposition
    (ops/hierarchical.py superblock layer): one super-coarse
    jobs x superblocks solve routes every job to a DCN domain, then
    per-superblock coarse problems solve as ONE batched kernel, then the
    unchanged fine/refine machinery.  The flat solve at this scale is
    not tractable on any backend; the single-level match_xl coarse pass
    alone would be a 1M x 2048-block problem.  CPU fallback is allowed
    and stamped (`backend` + `cores` columns) — logical byte columns are
    backend-stable, so bench_gate diffs them across machines.  Per-level
    walls (super_coarse/coarse/fine/refine) ride as their own phases."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.hierarchical import HierParams, hierarchical_match
    from cook_tpu.ops.match import MatchProblem

    if smoke:
        J, N = 8192, 1024
        j_real, n_real = 8000, 1000
        # 64-node blocks, 256-node superblocks -> 4 blocks/superblock,
        # 4 superblocks: every level genuinely engaged at smoke size
        params = HierParams(nodes_per_block=64, superblock_nodes=256,
                            chunk=256, kc=32)
    else:
        J, N = 1_048_576, 102_400
        j_real, n_real = 1_000_000, 100_000
        tuned = load_tuned()
        # 512-node blocks x 16-block superblocks = 8192-node DCN
        # domains -> 13 superblocks over 100k nodes; the coarse level
        # sees [16, slots, 16] batched problems instead of one
        # 1M x 256-block monolith
        params = HierParams(nodes_per_block=tuned["hier_nodes_per_block"],
                            superblock_nodes=(
                                16 * tuned["hier_nodes_per_block"]),
                            chunk=min(tuned["chunk"], 8192),
                            rounds=tuned["rounds"], passes=tuned["passes"],
                            kc=tuned["kc"])
    demands, avail, totals = make_problem(J, N, seed=4)
    job_valid = np.zeros(J, dtype=bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n_real] = True
    mark = byte_mark()
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid), feasible=None,
    )
    note_problem_bytes(problem)
    mesh = None
    if len(jax.devices()) > 1:
        from cook_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

    runs = []

    def solve():
        result, stats = hierarchical_match(problem, params=params,
                                           mesh=mesh)
        runs.append(stats)
        return np.asarray(result.assignment)

    t0 = time.perf_counter()
    assignment = solve()
    log(f"match_xxl compile+first run: "
        f"{(time.perf_counter() - t0) * 1000:.0f} ms (superblocks "
        f"{runs[-1]['superblocks']} x {runs[-1]['superblock_blocks']} "
        f"blocks, super {runs[-1]['super_shape']}, coarse "
        f"{runs[-1]['coarse_shape']}, fine {runs[-1]['fine_shape']})")
    xxl_bytes = byte_stamp(mark)
    p50, times = time_fn(solve, repeats=repeats)
    timed = runs[-repeats:]

    def phase_p50(key):
        return float(np.percentile([s[key] * 1000 for s in timed], 50))

    eff = None
    if smoke:
        # hierarchical parity vs the flat CPU reference on the
        # superblock path — the >= 0.95 acceptance bar, checked every
        # CI run at smoke size (the full size has no tractable flat
        # reference; tests/test_superblocks.py pins the bar too)
        cpu_assign, kind = cpu_greedy(demands[:j_real], avail[:n_real],
                                      totals[:n_real])
        q_cpu = ref.packing_quality(demands[:j_real], cpu_assign)
        q_dev = ref.packing_quality(demands[:j_real], assignment[:j_real])
        eff = (q_dev["cpus_placed"] / q_cpu["cpus_placed"]
               if q_cpu["cpus_placed"] else 1.0)
        log(f"match_xxl {j_real} x {n_real} [{platform}]: p50 {p50:.1f} ms"
            f"; cpu[{kind}] placed {q_cpu['num_placed']} vs device "
            f"{q_dev['num_placed']}; packing efficiency {eff:.4f}")
    else:
        log(f"match_xxl {j_real} x {n_real} [{platform}]: p50 {p50:.1f} ms"
            f" (all {[f'{t:.0f}' for t in times]})")
    stats = timed[-1]
    # backend + cores stamped on EVERY phase row: a CPU-fallback number
    # must never read as a TPU number in bench_history
    stamp = {"backend": platform, "cores": os.cpu_count()}
    out = {
        "match_xxl": {"p50_ms": p50, "jobs": j_real, "nodes": n_real,
                      "superblocks": stats["superblocks"],
                      "superblock_nodes": stats["superblock_nodes"],
                      "blocks": stats["blocks"],
                      "nodes_per_block": stats["nodes_per_block"],
                      "spilled": stats["spilled"],
                      "superblock_spilled": stats["superblock_spilled"],
                      **xxl_bytes, **stamp,
                      **({"packing_eff": eff} if eff is not None else {})},
        "match_xxl_super_coarse": {"p50_ms": phase_p50("super_coarse_s"),
                                   **stamp},
        "match_xxl_coarse": {"p50_ms": phase_p50("coarse_s"), **stamp},
        "match_xxl_fine": {"p50_ms": phase_p50("fine_s"), **stamp},
        "match_xxl_refine": {"p50_ms": phase_p50("refine_s"), **stamp},
    }
    return out


def bench_dru(jax, jnp):
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.dru import dru_rank

    T, U = 131072, 64
    t_real = 110_000
    tasks, div, host = make_dru_problem(jnp, T, U, t_real=t_real, seed=3)
    user, mem, cpus, order = (host["user"], host["mem"], host["cpus"],
                              host["order"])

    def solve():
        return fetch_result(dru_rank(tasks, div, div, div).rank)

    solve()
    p50, _ = time_fn(solve)

    from cook_tpu.ops import native
    if native.available():
        t0 = time.perf_counter()
        native.dru_rank(user[:t_real], mem[:t_real], cpus[:t_real],
                        np.zeros(t_real), order[:t_real],
                        np.asarray(div, np.float64), np.asarray(div, np.float64),
                        np.asarray(div, np.float64))
        cpu_ms = (time.perf_counter() - t0) * 1000
    else:
        cpu_ms = float("nan")
    log(f"dru rank 110k tasks/64 users: tpu p50 {p50:.1f} ms; "
        f"cpu[c++] {cpu_ms:.1f} ms")
    return p50


def bench_multipool(jax, jnp, tuned):
    """BASELINE config 3: multi-pool cpu+mem+gpu bin-packing, pools as the
    batch axis of one vmapped solve."""
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.match import (MatchProblem, backend_flags,
                                    chunked_match, vmap_safe_backend)

    P, J, N = 8, 16384, 2048
    rng = np.random.default_rng(5)
    demands = np.stack([
        rng.choice([512, 1024, 2048, 4096], (P, J)).astype(np.float32),
        rng.choice([0.5, 1, 2, 4], (P, J)).astype(np.float32),
        (rng.uniform(size=(P, J)) < 0.1).astype(np.float32)
        * rng.integers(1, 4, (P, J)).astype(np.float32),
    ], axis=-1)
    totals = np.stack([
        np.full((P, N), 65536.0, np.float32),
        np.full((P, N), 32.0, np.float32),
    ], axis=-1)
    gpus = np.where(rng.uniform(size=(P, N, 1)) < 0.2, 8.0, 0.0)
    avail = np.concatenate(
        [totals * rng.uniform(0.2, 1.0, (P, N, 1)).astype(np.float32),
         gpus.astype(np.float32)], axis=-1)
    problems = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.ones((P, J), bool),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.ones((P, N), bool),
        feasible=None,
    )
    # pallas_call batching under vmap is not guaranteed; the pool-batched
    # solve keeps to the pure-XLA backends
    backend = vmap_safe_backend(tuned["backend"])
    solve = jax.vmap(
        lambda p: chunked_match(p, chunk=min(tuned["chunk"], J),
                                rounds=tuned["rounds"], kc=tuned["kc"],
                                passes=tuned["passes"],
                                **backend_flags(backend))
    )

    def run():
        return fetch_result(solve(problems).assignment)

    run()
    p50, _ = time_fn(run)
    assignment = run()
    placed = int((assignment >= 0).sum())
    log(f"multi-pool 8 x (16k x 2k) cpu+mem+gpu: p50 {p50:.1f} ms, "
        f"placed {placed}/{P * J}")
    return p50


def _pipeline_scenario(n_pools, hosts_per_pool, jobs_per_pool, seed=11,
                       chunk=512, rounds=6, kc=128):
    """Fresh multi-pool scheduler + deterministically seeded job set for
    the pipelined-vs-serial cycle comparison.  Same seed -> identical
    problem, so serial and pipelined runs are parity-comparable."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig

    rng = np.random.default_rng(seed)
    store = JobStore(clock=lambda: 1_000_000)
    hosts = []
    for p in range(n_pools):
        store.set_pool(Pool(name=f"pool{p}"))
        for i in range(hosts_per_pool):
            hosts.append(MockHost(node_id=f"p{p}h{i}", hostname=f"p{p}h{i}",
                                  mem=32768.0, cpus=16.0, pool=f"pool{p}"))
    cluster = MockCluster("bench", hosts, clock=store.clock)
    config = SchedulerConfig(
        match=MatchConfig(chunk=chunk, chunk_rounds=rounds, chunk_passes=2,
                          chunk_kc=kc, quality_audit_every=0),
        device_telemetry=False,
    )
    scheduler = Scheduler(store, [cluster], config)
    jobs = []
    mems = rng.choice([512.0, 1024.0, 2048.0, 4096.0],
                      (n_pools, jobs_per_pool))
    cpus = rng.choice([1.0, 2.0, 4.0], (n_pools, jobs_per_pool))
    for p in range(n_pools):
        for i in range(jobs_per_pool):
            jobs.append(Job(
                uuid=f"bench-{p}-{i}", user=f"u{i % 8}", pool=f"pool{p}",
                priority=50,
                resources=Resources(mem=float(mems[p, i]),
                                    cpus=float(cpus[p, i])),
                command="true",
            ))
    store.submit_jobs(jobs)
    return store, scheduler


def _run_match_pass(store, scheduler, pipelined: bool):
    """One multi-pool match pass; returns (wall_ms, phase_sum_ms,
    overlap_fraction, matched set).  Rank runs outside the timed section
    — the compared quantity is the cycle's tensor_build+solve+launch.
    GC is paused across the timed section (collections land between
    passes, not inside one — a gen-2 sweep mid-cycle is 100+ ms of pure
    measurement noise at this object count).  The pipelined wall is the
    engine's own pass wall (record.pipeline_wall_s): both sides of the
    comparison then exclude the identical multi-pool epilogue
    (spare-cache refresh, queue filtering, record commit), which the
    serial side's summed phases never contained either."""
    import gc

    from cook_tpu.scheduler.pipeline import PIPELINE_PHASES

    pools = [p for p in store.pools.values() if p.schedules_jobs]
    for pool in pools:
        scheduler.rank_cycle(pool)
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        if pipelined:
            outcomes = scheduler.match_cycle_pipelined()
        else:
            outcomes = {p.name: scheduler.match_cycle(p) for p in pools}
        wall_ms = (time.perf_counter() - t0) * 1000
    finally:
        gc.enable()
    records = scheduler.recorder.records(limit=len(pools))
    phase_sum_ms = sum(
        r.phases.get(name, 0.0) for r in records for name in PIPELINE_PHASES
    ) * 1000
    overlap = max((r.overlap_fraction for r in records), default=0.0)
    if pipelined and records:
        wall_ms = records[-1].pipeline_wall_s * 1000
    matched = {(j.uuid, o.hostname)
               for out in outcomes.values() for j, o in out.matched}
    return wall_ms, phase_sum_ms, overlap, matched


def bench_pipeline(jax, jnp, *, n_pools=6, hosts_per_pool=24,
                   jobs_per_pool=1800, rounds=8, repeats=5) -> dict:
    """Pipelined match cycle vs the serial per-pool loop on the SAME
    seeded multi-pool problem (scheduler/pipeline.py).  Reports the
    serial pass's wall and summed phases, the pipelined pass's wall, the
    recorded device/host overlap fraction, and decision parity — the
    ISSUE-5 acceptance bar is pipelined wall < 0.8 x the summed serial
    tensor_build+solve+launch phases, with a nonzero overlap fraction."""
    serial_walls, serial_sums = [], []
    pipe_walls, overlaps = [], []
    parity = True
    serial_matched = None
    # warmup run per mode pays the XLA compiles (shapes repeat across
    # runs; fresh schedulers per run keep the problem identical)
    for warm_pipelined in (False, True):
        store, scheduler = _pipeline_scenario(n_pools, hosts_per_pool,
                                              jobs_per_pool, rounds=rounds)
        _run_match_pass(store, scheduler, warm_pipelined)
    for _ in range(repeats):
        store, scheduler = _pipeline_scenario(n_pools, hosts_per_pool,
                                              jobs_per_pool, rounds=rounds)
        wall, phase_sum, _, matched = _run_match_pass(store, scheduler,
                                                      False)
        serial_walls.append(wall)
        serial_sums.append(phase_sum)
        serial_matched = matched
        store, scheduler = _pipeline_scenario(n_pools, hosts_per_pool,
                                              jobs_per_pool, rounds=rounds)
        wall, _, overlap, matched = _run_match_pass(store, scheduler, True)
        pipe_walls.append(wall)
        overlaps.append(overlap)
        parity = parity and matched == serial_matched
    p50_pipe = float(np.percentile(pipe_walls, 50))
    p50_serial = float(np.percentile(serial_walls, 50))
    serial_sum = float(np.percentile(serial_sums, 50))
    overlap = float(np.percentile(overlaps, 50))
    log(f"pipeline {n_pools} pools x ({jobs_per_pool} jobs x "
        f"{hosts_per_pool} hosts): pipelined p50 {p50_pipe:.1f} ms vs "
        f"serial {p50_serial:.1f} ms (summed phases {serial_sum:.1f} ms); "
        f"overlap {overlap:.2f}, parity {parity}, "
        f"wall/serial_sum {p50_pipe / max(serial_sum, 1e-9):.2f}")
    return {
        "pipeline": {"p50_ms": p50_pipe, "pools": n_pools,
                     "jobs": jobs_per_pool, "hosts": hosts_per_pool,
                     "overlap_fraction": overlap,
                     "serial_phase_sum_ms": serial_sum,
                     "parity": bool(parity)},
        "pipeline_serial": {"p50_ms": p50_serial, "pools": n_pools,
                            "jobs": jobs_per_pool,
                            "hosts": hosts_per_pool},
    }


def bench_speculation(*, smoke=False) -> dict:
    """`speculation` phase: prediction-assisted speculative cycles
    (scheduler/prediction.py) A/B on the seeded completion-heavy
    wave-drain trace (sim/loadgen.completion_heavy_trace) — the SAME
    simulator run with and without speculation.  Gated p50 is the
    speculative run's cycle-start-to-first-launch latency (the window
    speculation exists to close); the fraction of cycles served from a
    committed speculation and the non-speculative baseline ride in the
    record.  The ISSUE-10 acceptance bar is >= 20% of cycles served from
    speculation with a measurably lower pre-launch p50."""
    from cook_tpu.scheduler.core import SchedulerConfig
    from cook_tpu.sim.loadgen import completion_heavy_trace
    from cook_tpu.sim.simulator import SimConfig, Simulator

    if smoke:
        n_jobs, n_hosts, cycles = 24, 4, 40
    else:
        n_jobs, n_hosts, cycles = 192, 16, 80

    def run(speculate):
        jobs, hosts = completion_heavy_trace(jobs=n_jobs, hosts=n_hosts)
        config = SimConfig(
            cycle_ms=30_000, max_cycles=cycles, speculate=speculate,
            scheduler=SchedulerConfig(device_telemetry=False),
        )
        return Simulator(jobs, hosts, config).run().speculation_stats()

    # best-of-3 BOTH sides: the speculative pre-launch p50 is a
    # sub-millisecond host measurement (the commit-validation wall) and
    # a single run's p50 swings several ms under concurrent CPU load —
    # the min is the honest "what the path costs" figure (the same
    # robust-to-load idiom as the columnar rank-speed test), and the
    # baseline gets the identical treatment so the A/B stays symmetric
    base = min((run(False) for _ in range(3)),
               key=lambda s: s["pre_launch_p50_ms"])
    spec = min((run(True) for _ in range(3)),
               key=lambda s: s["pre_launch_p50_ms"])
    log(f"speculation {n_jobs} jobs x {n_hosts} hosts: hit fraction "
        f"{spec['hit_fraction']:.2f} over {spec['cycles']} cycles; "
        f"pre-launch p50 {spec['pre_launch_p50_ms']:.2f} ms speculative "
        f"vs {base['pre_launch_p50_ms']:.2f} ms baseline")
    return {
        "speculation": {
            "p50_ms": spec["pre_launch_p50_ms"],
            "hit_fraction": spec["hit_fraction"],
            "cycles": spec["cycles"],
            "baseline_p50_ms": base["pre_launch_p50_ms"],
            "jobs": n_jobs,
            "hosts": n_hosts,
        },
    }


def bench_gang(*, smoke=False) -> dict:
    """`gang` phase: topology-aware gang scheduling (scheduler/gang.py +
    the matcher's all-or-nothing chokepoint) on the seeded gang/topology
    trace (sim/loadgen.gang_topology_trace).  Gated p50 is the gang
    admission latency — submit to all-members-running, in VIRTUAL ms, so
    the figure is deterministic and a regression means the placement
    logic got worse, not the machine slower.  `placed_fraction`
    (gangs fully placed / gangs) and `assembled_share` / `block_spread`
    ride in the record; the acceptance bar is every gang placed whole
    with block spread 1.0."""
    from cook_tpu.scheduler.core import SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig
    from cook_tpu.sim.loadgen import gang_topology_trace
    from cook_tpu.sim.simulator import SimConfig, Simulator

    if smoke:
        n_blocks, block_hosts, gang_sizes = 2, 4, (4, 4, 2)
    else:
        n_blocks, block_hosts, gang_sizes = 4, 8, (8, 8, 4, 4, 2, 2)

    jobs, hosts = gang_topology_trace(
        n_blocks=n_blocks, block_hosts=block_hosts, gang_sizes=gang_sizes)
    config = SimConfig(
        cycle_ms=30_000, max_cycles=200,
        scheduler=SchedulerConfig(
            device_telemetry=False,
            match=MatchConfig(gang_enabled=True,
                              topology_block_hosts=block_hosts,
                              topology_weight=0.5)),
    )
    result = Simulator(jobs, hosts, config).run()
    stats = result.gang_stats(jobs, hosts, nodes_per_block=block_hosts)
    placed = sum(1 for g in stats["per_gang"]
                 if g["placed_members"] == g["size"])
    placed_fraction = placed / stats["gangs"] if stats["gangs"] else 0.0
    log(f"gang {stats['gangs']} gangs on {n_blocks}x{block_hosts} hosts: "
        f"admission p50 {stats['wait_ms_p50']:.0f} virtual-ms, placed "
        f"fraction {placed_fraction:.2f}, assembled "
        f"{stats['assembled']}/{stats['gangs']}, block spread "
        f"{stats['mean_block_spread']:.2f}")
    return {
        "gang": {
            "p50_ms": stats["wait_ms_p50"],
            "placed_fraction": placed_fraction,
            "assembled_share": stats["assembled_share"],
            "block_spread": stats["mean_block_spread"],
            "gangs": stats["gangs"],
            "hosts": n_blocks * block_hosts,
        },
    }


def encode_family_mark():
    """Node-encode + job-feasibility H2D totals — the exact families the
    device-resident mirror (scheduler/device_state.py) keeps on device;
    the match_resident phase's warm-vs-cold claim is judged on these."""
    totals = _data_plane().LEDGER.family_totals()
    dp = _data_plane()
    return sum(totals.get(fam, {}).get("h2d_bytes", 0)
               for fam in (dp.FAM_NODE_ENCODE, dp.FAM_FEASIBILITY))


def bench_match_resident(*, smoke=False) -> dict:
    """`match_resident` tier: device-resident match state
    (scheduler/device_state.py) through a REAL scheduler — one cold
    cycle (full rebuild upload) then three warm cycles (two unchanged,
    one with a single submitted job exercising the O(delta) scatter).
    The gated columns are the WARM phase's p50 and its `h2d_bytes` —
    byte growth on warm cycles is a regression, not informational
    (tools/bench_gate.py gates match_resident* byte columns by
    default).  `encode_h2d_bytes` carries the node-encode +
    job-feasibility split the >=90% warm-reduction acceptance bar is
    judged on (PR 11 TransferLedger stamps)."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig

    if smoke:
        n_jobs, n_hosts = 1000, 16
    else:
        n_jobs, n_hosts = 8000, 128
    store = JobStore(clock=lambda: 1_000_000)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4096.0,
                      cpus=8.0) for i in range(n_hosts)]
    cluster = MockCluster("bench", hosts, clock=store.clock)
    config = SchedulerConfig(
        match=MatchConfig(chunk=0, device_residency=True,
                          quality_audit_every=0),
        device_telemetry=False,
    )
    scheduler = Scheduler(store, [cluster], config)
    # near-host-size jobs: a handful match on the cold cycle, the rest
    # wait — so warm cycles see an UNCHANGED pool (the residency case)
    # while the solve still runs the real kernel end to end
    store.submit_jobs([
        Job(uuid=f"res-{i}", user=f"u{i % 8}", pool="default", priority=50,
            resources=Resources(mem=4000.0, cpus=8.0), command="true")
        for i in range(n_jobs)
    ])
    pool = store.pools["default"]

    def cycle():
        mark, enc = byte_mark(), encode_family_mark()
        t0 = time.perf_counter()
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        wall_ms = (time.perf_counter() - t0) * 1000
        stamp = byte_stamp(mark)
        stamp["encode_h2d_bytes"] = encode_family_mark() - enc
        return wall_ms, stamp

    cold_ms, cold = cycle()
    warm_walls, warm = [], {"h2d_bytes": 0, "d2h_bytes": 0,
                            "encode_h2d_bytes": 0}
    for i in range(3):
        if i == 2:
            # one delta cycle: a single new job must ride the donated-
            # buffer scatter, not a rebuild
            store.submit_jobs([Job(
                uuid=f"res-delta-{i}", user="delta", pool="default",
                priority=50, resources=Resources(mem=4000.0, cpus=8.0),
                command="true")])
        wall_ms, stamp = cycle()
        warm_walls.append(wall_ms)
        for col in warm:
            warm[col] += stamp[col]
    warm_p50 = float(np.percentile(warm_walls, 50))
    reduction = (1.0 - warm["encode_h2d_bytes"] / 3.0
                 / max(cold["encode_h2d_bytes"], 1))
    last = scheduler.recorder.records(limit=1)[0].device_state
    log(f"match_resident {n_jobs} jobs x {n_hosts} hosts: cold "
        f"{cold_ms:.1f} ms / {cold['encode_h2d_bytes']} encode B; warm "
        f"p50 {warm_p50:.1f} ms / {warm['encode_h2d_bytes']} encode B "
        f"over 3 cycles (per-cycle reduction {reduction:.1%}); last "
        f"cycle delta_rows={last.get('delta_rows')} "
        f"rebuild={last.get('rebuild')}")
    return {
        "match_resident": {"p50_ms": warm_p50, "jobs": n_jobs,
                           "hosts": n_hosts, "warm_cycles": 3,
                           **warm,
                           "encode_reduction": reduction},
        "match_resident_cold": {"p50_ms": cold_ms, "jobs": n_jobs,
                                "hosts": n_hosts, **cold},
    }


def _family_h2d(family) -> int:
    dp = _data_plane()
    return dp.LEDGER.family_totals().get(family, {}).get("h2d_bytes", 0)


def bench_rebalance_resident(*, smoke=False) -> dict:
    """`rebalance_resident` tier: the rebalancer's cycle-start victim
    tensors through the keyed-row resident mirror
    (scheduler/device_state.ResidentRows) — one cold cycle (full
    rebuild), two unchanged warm cycles, one delta cycle (a task
    finishes).  `encode_h2d_bytes` is the FAM_REBALANCE ledger column
    the >= 90% warm-reduction bar is judged on; bench_gate gates the
    rebalance_resident* byte columns like match_resident's."""
    from cook_tpu.models.entities import (DEFAULT_USER, Pool, Resources,
                                          Share)
    from cook_tpu.models.store import JobStore
    from cook_tpu.obs import data_plane
    from cook_tpu.scheduler.device_state import ResidentRows
    from cook_tpu.scheduler.rebalancer import (RebalancerParams,
                                               rebalance_pool)

    if smoke:
        n_hosts, tasks_per_host = 8, 4
    else:
        n_hosts, tasks_per_host = 64, 16
    store = JobStore(clock=lambda: 1_000_000)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=1)))
    from cook_tpu.models.entities import Job

    for h in range(n_hosts):
        for k in range(tasks_per_host):
            job = Job(uuid=f"reb-{h}-{k}", user=f"hog{k % 4}",
                      pool="default", priority=50,
                      resources=Resources(mem=300.0 + 10 * h, cpus=3.0),
                      command="true")
            store.submit_jobs([job])
            store.create_instance(job.uuid, f"t-{h}-{k}",
                                  hostname=f"h{h}", node_id=f"h{h}",
                                  compute_cluster="bench")
    spare = {f"h{h}": Resources(mem=50.0, cpus=1.0)
             for h in range(n_hosts)}
    params = RebalancerParams(safe_dru_threshold=0.0, min_dru_diff=0.01,
                              max_preemption=8, resident=True)
    mirror = ResidentRows("rebalance:bench",
                          family=data_plane.FAM_REBALANCE)
    pool = store.pools["default"]

    def cycle():
        mark = byte_mark()
        fam0 = _family_h2d(data_plane.FAM_REBALANCE)
        t0 = time.perf_counter()
        # empty pending queue: measures the cycle-START tensor build,
        # the path the mirror serves (decision scatters are O(changed)
        # either way)
        rebalance_pool(store, pool, [], dict(spare), params,
                       resident=mirror)
        wall_ms = (time.perf_counter() - t0) * 1000
        stamp = byte_stamp(mark)
        stamp["encode_h2d_bytes"] = (
            _family_h2d(data_plane.FAM_REBALANCE) - fam0)
        return wall_ms, stamp

    cold_ms, cold = cycle()
    warm_walls, warm = [], {"h2d_bytes": 0, "d2h_bytes": 0,
                            "encode_h2d_bytes": 0}
    for i in range(3):
        if i == 2:
            # one delta cycle: a finished task must ride the
            # donated-buffer scatter, not a rebuild
            from cook_tpu.models.entities import InstanceStatus

            store.update_instance_state("t-0-0", InstanceStatus.SUCCESS)
        wall_ms, stamp = cycle()
        warm_walls.append(wall_ms)
        for col in warm:
            warm[col] += stamp[col]
    warm_p50 = float(np.percentile(warm_walls, 50))
    reduction = (1.0 - warm["encode_h2d_bytes"] / 3.0
                 / max(cold["encode_h2d_bytes"], 1))
    n_tasks = n_hosts * tasks_per_host
    log(f"rebalance_resident {n_tasks} tasks x {n_hosts} hosts: cold "
        f"{cold_ms:.1f} ms / {cold['encode_h2d_bytes']} B; warm p50 "
        f"{warm_p50:.1f} ms / {warm['encode_h2d_bytes']} B over 3 "
        f"cycles (per-cycle reduction {reduction:.1%}); last "
        f"delta_rows={mirror.last.get('delta_rows')} "
        f"rebuild={mirror.last.get('rebuild')}")
    return {
        "rebalance_resident": {"p50_ms": warm_p50, "tasks": n_tasks,
                               "hosts": n_hosts, "warm_cycles": 3,
                               **warm, "encode_reduction": reduction},
        "rebalance_resident_cold": {"p50_ms": cold_ms, "tasks": n_tasks,
                                    "hosts": n_hosts, **cold},
    }


def bench_elastic_resident(*, smoke=False) -> dict:
    """`elastic_resident` tier: the capacity planner's per-interval
    demand/capacity tensors through the keyed-row resident mirror —
    cold plan, two unchanged warm plans, one delta plan (one pool's
    queue grows by a job).  `encode_h2d_bytes` is the FAM_ELASTIC
    column; gated like the other resident tiers."""
    import types

    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.elastic import CapacityPlanner, ElasticParams
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.obs import data_plane
    from cook_tpu.txn import TransactionLog

    if smoke:
        n_pools, queue_len = 4, 16
    else:
        n_pools, queue_len = 16, 256
    store = JobStore(clock=lambda: 1_000_000)
    for i in range(n_pools):
        store.set_pool(Pool(name=f"p{i}"))
    cluster = MockCluster("bench", [
        MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000.0, cpus=8.0,
                 pool=f"p{i}") for i in range(n_pools)],
        clock=store.clock)
    planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                              ElasticParams(enabled=True, resident=True))

    def job(pool, k):
        return Job(uuid=f"el-{pool}-{k}", user="u", pool=pool, priority=50,
                   resources=Resources(mem=100.0 + k, cpus=1.0),
                   command="true")

    queues = {f"p{i}": types.SimpleNamespace(
        jobs=[job(f"p{i}", k) for k in range(queue_len)])
        for i in range(n_pools - 1)}  # last pool idles: a lender

    def cycle():
        mark = byte_mark()
        fam0 = _family_h2d(data_plane.FAM_ELASTIC)
        t0 = time.perf_counter()
        planner.plan_cycle(queues)
        wall_ms = (time.perf_counter() - t0) * 1000
        stamp = byte_stamp(mark)
        stamp["encode_h2d_bytes"] = (
            _family_h2d(data_plane.FAM_ELASTIC) - fam0)
        return wall_ms, stamp

    cold_ms, cold = cycle()
    warm_walls, warm = [], {"h2d_bytes": 0, "d2h_bytes": 0,
                            "encode_h2d_bytes": 0}
    for i in range(3):
        if i == 2:
            # delta plan: ONE pool's queue grows within its j_pad
            # bucket -> exactly one mirror row scatters
            queues["p0"].jobs.append(job("p0", queue_len))
        wall_ms, stamp = cycle()
        warm_walls.append(wall_ms)
        for col in warm:
            warm[col] += stamp[col]
    warm_p50 = float(np.percentile(warm_walls, 50))
    reduction = (1.0 - warm["encode_h2d_bytes"] / 3.0
                 / max(cold["encode_h2d_bytes"], 1))
    log(f"elastic_resident {n_pools} pools x {queue_len} queued: cold "
        f"{cold_ms:.1f} ms / {cold['encode_h2d_bytes']} B; warm p50 "
        f"{warm_p50:.1f} ms / {warm['encode_h2d_bytes']} B over 3 "
        f"plans (per-cycle reduction {reduction:.1%}); last "
        f"delta_rows={planner._resident.last.get('delta_rows')} "
        f"rebuild={planner._resident.last.get('rebuild')}")
    return {
        "elastic_resident": {"p50_ms": warm_p50, "pools": n_pools,
                             "queued": queue_len, "warm_cycles": 3,
                             **warm, "encode_reduction": reduction},
        "elastic_resident_cold": {"p50_ms": cold_ms, "pools": n_pools,
                                  "queued": queue_len, **cold},
    }


def bench_control_plane(*, rps=150.0, duration_s=8.0, seed=13,
                        smoke=False) -> dict:
    """Control-plane write-path phase: sustained submit/query/kill
    traffic (tools/loadtest.py, seeded rest_traffic_trace) against an
    in-process control plane — real store lock, real journal fsyncs,
    real REST stack.  The gated p50 is CLIENT-observed commit-ack
    latency (apply + group fsync), the ROADMAP-item-2 baseline; p99 and
    the achieved rate ride in the record so the sharding work is judged
    against the full distribution.

    Closed loop with ONE worker on purpose: the client shares this
    process (and GIL) with the server, so concurrent open-loop traffic
    measures burst queueing and scheduler jitter, not the write path —
    the serial closed-loop p50 is the commit SERVICE time (REST parse +
    apply under the store lock + group fsync), stable run-over-run
    (<10% spread measured) where loaded percentiles swing 2x.  Real
    at-target-RPS numbers come from `tools/loadtest.py --mode open`
    against a deployed server."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import loadtest

    if smoke:
        rps, duration_s = 80.0, 3.0
    report = loadtest.run_inprocess(rps=rps, duration_s=duration_s,
                                    mode="closed", workers=1, seed=seed,
                                    warmup=25)
    ack = report["commit_ack"]
    log(f"control plane {report['achieved_rps']:.0f} rps achieved "
        f"(target {rps:.0f}): commit-ack p50 {ack['p50_ms']:.2f} ms, "
        f"p99 {ack['p99_ms']:.2f} ms over {ack['count']} submits; "
        f"errors {report['errors']}")
    return {
        "p50_ms": float(ack["p50_ms"] or 0.0),
        "commit_ack_p99_ms": float(ack["p99_ms"] or 0.0),
        "submits": ack["count"],
        "target_rps": rps,
        "achieved_rps": report["achieved_rps"],
        "errors": report["errors"],
    }


def bench_control_plane_sharded(*, rps=300.0, duration_s=8.0, seed=13,
                                smoke=False, shards=4,
                                workers=8) -> dict:
    """Sharded control-plane phase (cook_tpu/shard/): the SAME seeded
    bursty trace as `control_plane`, driven closed-loop at `workers`
    concurrency against a `shards`-way partitioned plane (per-shard
    locks, journal segments, idempotency tables), with traffic spread
    over one pool per shard.

    A concurrency-matched single-shard baseline runs second on the same
    trace, so every record carries the apples-to-apples comparison
    (`single_shard` + `rps_speedup_vs_single`): under concurrent
    commits the single journal's group-fsync barrier serializes, while
    N segments fsync in parallel (os.fsync drops the GIL) — measured
    here as higher achieved RPS at equal-or-lower commit-ack p50
    (~1.04x on this in-process rig, where the GIL caps the win; the
    comparison is RECORDED, not gate-enforced — tools/bench_gate.py
    gates the sharded run's p50 round over round like any phase.  The
    mp phase's fleet-vs-sharded speedup, by contrast, DOES self-gate
    once the recorded core count clears bench_gate.MP_GATE_MIN_CORES)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import loadtest

    if smoke:
        rps, duration_s = 160.0, 3.0
    kw = dict(rps=rps, duration_s=duration_s, mode="closed",
              workers=workers, seed=seed, warmup=25)
    sharded = loadtest.run_inprocess(shards=shards, **kw)
    baseline = loadtest.run_inprocess(shards=1, **kw)
    ack = sharded["commit_ack"]
    base_ack = baseline["commit_ack"]
    speedup = (sharded["achieved_rps"] / baseline["achieved_rps"]
               if baseline["achieved_rps"] else 0.0)
    per_shard = sharded.get("per_shard") or {}
    log(f"control plane sharded ({shards} shards, {workers} workers): "
        f"{sharded['achieved_rps']:.0f} rps, commit-ack p50 "
        f"{ack['p50_ms']:.2f} ms / p99 {ack['p99_ms']:.2f} ms vs "
        f"single-shard {baseline['achieved_rps']:.0f} rps, p50 "
        f"{base_ack['p50_ms']:.2f} ms / p99 {base_ack['p99_ms']:.2f} ms "
        f"({speedup:.2f}x rps); hottest shard "
        f"{per_shard.get('hottest_shard')} at "
        f"{per_shard.get('hottest_commit_p99_ms', 0.0):.1f} ms p99")
    return {
        "p50_ms": float(ack["p50_ms"] or 0.0),
        "commit_ack_p99_ms": float(ack["p99_ms"] or 0.0),
        "submits": ack["count"],
        "shards": shards,
        "workers": workers,
        "target_rps": rps,
        "achieved_rps": sharded["achieved_rps"],
        "errors": sharded["errors"],
        "rps_speedup_vs_single": speedup,
        "per_shard": per_shard.get("shards", {}),
        "hottest_shard": per_shard.get("hottest_shard"),
        "single_shard": {
            "p50_ms": float(base_ack["p50_ms"] or 0.0),
            "commit_ack_p99_ms": float(base_ack["p99_ms"] or 0.0),
            "achieved_rps": baseline["achieved_rps"],
        },
    }


def bench_control_plane_mp(*, rps=300.0, duration_s=8.0, seed=13,
                           smoke=False, groups=4, workers=8,
                           baseline=None) -> dict:
    """Multi-process control-plane phase (cook_tpu/mp/): the SAME
    seeded trace as `control_plane_sharded`, driven closed-loop through
    the shard-aware FRONT END of a fleet of `groups` worker PROCESSES
    (one shard-group each, one traffic pool per group).  Forwarding,
    connection pooling, per-worker breakers, and any cross-group 2PC
    are all inside the measured path.

    `rps_speedup_vs_sharded` compares against the in-process sharded
    phase's achieved RPS on the same trace (pass that phase dict as
    `baseline` to reuse its numbers; otherwise a quick inline baseline
    runs).  The record stamps `cores` = os.cpu_count(): worker
    processes only beat the in-process plane when they actually get
    cores — on a 1-core box the fleet pays forwarding overhead for no
    parallelism and the honest speedup is <= 1x (the >= 2.5x target
    needs >= `groups` cores; docs/observability.md).  The comparison
    SELF-GATES in tools/bench_gate.py when the recorded `cores` >= 4
    (speedup must reach 2.5x); below that core floor it stays recorded,
    not gated.  The gate also tracks this phase's commit-ack p50 round
    over round, skipping pairs recorded on differing core counts."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import loadtest

    if smoke:
        rps, duration_s = 160.0, 3.0
    kw = dict(rps=rps, duration_s=duration_s, mode="closed",
              workers=workers, seed=seed, warmup=25)
    mp_report = loadtest.run_mp(groups=groups, standbys=0, **kw)
    if baseline is None:
        base = loadtest.run_inprocess(shards=groups, **kw)
        baseline = {"achieved_rps": base["achieved_rps"],
                    "p50_ms": float(base["commit_ack"]["p50_ms"] or 0.0),
                    "commit_ack_p99_ms":
                        float(base["commit_ack"]["p99_ms"] or 0.0)}
    ack = mp_report["commit_ack"]
    sharded_rps = baseline.get("achieved_rps", 0.0)
    speedup = (mp_report["achieved_rps"] / sharded_rps
               if sharded_rps else 0.0)
    cores = os.cpu_count() or 1
    mp_stats = mp_report.get("mp", {})
    log(f"control plane mp ({groups} worker processes, {workers} "
        f"clients, {cores} cores): {mp_report['achieved_rps']:.0f} rps "
        f"through the front end, commit-ack p50 {ack['p50_ms']:.2f} ms "
        f"/ p99 {ack['p99_ms']:.2f} ms — {speedup:.2f}x vs the "
        f"in-process sharded plane at {sharded_rps:.0f} rps"
        + ("" if cores >= groups else
           f" (only {cores} core(s): forwarding overhead with no "
           f"process parallelism — expect >= 2.5x at >= {groups} "
           f"cores)"))
    return {
        "p50_ms": float(ack["p50_ms"] or 0.0),
        "commit_ack_p99_ms": float(ack["p99_ms"] or 0.0),
        "submits": ack["count"],
        "groups": groups,
        "workers": workers,
        "cores": cores,
        "target_rps": rps,
        "achieved_rps": mp_report["achieved_rps"],
        "errors": mp_report["errors"],
        "rps_speedup_vs_sharded": speedup,
        "per_worker": mp_stats.get("per_worker", {}),
        "twopc": mp_stats.get("twopc", {}),
        "sharded_baseline": {
            "p50_ms": baseline.get("p50_ms", 0.0),
            "commit_ack_p99_ms": baseline.get("commit_ack_p99_ms", 0.0),
            "achieved_rps": sharded_rps,
        },
    }


def make_elastic_problem(jnp, p, j, p_real=None, seed=6):
    """Padded capacity-plan inputs at any size — ONE construction for
    the full and smoke tiers (ops/elastic.py solve shapes)."""
    from cook_tpu.ops.elastic import ElasticProblem

    rng = np.random.default_rng(seed)
    res = rng.uniform(100, 8000, (p, j, 3)).astype(np.float32)
    res[:, :, 2] = 0.0
    valid = rng.uniform(size=(p, j)) < 0.6
    demand_supply = rng.uniform(0, 500_000, (2, p, 3)).astype(np.float32)
    outstanding = np.zeros((p, p, 3), np.float32)
    live = p if p_real is None else p_real
    outstanding[0, 1 % p] = (5000.0, 8.0, 0.0)
    pool_valid = np.arange(p) < live
    problem = ElasticProblem(
        demand=jnp.asarray(demand_supply[0]),
        supply=jnp.asarray(demand_supply[1]),
        outstanding=jnp.asarray(outstanding),
        pool_valid=jnp.asarray(pool_valid),
    )
    return jnp.asarray(res), jnp.asarray(valid), problem


def bench_elastic(jax, jnp, p=64, j=16384, repeats=5):
    """Elastic capacity-plane planner solve (ops/elastic.py): the
    rank-weighted demand fold + the loan/reclaim assignment, timed as
    one fetch-terminated unit (what Scheduler.elastic_cycle dispatches
    per planning interval).  tools/bench_gate.py guards this phase."""
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.elastic import solve_capacity_plan, weighted_demand

    res, valid, problem = make_elastic_problem(jnp, p, j)

    def solve():
        demand = weighted_demand(res, valid, jnp.float32(64))
        plan = solve_capacity_plan(problem._replace(demand=demand),
                                   jnp.float32(0.1))
        return fetch_result((plan.reclaim, plan.loan))

    solve()
    p50, _ = time_fn(solve, repeats=repeats)
    log(f"elastic plan {p} pools x {j} queued jobs: p50 {p50:.2f} ms")
    return p50


def bench_rebalance(jax, jnp):
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.rebalance import find_preemption_decision

    T, H = 131072, 16384
    t_real, h_real = 100_000, 10_000
    state = make_rebalance_state(jnp, T, H, t_real=t_real, h_real=h_real,
                                 seed=4)
    demand = jnp.asarray([8000.0, 16.0, 0.0], dtype=jnp.float32)

    def solve():
        return fetch_result(find_preemption_decision(state, demand,
                                                     0.3, 1.0, 0.5))

    solve()
    p50, _ = time_fn(solve)
    log(f"rebalance victim search 100k x 10k: tpu p50 {p50:.1f} ms")

    # fast_cycle path: one sort per cycle + cheap per-decision solves
    from cook_tpu.ops.rebalance import decide_from_sorted, sort_rebalance_state

    def sort_once():
        return fetch_result(sort_rebalance_state(
            state.task_host, state.task_dru, state.task_res,
            state.task_eligible))

    sort_once()
    sort_p50, _ = time_fn(sort_once)
    ss = sort_rebalance_state(state.task_host, state.task_dru,
                              state.task_res, state.task_eligible)
    row_ok = state.task_eligible[ss.perm]
    dru_sorted = state.task_dru[ss.perm]

    def decide():
        return fetch_result(decide_from_sorted(ss, row_ok, dru_sorted,
                                               state.spare, state.host_ok,
                                               demand, 0.3, 1.0, 0.5))

    decide()
    dec_p50, _ = time_fn(decide)
    log(f"rebalance fast_cycle: sort {sort_p50:.1f} ms once + "
        f"{dec_p50:.1f} ms/decision "
        f"(100-decision cycle ~{sort_p50 + 100 * dec_p50:.0f} ms vs "
        f"{100 * p50:.0f} ms exact)")
    return p50


def _probe_device() -> str:
    """Probe accelerator init in a subprocess: a wedged device tunnel hangs
    the client inside PJRT, which no in-process timeout can interrupt.

    Returns "ok" (device answered), "wedged" (probe timed out — the
    transient tunnel failure mode, worth retrying), or "error" (the probe
    failed FAST — a plugin import/init error, which retrying won't fix).
    """
    import subprocess

    t0 = time.monotonic()
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120")),
            check=True, capture_output=True,
        )
        return "ok"
    except subprocess.TimeoutExpired:
        return "wedged"
    except Exception:
        # a fast non-zero exit is a persistent init error, not a wedge;
        # anything that took >30 s to die is treated as a wedge anyway
        return "wedged" if time.monotonic() - t0 > 30 else "error"


def _result_line(match_p50, cpu_ms, eff, j_real, n_real, platform,
                 extra="", note=""):
    return {
        "metric": f"match-cycle p50 latency, {j_real} jobs x {n_real} nodes "
                  f"(packing_eff={eff:.4f}{extra}, platform={platform})"
                  + note,
        "value": round(match_p50, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / match_p50, 2),
    }


# ------------------------------------------------- structured bench records


def resolved_backend() -> str:
    """The JAX backend this process's solves actually ran on — stamped
    into every record AND every phase so bench_gate can refuse to diff a
    silent CPU-fallback round against a real-accelerator round (the
    first five BENCH rounds were exactly that, undetected)."""
    import jax

    return jax.default_backend()


def make_record(mode: str, platform: str, phases: dict,
                headline=None, backend: str = None) -> dict:
    """One structured bench record (schema cook-bench/v1): per-phase p50s
    keyed by solve name, plus the headline line the driver scrapes.
    `tools/bench_gate.py` diffs consecutive records phase by phase —
    refusing pairs whose resolved JAX backend differs.  `backend` is
    stamped on the record and (unless a phase already carries its own)
    on every phase; default: the live `resolved_backend()`."""
    if backend is None:
        backend = resolved_backend()
    phases = {
        name: ({**info, "backend": info.get("backend", backend)}
               if isinstance(info, dict) else info)
        for name, info in phases.items()
    }
    return {
        "schema": BENCH_SCHEMA,
        "mode": mode,                 # "full" | "smoke"
        "platform": platform,         # "tpu" | "cpu" | ...
        "backend": backend,           # resolved JAX backend of the run
        "wall_time": time.time(),
        "phases": phases,             # name -> {"p50_ms": ..., "backend": ...}
        "headline": headline,
    }


def _next_phase_record_path(root: str) -> str:
    """Next free BENCH_r{NN}_phases.json: one higher than every existing
    BENCH_r<number>* round artifact (the driver's records included), so
    bench.py's structured records interleave with — and never clobber —
    the driver's round files."""
    idx = 0
    for path in glob.glob(os.path.join(root, "BENCH_r*")):
        m = re.match(r"BENCH_r(\d+)", os.path.basename(path))
        if m:
            idx = max(idx, int(m.group(1)))
    return os.path.join(root, f"BENCH_r{idx + 1:02d}_phases.json")


def write_bench_record(record: dict, out: str = None,
                       root: str = None) -> str:
    """Write the structured record; destination precedence: explicit
    `out` / $BENCH_OUT / the default family (BENCH_rsmoke.json for smoke
    — a fixed name, so repeated smoke runs don't litter the repo root —
    else the next free BENCH_r{NN}_phases.json).  The previous smoke
    record rotates to BENCH_rsmoke_prev.json so `bench.py --smoke;
    tools/bench_gate.py` always has a pair to diff — without the
    rotation the overwrite would erase the baseline the gate needs."""
    root = root or os.path.dirname(os.path.abspath(__file__))
    out = out or os.environ.get("BENCH_OUT")
    if out is None:
        if record["mode"] == "smoke":
            out = os.path.join(root, "BENCH_rsmoke.json")
            if os.path.exists(out):
                os.replace(out, os.path.join(root,
                                             "BENCH_rsmoke_prev.json"))
        else:
            out = _next_phase_record_path(root)
    with open(out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    log(f"bench record -> {out}")
    return out


def _record_out_arg() -> str:
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


def device_main():
    """Full device bench; assumes the accelerator is reachable (probed by
    the caller).  Prints the one JSON line on stdout."""
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    log(f"device: {jax.devices()[0]} ({platform})")
    match_p50, cpu_ms, eff, (j_real, n_real), match_bytes = bench_match(
        jax, jnp, platform)
    xl_phases = bench_match_xl(jax, jnp, platform)
    xxl_phases = bench_match_xxl(jax, jnp, platform)
    dru_p50 = bench_dru(jax, jnp)
    reb_p50 = bench_rebalance(jax, jnp)
    multi_p50 = bench_multipool(jax, jnp, load_tuned())
    elastic_p50 = bench_elastic(jax, jnp)
    resident_phases = bench_match_resident()
    control_plane = bench_control_plane()
    control_plane_sharded = bench_control_plane_sharded()
    control_plane_mp = bench_control_plane_mp(
        baseline=control_plane_sharded)
    pipeline_phases = bench_pipeline(jax, jnp, n_pools=8, hosts_per_pool=96,
                                     jobs_per_pool=1536)
    speculation_phases = bench_speculation()
    gang_phases = bench_gang()
    log(f"full-cycle estimate (rank+match+rebalance): "
        f"{dru_p50 + match_p50 + reb_p50:.1f} ms")
    extra = f", dru_ms={dru_p50:.1f}, rebalance_ms={reb_p50:.1f}"
    headline = _result_line(match_p50, cpu_ms, eff, j_real, n_real,
                            platform, extra=extra)
    write_bench_record(make_record("full", platform, {
        "match": {"p50_ms": match_p50, "jobs": j_real, "nodes": n_real,
                  "packing_eff": eff, "baseline_ms": cpu_ms,
                  **match_bytes},
        **xl_phases,
        **xxl_phases,
        "dru": {"p50_ms": dru_p50},
        "rebalance": {"p50_ms": reb_p50},
        "multipool": {"p50_ms": multi_p50},
        "elastic_plan": {"p50_ms": elastic_p50, "pools": 64, "jobs": 16384},
        **resident_phases,
        **bench_rebalance_resident(),
        **bench_elastic_resident(),
        "control_plane": control_plane,
        "control_plane_sharded": control_plane_sharded,
        "control_plane_mp": control_plane_mp,
        **pipeline_phases,
        **speculation_phases,
        **gang_phases,
    }, headline), out=_record_out_arg())
    print(json.dumps(headline), flush=True)


def cpu_main():
    """CPU-XLA fallback bench at reduced size.  Prints the JSON line."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    log(f"device: {jax.devices()[0]} (cpu fallback)")
    match_p50, cpu_ms, eff, (j_real, n_real), match_bytes = bench_match(
        jax, jnp, "cpu")
    # the accelerator was unreachable; this measures CPU XLA vs the C++
    # baseline at reduced size — see docs/status.md for the real-TPU
    # numbers measured interactively (552 ms for 100k x 10k vs 5.3-6.3 s
    # C++, tpu_sweep_r2.jsonl)
    note = " [CPU FALLBACK — accelerator unreachable; see docs/status.md]"
    headline = _result_line(match_p50, cpu_ms, eff, j_real, n_real,
                            "cpu", note=note)
    # match_xl runs at FULL 100k x 10k even on the CPU fallback: the
    # hierarchical decomposition is precisely what makes the XL pool
    # tractable without an accelerator (the flat solve is not)
    xl_phases = bench_match_xl(jax, jnp, "cpu")
    # match_xxl (1M x 100k) runs at FULL scale on the CPU fallback too:
    # the superblock decomposition is what makes the mega-pool
    # tractable at all, and the phase rows carry honest backend=cpu +
    # cores stamps
    xxl_phases = bench_match_xxl(jax, jnp, "cpu")
    write_bench_record(make_record("full", "cpu", {
        "match": {"p50_ms": match_p50, "jobs": j_real, "nodes": n_real,
                  "packing_eff": eff, "baseline_ms": cpu_ms,
                  **match_bytes},
        **xl_phases,
        **xxl_phases,
        # device residency moves the same logical bytes on any backend
        **bench_match_resident(),
        **bench_rebalance_resident(),
        **bench_elastic_resident(),
        # the control plane never needed the accelerator; its phases are
        # measured at full scale even on the CPU fallback
        "control_plane": bench_control_plane(),
        "control_plane_sharded": bench_control_plane_sharded(),
        "control_plane_mp": bench_control_plane_mp(),
        # the speculation A/B runs through the trace simulator on
        # whatever backend is live — full scale here too
        **bench_speculation(),
        # gang admission latency is virtual-time: backend-independent
        **bench_gang(),
    }, headline), out=_record_out_arg())
    print(json.dumps(headline), flush=True)


def bench_smoke(jax, jnp, repeats: int = 3) -> dict:
    """Smoke tier: the same three solves at tiny padded sizes, warm p50s
    after one compile run each.  Seconds, not minutes — fast enough for
    the tier-1 suite (tests/test_bench_smoke.py), while still exercising
    the real kernels, the fetch-to-observe-completion timing, and the
    packing-parity check end to end."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.common import fetch_result
    from cook_tpu.ops.dru import dru_rank
    from cook_tpu.ops.match import MatchProblem, backend_flags, chunked_match
    from cook_tpu.ops.rebalance import find_preemption_decision

    phases = {}
    # match: 1k x 128 padded, chunked xla backend
    J, N = 1024, 128
    j_real, n_real = 1000, 120
    demands, avail, totals = make_problem(J, N, seed=7)
    job_valid = np.zeros(J, bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, bool)
    node_valid[:n_real] = True
    mark = byte_mark()
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid), feasible=None,
    )
    note_problem_bytes(problem)

    def solve_match():
        # kc=32/rounds=3/passes=3: full parity (eff 1.0) with the CPU
        # greedy at this saturated tiny shape; narrower candidate lists
        # drop ~27% of placements and would read as a broken matcher
        return fetch_result(chunked_match(
            problem, chunk=256, rounds=3, kc=32, passes=3,
            **backend_flags("xla")).assignment)

    assignment = solve_match()
    match_bytes = byte_stamp(mark)  # problem build + one solve's fetch
    p50, _ = time_fn(solve_match, repeats=repeats)
    cpu_assign = ref.np_greedy_match(demands[:j_real], avail[:n_real],
                                     totals[:n_real])
    q_dev = ref.packing_quality(demands[:j_real], assignment[:j_real])
    q_cpu = ref.packing_quality(demands[:j_real], cpu_assign)
    eff = (q_dev["cpus_placed"] / q_cpu["cpus_placed"]
           if q_cpu["cpus_placed"] else 1.0)
    phases["match"] = {"p50_ms": p50, "jobs": j_real, "nodes": n_real,
                       "packing_eff": eff, **match_bytes}
    log(f"smoke match {j_real} x {n_real}: p50 {p50:.2f} ms, eff {eff:.4f}")

    # dru rank: 2k tasks x 8 users (same construction as the full tier)
    T, U = 2048, 8
    mark = byte_mark()
    tasks, div, _ = make_dru_problem(jnp, T, U, seed=8)
    note_problem_bytes((tasks, div), family=_data_plane().FAM_DRU)

    def solve_dru():
        return fetch_result(dru_rank(tasks, div, div, div).rank)

    solve_dru()
    dru_bytes = byte_stamp(mark)
    dru_p50, _ = time_fn(solve_dru, repeats=repeats)
    phases["dru"] = {"p50_ms": dru_p50, "tasks": T, **dru_bytes}
    log(f"smoke dru {T} tasks: p50 {dru_p50:.2f} ms")

    # rebalance victim search: 2k tasks x 256 hosts (shared construction)
    T2, H = 2048, 256
    mark = byte_mark()
    state = make_rebalance_state(jnp, T2, H, seed=9)
    demand = jnp.asarray([8000.0, 16.0, 0.0], dtype=jnp.float32)
    note_problem_bytes((state, demand))

    def solve_reb():
        return fetch_result(
            find_preemption_decision(state, demand, 0.3, 1.0, 0.5))

    solve_reb()
    reb_bytes = byte_stamp(mark)
    reb_p50, _ = time_fn(solve_reb, repeats=repeats)
    phases["rebalance"] = {"p50_ms": reb_p50, "tasks": T2, "hosts": H,
                           **reb_bytes}
    log(f"smoke rebalance {T2} x {H}: p50 {reb_p50:.2f} ms")

    # elastic capacity plan: 8 pools x 256 queued jobs (shared construction)
    elastic_p50 = bench_elastic(jax, jnp, p=8, j=256, repeats=repeats)
    phases["elastic_plan"] = {"p50_ms": elastic_p50, "pools": 8, "jobs": 256}

    # hierarchical two-level matcher, tiny tier (2k jobs x 256 nodes):
    # same coarse/scatter/fine/refine pipeline as the 100k x 10k full
    # tier, so the gate tracks the XL trajectory every CI run
    phases.update(bench_match_xl(jax, jnp, jax.devices()[0].platform,
                                 smoke=True, repeats=repeats))

    # superblock mega-matcher, tiny tier (8k jobs x 1k nodes, 4
    # superblocks x 4 blocks): the two-level super-coarse/coarse path
    # plus per-level walls, gate-tracked toward the 1M x 100k full tier
    phases.update(bench_match_xxl(jax, jnp, jax.devices()[0].platform,
                                  smoke=True, repeats=repeats))

    # device-resident match state: cold rebuild + 3 warm delta cycles
    # (warm p50 AND warm h2d_bytes are gate-visible; bytes growth on
    # warm cycles is a regression)
    phases.update(bench_match_resident(smoke=True))

    # keyed-row resident mirrors: rebalancer victim tensors + elastic
    # demand/capacity tensors (warm encode bytes gated like
    # match_resident's)
    phases.update(bench_rebalance_resident(smoke=True))
    phases.update(bench_elastic_resident(smoke=True))

    # control plane: the smoke loadtest against an in-process server —
    # commit-ack latency under sustained submit/query/kill traffic
    phases["control_plane"] = bench_control_plane(smoke=True)

    # sharded control plane (cook_tpu/shard/): same trace, 4 shards vs a
    # concurrency-matched single-shard baseline — the partitioning win
    # (parallel journal-segment fsyncs) is gate-tracked every CI run
    phases["control_plane_sharded"] = bench_control_plane_sharded(
        smoke=True)

    # multi-process fleet (cook_tpu/mp/): same trace through the
    # shard-aware front end over worker processes; speedup vs the
    # in-process sharded phase above is recorded with a `cores` stamp
    # (a 1-core box honestly records <= 1x)
    phases["control_plane_mp"] = bench_control_plane_mp(
        smoke=True, baseline=phases["control_plane_sharded"])

    # prediction-assisted speculative cycles: the completion-heavy A/B
    # (hit fraction + cycle-start-to-first-launch p50), tiny tier
    phases.update(bench_speculation(smoke=True))

    # topology-aware gang scheduling: admission latency (virtual ms,
    # deterministic) + placed fraction on the seeded gang/topology trace
    phases.update(bench_gang(smoke=True))
    return phases


def smoke_main(out: str = None, pipeline: bool = None) -> dict:
    """`python bench.py --smoke`: run the smoke tier, write the
    structured record, print the headline JSON line.  Returns the
    record (tests call this in-process).  The pipelined-vs-serial
    match-cycle tier (phases `pipeline` + `pipeline_serial`) is included
    BY DEFAULT so every smoke record carries the same phase set and
    bench_gate's dropped-phase rule never misreads a flag mismatch as a
    regression; `--no-pipeline` (or BENCH_NO_PIPELINE) skips it for
    quick kernel-only iterations — but a gate run against a
    pipeline-bearing baseline will then fail on the missing phases, by
    design."""
    import jax
    import jax.numpy as jnp

    if pipeline is None:
        pipeline = ("--no-pipeline" not in sys.argv
                    and not os.environ.get("BENCH_NO_PIPELINE"))
    platform = jax.devices()[0].platform
    log(f"smoke bench on {jax.devices()[0]} ({platform})")
    phases = bench_smoke(jax, jnp)
    if pipeline:
        phases.update(bench_pipeline(jax, jnp))
    match = phases["match"]
    headline = {
        "metric": (f"smoke match-cycle p50 latency, {match['jobs']} jobs x "
                   f"{match['nodes']} nodes (packing_eff="
                   f"{match['packing_eff']:.4f}, platform={platform})"),
        "value": round(match["p50_ms"], 2),
        "unit": "ms",
    }
    record = make_record("smoke", platform, phases, headline)
    write_bench_record(record, out=out if out is not None
                       else _record_out_arg())
    print(json.dumps(headline), flush=True)
    return record


def _try_device_upgrade(budget_s: float) -> bool:
    """Run the device bench in a subprocess (this process already
    initialized jax on CPU) and relay its JSON line.  Returns success."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--device-only"],
            timeout=budget_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        log("device bench subprocess timed out; keeping the CPU number")
        return False
    for ln in (proc.stderr or "").splitlines():
        log(f"[device bench] {ln}")
    lines = [ln for ln in (proc.stdout or "").splitlines() if ln.strip()]
    if proc.returncode == 0 and lines:
        try:
            parsed = json.loads(lines[-1])
        except ValueError:
            log("device bench printed unparseable output; keeping CPU line")
            return False
        # re-print: the driver takes the last JSON line, upgrading the
        # artifact from the CPU fallback to the real device measurement
        print(json.dumps(parsed), flush=True)
        return True
    log(f"device bench subprocess rc={proc.returncode}; keeping CPU number")
    return False


def main():
    """CPU-first, device-upgrade bench driver.

    The round artifact must NEVER be empty (round 3 lost its number to an
    1800 s probe-retry window outliving the driver's timeout).  Order:
      1. one fast probe; device up -> full device bench, done;
      2. otherwise run the CPU fallback and PRINT its line immediately;
      3. spend the remaining (bounded) window re-probing, and on recovery
         run the device bench in a subprocess, re-printing on success —
         the last JSON line on stdout wins.
    """
    if "--smoke" in sys.argv or os.environ.get("BENCH_SMOKE"):
        smoke_main()
        return
    if "--pipeline" in sys.argv:
        # standalone pipeline tier (quick iteration on the engine); a
        # record is written only to an explicit --out/$BENCH_OUT, under
        # its own mode so it never shadows the smoke/full families
        import jax
        import jax.numpy as jnp

        phases = bench_pipeline(jax, jnp)
        headline = {
            "metric": ("pipelined match-cycle wall, "
                       f"{phases['pipeline']['pools']} pools "
                       f"(overlap={phases['pipeline']['overlap_fraction']:.2f}, "
                       f"parity={phases['pipeline']['parity']})"),
            "value": round(phases["pipeline"]["p50_ms"], 2),
            "unit": "ms",
        }
        out = _record_out_arg() or os.environ.get("BENCH_OUT")
        if out:
            write_bench_record(
                make_record("pipeline", jax.devices()[0].platform, phases,
                            headline), out=out)
        print(json.dumps(headline), flush=True)
        return
    if "--device-only" in sys.argv:
        device_main()
        return
    if "--cpu-only" in sys.argv or os.environ.get("BENCH_FORCE_CPU"):
        cpu_main()
        return

    probe = _probe_device()
    if probe == "ok":
        device_main()
        return

    log(f"accelerator probe: {probe}; printing CPU fallback first")
    cpu_main()
    if probe == "error":
        log("probe failed fast (persistent init error, not a tunnel "
            "wedge) — skipping the retry window")
        return
    if os.environ.get("CI") or os.environ.get("BENCH_SMOKE"):
        # CI-adjacent runs must not burn the full re-probe window on a
        # machine that will never grow an accelerator (BENCH_r05 lost
        # 600 s to exactly this); the CPU line already printed stands
        log("CI run: skipping the device-upgrade re-probe window")
        return
    window = float(os.environ.get("BENCH_PROBE_WINDOW_S", "600"))
    interval = float(os.environ.get("BENCH_PROBE_INTERVAL_S", "120"))
    deadline = time.monotonic() + window
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            log("upgrade window expired; the CPU fallback line stands")
            return
        log(f"re-probing for a device upgrade ({remaining:.0f} s left)")
        if _probe_device() == "ok":
            budget = max(deadline - time.monotonic(), 300.0)
            if _try_device_upgrade(budget):
                return
        time.sleep(min(interval, max(deadline - time.monotonic(), 1)))


if __name__ == "__main__":
    main()
