"""Benchmark: scheduling-cycle latency at the BASELINE.md north-star scale.

Measures the TPU match solve (the Fenzo replacement) on the headline config
— 100k pending jobs x 10k nodes, one cycle — against the reference-faithful
CPU greedy baseline (same decisions, numpy-vectorized inner loop), plus
packing-efficiency parity on a smaller exactly-comparable config.

Prints ONE JSON line:
  {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": speedup}
All supporting detail goes to stderr.
"""
import json
import sys
import time

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def make_problem(j, n, seed=0):
    rng = np.random.default_rng(seed)
    demands = np.stack(
        [
            rng.choice([512, 1024, 2048, 4096, 8192], j).astype(np.float32),
            rng.choice([0.5, 1, 2, 4], j).astype(np.float32),
            np.zeros(j, dtype=np.float32),
        ],
        axis=-1,
    )
    totals = np.stack(
        [np.full(n, 65536.0, dtype=np.float32),
         np.full(n, 32.0, dtype=np.float32)],
        axis=-1,
    )
    frac = rng.uniform(0.2, 1.0, (n, 1)).astype(np.float32)
    avail = np.concatenate([totals * frac, np.zeros((n, 1), np.float32)],
                           axis=-1)
    return demands, avail, totals


def main():
    import jax
    import jax.numpy as jnp

    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.match import MatchProblem, chunked_match

    platform = jax.devices()[0].platform
    log(f"device: {jax.devices()[0]} ({platform})")

    # ---- parity check on an exactly-comparable config (1k x 1k) ----
    d_s, a_s, t_s = make_problem(1024, 1024, seed=1)
    small = MatchProblem(
        demands=jnp.asarray(d_s),
        job_valid=jnp.ones(1024, dtype=bool),
        avail=jnp.asarray(a_s),
        totals=jnp.asarray(t_s),
        node_valid=jnp.ones(1024, dtype=bool),
        feasible=None,
    )
    t0 = time.perf_counter()
    cpu_small = ref.np_greedy_match(d_s, a_s, t_s)
    cpu_small_ms = (time.perf_counter() - t0) * 1000
    tpu_small = np.asarray(chunked_match(small, chunk=256, rounds=6, kc=128).assignment)
    q_cpu = ref.packing_quality(d_s, cpu_small)
    q_tpu = ref.packing_quality(d_s, tpu_small)
    packing_eff = (q_tpu["cpus_placed"] / q_cpu["cpus_placed"]
                   if q_cpu["cpus_placed"] else 1.0)
    log(f"parity 1k x 1k: cpu placed {q_cpu['num_placed']}, "
        f"tpu placed {q_tpu['num_placed']}, packing efficiency "
        f"{packing_eff:.4f} (target >= 0.99); cpu greedy {cpu_small_ms:.1f} ms")

    # ---- headline config: 100k x 10k ----
    J, N = 131072, 16384  # padded buckets over 100k x 10k
    j_real, n_real = 100_000, 10_000
    demands, avail, totals = make_problem(J, N, seed=2)
    job_valid = np.zeros(J, dtype=bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n_real] = True
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid),
        feasible=None,
    )
    solve = lambda: chunked_match(problem, chunk=1024, rounds=6, kc=128)
    t0 = time.perf_counter()
    result = solve()
    result.assignment.block_until_ready()
    compile_ms = (time.perf_counter() - t0) * 1000
    log(f"headline compile+first run: {compile_ms:.0f} ms")

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        result = solve()
        result.assignment.block_until_ready()
        times.append((time.perf_counter() - t0) * 1000)
    p50 = float(np.percentile(times, 50))
    placed = int(np.asarray(jnp.sum(result.assignment >= 0)))
    log(f"headline 100k x 10k: p50 {p50:.1f} ms over {len(times)} runs "
        f"(all: {[f'{t:.0f}' for t in times]}), placed {placed}")

    # ---- CPU baseline on the same headline config ----
    t0 = time.perf_counter()
    cpu_big = ref.np_greedy_match(
        demands[:j_real], avail[:n_real], totals[:n_real]
    )
    cpu_big_ms = (time.perf_counter() - t0) * 1000
    q_cpu_big = ref.packing_quality(demands[:j_real], cpu_big)
    tpu_big = np.asarray(result.assignment[:j_real])
    q_tpu_big = ref.packing_quality(demands[:j_real], tpu_big)
    big_eff = (q_tpu_big["cpus_placed"] / q_cpu_big["cpus_placed"]
               if q_cpu_big["cpus_placed"] else 1.0)
    log(f"cpu baseline 100k x 10k: {cpu_big_ms:.0f} ms, "
        f"placed {q_cpu_big['num_placed']}; tpu placed "
        f"{q_tpu_big['num_placed']}; packing efficiency {big_eff:.4f}")

    print(json.dumps({
        "metric": "match-cycle p50 latency, 100k jobs x 10k nodes "
                  f"(packing_eff={big_eff:.4f}, platform={platform})",
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_big_ms / p50, 2),
    }))


if __name__ == "__main__":
    main()
