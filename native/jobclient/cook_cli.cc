// Command-line front end for the C++ jobclient — the smoke-test binary
// the integration tests drive against a live scheduler (the role of the
// Java client's examples/tests).
//
//   cook_cli --url http://host:port --user alice submit "echo hi" [mem cpus]
//   cook_cli --url ... wait <uuid> [timeout_ms]
//   cook_cli --url ... show <uuid>
//   cook_cli --url ... kill <uuid>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "cook_client.hpp"

int main(int argc, char** argv) {
  std::string url = "http://127.0.0.1:12321";
  std::string user = "anonymous";
  int i = 1;
  while (i + 1 < argc && argv[i][0] == '-') {
    if (!strcmp(argv[i], "--url")) url = argv[++i];
    else if (!strcmp(argv[i], "--user")) user = argv[++i];
    else break;
    ++i;
  }
  if (i >= argc) {
    fprintf(stderr, "usage: cook_cli [--url U] [--user u] "
                    "submit|wait|show|kill ...\n");
    return 2;
  }
  std::string cmd = argv[i++];
  if (cmd == "--help" || cmd == "-h" || cmd == "help") {
    fprintf(stderr, "usage: cook_cli [--url U] [--user u] <command>\n"
                    "  submit <cmd> [mem] [cpus]   print the job uuid\n"
                    "  wait <uuid> [timeout_ms]    poll until terminal\n"
                    "  show <uuid>                 job + instance status\n"
                    "  kill <uuid>\n");
    return 0;
  }
  cook::JobClient client = cook::JobClient::Builder()
                               .url(url)
                               .user(user)
                               .poll_interval_ms(200)
                               .build();
  try {
    if (cmd == "submit") {
      if (i >= argc) { fprintf(stderr, "submit needs a command\n"); return 2; }
      cook::JobSpec spec;
      spec.command = argv[i++];
      if (i < argc) spec.mem = std::stod(argv[i++]);
      if (i < argc) spec.cpus = std::stod(argv[i++]);
      auto uuids = client.submit({spec});
      printf("%s\n", uuids[0].c_str());
    } else if (cmd == "wait") {
      if (i >= argc) { fprintf(stderr, "wait needs a uuid\n"); return 2; }
      std::string uuid = argv[i++];
      int timeout_ms = i < argc ? std::stoi(argv[i++]) : 60000;
      client.set_listener([](const cook::JobStatus& status) {
        fprintf(stderr, "status: %s\n", status.status.c_str());
      });
      cook::JobStatus status = client.wait(uuid, timeout_ms);
      printf("%s\n", status.status.c_str());
      return status.completed() && status.succeeded() ? 0 : 1;
    } else if (cmd == "show") {
      if (i >= argc) { fprintf(stderr, "show needs a uuid\n"); return 2; }
      cook::JobStatus status = client.query(argv[i]);
      printf("%s %s\n", status.uuid.c_str(), status.status.c_str());
      for (const auto& inst : status.instances) {
        printf("  %s %s host=%s\n", inst.task_id.c_str(),
               inst.status.c_str(), inst.hostname.c_str());
      }
    } else if (cmd == "kill") {
      if (i >= argc) { fprintf(stderr, "kill needs a uuid\n"); return 2; }
      client.kill(argv[i]);
      printf("killed\n");
    } else {
      fprintf(stderr, "unknown command %s\n", cmd.c_str());
      return 2;
    }
  } catch (const cook::JobClientError& e) {
    fprintf(stderr, "error (%d): %s\n", e.status, e.what());
    return 1;
  } catch (const std::exception& e) {
    // e.g. the JSON parser on a non-JSON body from a proxy
    fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
  return 0;
}
