// Implementation of the cook C++ jobclient (see cook_client.hpp).
// Reference parity: jobclient/java/.../JobClient.java — submit/query/kill/
// retry/listener-polling over the REST API (rest/api.clj routes).
#include "cook_client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <sstream>
#include <thread>

namespace cook {
namespace {

struct ParsedUrl {
  std::string host;
  int port = 80;
  std::string path_prefix;
};

ParsedUrl parse_url(const std::string& url) {
  ParsedUrl out;
  std::string rest = url;
  const std::string scheme = "http://";
  if (rest.rfind(scheme, 0) == 0) rest = rest.substr(scheme.size());
  auto slash = rest.find('/');
  std::string hostport = rest.substr(0, slash);
  if (slash != std::string::npos) {
    out.path_prefix = rest.substr(slash);
    while (!out.path_prefix.empty() && out.path_prefix.back() == '/') {
      out.path_prefix.pop_back();
    }
  }
  auto colon = hostport.rfind(':');
  if (colon != std::string::npos) {
    out.host = hostport.substr(0, colon);
    out.port = std::stoi(hostport.substr(colon + 1));
  } else {
    out.host = hostport;
  }
  return out;
}

class Socket {
 public:
  Socket(const std::string& host, int port, int timeout_ms) {
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* result = nullptr;
    if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &result) != 0) {
      throw JobClientError(0, "cannot resolve " + host);
    }
    for (addrinfo* ai = result; ai; ai = ai->ai_next) {
      fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
      setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
      setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
      if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) break;
      close(fd_);
      fd_ = -1;
    }
    freeaddrinfo(result);
    if (fd_ < 0) {
      throw JobClientError(0, "cannot connect to " + host + ":" +
                                  std::to_string(port));
    }
  }
  ~Socket() {
    if (fd_ >= 0) close(fd_);
  }
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;

  void send_all(const std::string& data) const {
    size_t sent = 0;
    while (sent < data.size()) {
      ssize_t n = ::send(fd_, data.data() + sent, data.size() - sent, 0);
      if (n <= 0) throw JobClientError(0, "send failed");
      sent += static_cast<size_t>(n);
    }
  }

  std::string recv_all() const {
    std::string out;
    char buf[16384];
    while (true) {
      ssize_t n = ::recv(fd_, buf, sizeof buf, 0);
      if (n < 0) throw JobClientError(0, "recv failed/timed out");
      if (n == 0) break;
      out.append(buf, static_cast<size_t>(n));
      // stop early once content-length is satisfied
      auto header_end = out.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        auto cl = out.find("Content-Length: ");
        if (cl != std::string::npos && cl < header_end) {
          size_t len = std::stoul(out.substr(cl + 16));
          if (out.size() >= header_end + 4 + len) break;
        }
      }
    }
    return out;
  }

 private:
  int fd_ = -1;
};

std::string base64(const std::string& input) {
  static const char* alphabet =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  size_t i = 0;
  while (i + 2 < input.size()) {
    uint32_t n = (static_cast<uint8_t>(input[i]) << 16) |
                 (static_cast<uint8_t>(input[i + 1]) << 8) |
                 static_cast<uint8_t>(input[i + 2]);
    out += alphabet[(n >> 18) & 63];
    out += alphabet[(n >> 12) & 63];
    out += alphabet[(n >> 6) & 63];
    out += alphabet[n & 63];
    i += 3;
  }
  if (i + 1 == input.size()) {
    uint32_t n = static_cast<uint8_t>(input[i]) << 16;
    out += alphabet[(n >> 18) & 63];
    out += alphabet[(n >> 12) & 63];
    out += "==";
  } else if (i + 2 == input.size()) {
    uint32_t n = (static_cast<uint8_t>(input[i]) << 16) |
                 (static_cast<uint8_t>(input[i + 1]) << 8);
    out += alphabet[(n >> 18) & 63];
    out += alphabet[(n >> 12) & 63];
    out += alphabet[(n >> 6) & 63];
    out += '=';
  }
  return out;
}

}  // namespace

JobClient JobClient::Builder::build() const { return JobClient(*this); }

HttpResponse JobClient::request(const std::string& method,
                                const std::string& path,
                                const std::string& body) const {
  ParsedUrl url = parse_url(cfg_.url_);
  Socket sock(url.host, url.port, cfg_.timeout_ms_);
  std::ostringstream req;
  req << method << ' ' << url.path_prefix << path << " HTTP/1.1\r\n"
      << "Host: " << url.host << "\r\n"
      << "Connection: close\r\n"
      << "Accept: application/json\r\n"
      << "Authorization: Basic " << base64(cfg_.user_ + ":") << "\r\n"
      << "X-Cook-Requesting-User: " << cfg_.user_ << "\r\n";
  if (!cfg_.impersonate_.empty()) {
    req << "X-Cook-Impersonate: " << cfg_.impersonate_ << "\r\n";
  }
  if (!body.empty()) {
    req << "Content-Type: application/json\r\n"
        << "Content-Length: " << body.size() << "\r\n";
  }
  req << "\r\n" << body;
  sock.send_all(req.str());
  std::string raw = sock.recv_all();

  HttpResponse resp;
  auto line_end = raw.find("\r\n");
  if (line_end == std::string::npos || raw.size() < 12) {
    throw JobClientError(0, "malformed HTTP response");
  }
  resp.status = std::stoi(raw.substr(9, 3));
  auto header_end = raw.find("\r\n\r\n");
  if (header_end != std::string::npos) {
    resp.body = raw.substr(header_end + 4);
  }
  return resp;
}

std::vector<std::string> JobClient::submit(const std::vector<JobSpec>& jobs) {
  json::Array arr;
  for (const auto& job : jobs) {
    json::Object spec;
    if (!job.uuid.empty()) spec["uuid"] = job.uuid;
    spec["name"] = job.name;
    spec["command"] = job.command;
    spec["mem"] = job.mem;
    spec["cpus"] = job.cpus;
    if (job.gpus > 0) spec["gpus"] = job.gpus;
    if (job.disk > 0) spec["disk"] = job.disk;
    if (job.ports > 0) spec["ports"] = job.ports;
    spec["max_retries"] = job.max_retries;
    spec["priority"] = job.priority;
    if (!job.pool.empty()) spec["pool"] = job.pool;
    if (!job.group_uuid.empty()) spec["group"] = job.group_uuid;
    if (!job.env.empty()) {
      json::Object env;
      for (const auto& [key, value] : job.env) env[key] = value;
      spec["env"] = std::move(env);
    }
    if (!job.labels.empty()) {
      json::Object labels;
      for (const auto& [key, value] : job.labels) labels[key] = value;
      spec["labels"] = std::move(labels);
    }
    arr.push_back(json::Value(std::move(spec)));
  }
  json::Object body;
  body["jobs"] = std::move(arr);
  HttpResponse resp = request("POST", "/jobs", json::Value(body).dump());
  if (resp.status != 201) {
    throw JobClientError(resp.status, "submit failed: " + resp.body);
  }
  std::vector<std::string> uuids;
  json::Value parsed = json::parse(resp.body);
  for (const auto& v : parsed.get("jobs").as_array()) {
    uuids.push_back(v.as_string());
  }
  return uuids;
}

JobStatus JobClient::parse_job(const json::Value& v) {
  JobStatus status;
  status.uuid = v.get_string("uuid");
  status.status = v.get_string("status");
  const json::Value& instances = v.get("instances");
  if (instances.type() == json::Value::Type::Arr) {
    for (const auto& item : instances.as_array()) {
      if (item.type() != json::Value::Type::Obj) continue;  // bare ids
      InstanceStatus inst;
      inst.task_id = item.get_string("task_id");
      inst.status = item.get_string("status");
      inst.hostname = item.get_string("hostname");
      inst.reason = item.get_string("reason_string");
      const json::Value& exit_code = item.get("exit_code");
      if (!exit_code.is_null()) {
        inst.exit_code = static_cast<int>(exit_code.as_number());
      }
      status.instances.push_back(std::move(inst));
    }
  }
  return status;
}

JobStatus JobClient::query(const std::string& uuid) {
  HttpResponse resp = request("GET", "/jobs/" + uuid);
  if (resp.status != 200) {
    throw JobClientError(resp.status, "query failed: " + resp.body);
  }
  return parse_job(json::parse(resp.body));
}

std::vector<JobStatus> JobClient::query_all(
    const std::vector<std::string>& uuids) {
  // batched query like the Java client's QUERY_BATCH_SIZE fan-out
  std::string path = "/jobs?";
  for (size_t i = 0; i < uuids.size(); ++i) {
    if (i) path += '&';
    path += "job=" + uuids[i];
  }
  HttpResponse resp = request("GET", path);
  if (resp.status != 200) {
    throw JobClientError(resp.status, "query failed: " + resp.body);
  }
  std::vector<JobStatus> out;
  json::Value parsed = json::parse(resp.body);
  for (const auto& v : parsed.as_array()) {
    out.push_back(parse_job(v));
  }
  return out;
}

void JobClient::kill(const std::string& uuid) {
  HttpResponse resp = request("DELETE", "/jobs?job=" + uuid);
  if (resp.status >= 300) {
    throw JobClientError(resp.status, "kill failed: " + resp.body);
  }
}

void JobClient::retry(const std::string& uuid, int retries) {
  json::Object body;
  body["job"] = uuid;
  body["retries"] = retries;
  HttpResponse resp = request("POST", "/retry", json::Value(body).dump());
  if (resp.status >= 300) {
    throw JobClientError(resp.status, "retry failed: " + resp.body);
  }
}

JobStatus JobClient::wait(const std::string& uuid, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  std::string last;
  JobStatus status;
  int consecutive_failures = 0;
  bool have_status = false;
  while (true) {
    try {
      status = query(uuid);
      have_status = true;
      consecutive_failures = 0;
    } catch (const JobClientError& e) {
      // definitive client errors (404 unknown job, 401/403 auth) fail
      // fast; transport failures (status 0) AND server-side 5xx (a
      // proxy answering 502/503 during leader failover) are polled
      // through like the Java client does — but never past the
      // deadline, and never swallowed into a default-constructed
      // status the caller can't distinguish from a real one
      if (e.status >= 400 && e.status < 500) throw;
      if (++consecutive_failures >= 5) throw;
      if (std::chrono::steady_clock::now() >= deadline) {
        if (!have_status) throw;
        return status;
      }
      std::this_thread::sleep_for(
          std::chrono::milliseconds(cfg_.poll_ms_ * consecutive_failures));
      continue;
    } catch (const std::exception&) {
      // malformed body from an intermediary: transient, same policy
      if (++consecutive_failures >= 5) throw;
      if (std::chrono::steady_clock::now() >= deadline) {
        if (!have_status) throw;
        return status;
      }
      std::this_thread::sleep_for(
          std::chrono::milliseconds(cfg_.poll_ms_ * consecutive_failures));
      continue;
    }
    if (status.status != last) {
      last = status.status;
      if (listener_) listener_(status);
    }
    if (status.completed()) return status;
    if (std::chrono::steady_clock::now() >= deadline) return status;
    std::this_thread::sleep_for(std::chrono::milliseconds(cfg_.poll_ms_));
  }
}

}  // namespace cook
