// cook C++ jobclient: the native-language analog of the reference's Java
// JobClient (/root/reference/jobclient/java/.../JobClient.java) — builder
// configuration, batch submission, query, kill, and a status-polling wait
// loop that fires listener callbacks on every state change.
//
// Dependency-free: HTTP over POSIX sockets (the scheduler's REST surface
// is plain HTTP behind trusted proxies, like the reference's), JSON via
// the bundled mini parser (json.hpp).
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <optional>
#include <string>
#include <vector>

#include "json.hpp"

namespace cook {

struct JobSpec {
  std::string uuid;        // empty = server-assigned
  std::string name = "cookjob";
  std::string command;
  double mem = 128.0;      // MB
  double cpus = 1.0;
  double gpus = 0.0;
  double disk = 0.0;
  int ports = 0;
  int max_retries = 1;
  int priority = 50;
  std::string pool;        // empty = server default
  std::string group_uuid;
  std::map<std::string, std::string> env;
  std::map<std::string, std::string> labels;
};

struct InstanceStatus {
  std::string task_id;
  std::string status;      // unknown/running/success/failed
  std::string hostname;
  std::optional<int> exit_code;
  std::string reason;
};

struct JobStatus {
  std::string uuid;
  std::string status;      // waiting/running/completed
  std::vector<InstanceStatus> instances;

  bool completed() const { return status == "completed"; }
  bool succeeded() const {
    for (const auto& inst : instances) {
      if (inst.status == "success") return true;
    }
    return false;
  }
};

struct HttpResponse {
  int status = 0;
  std::string body;
};

class JobClientError : public std::runtime_error {
 public:
  JobClientError(int status, const std::string& message)
      : std::runtime_error(message), status(status) {}
  int status;
};

class JobClient {
 public:
  // Builder mirrors the Java client's JobClient.Builder
  class Builder {
   public:
    Builder& url(std::string u) { url_ = std::move(u); return *this; }
    Builder& user(std::string u) { user_ = std::move(u); return *this; }
    Builder& impersonate(std::string u) { impersonate_ = std::move(u);
                                          return *this; }
    Builder& poll_interval_ms(int ms) { poll_ms_ = ms; return *this; }
    Builder& request_timeout_ms(int ms) { timeout_ms_ = ms; return *this; }
    JobClient build() const;

   private:
    friend class JobClient;
    std::string url_ = "http://127.0.0.1:12321";
    std::string user_ = "anonymous";
    std::string impersonate_;
    int poll_ms_ = 1000;
    int timeout_ms_ = 30000;
  };

  using Listener = std::function<void(const JobStatus&)>;

  // Submit jobs (and optional group uuids referenced by them); returns
  // the job uuids in submission order.
  std::vector<std::string> submit(const std::vector<JobSpec>& jobs);

  JobStatus query(const std::string& uuid);
  std::vector<JobStatus> query_all(const std::vector<std::string>& uuids);

  void kill(const std::string& uuid);
  void retry(const std::string& uuid, int retries);

  // Poll until the job completes or timeout_ms elapses; the listener (if
  // set) fires on every observed status change, like the Java client's
  // JobListener. Returns the final observed status.
  JobStatus wait(const std::string& uuid, int timeout_ms = 600000);

  void set_listener(Listener listener) { listener_ = std::move(listener); }

  // exposed for testing
  HttpResponse request(const std::string& method, const std::string& path,
                       const std::string& body = "") const;

 private:
  friend class Builder;
  explicit JobClient(Builder builder) : cfg_(std::move(builder)) {}

  static JobStatus parse_job(const json::Value& v);

  Builder cfg_;
  Listener listener_;
};

}  // namespace cook
