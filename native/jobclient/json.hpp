// Minimal JSON value, parser, and writer for the cook C++ jobclient.
//
// The reference's Java client (jobclient/java/.../JobClient.java) leans on
// org.json; this client is dependency-free, so the tiny subset of JSON the
// cook REST API speaks (objects, arrays, strings, numbers, bools, null) is
// implemented here directly.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace cook {
namespace json {

class Value;
using Array = std::vector<Value>;
using Object = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Null, Bool, Number, String, Arr, Obj };

  Value() : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(double d) : type_(Type::Number), num_(d) {}
  Value(int i) : type_(Type::Number), num_(i) {}
  Value(int64_t i) : type_(Type::Number), num_(static_cast<double>(i)) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Arr), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Obj), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool as_bool() const { return bool_; }
  double as_number() const { return num_; }
  const std::string& as_string() const { return str_; }
  const Array& as_array() const { return arr_; }
  const Object& as_object() const { return obj_; }
  Array& as_array() { return arr_; }
  Object& as_object() { return obj_; }

  // lookup with default for optional fields
  const Value& get(const std::string& key) const {
    static const Value kNull;
    auto it = obj_.find(key);
    return it == obj_.end() ? kNull : it->second;
  }
  std::string get_string(const std::string& key,
                         const std::string& fallback = "") const {
    const Value& v = get(key);
    return v.type_ == Type::String ? v.str_ : fallback;
  }
  double get_number(const std::string& key, double fallback = 0) const {
    const Value& v = get(key);
    return v.type_ == Type::Number ? v.num_ : fallback;
  }

  std::string dump() const {
    std::ostringstream out;
    write(out);
    return out.str();
  }

 private:
  void write(std::ostringstream& out) const {
    switch (type_) {
      case Type::Null: out << "null"; break;
      case Type::Bool: out << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 1e15) {
          out << static_cast<int64_t>(num_);
        } else {
          out << num_;
        }
        break;
      }
      case Type::String: write_string(out, str_); break;
      case Type::Arr: {
        out << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) out << ',';
          arr_[i].write(out);
        }
        out << ']';
        break;
      }
      case Type::Obj: {
        out << '{';
        bool first = true;
        for (const auto& [key, value] : obj_) {
          if (!first) out << ',';
          first = false;
          write_string(out, key);
          out << ':';
          value.write(out);
        }
        out << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& out, const std::string& s) {
    out << '"';
    for (char c : s) {
      switch (c) {
        case '"': out << "\\\""; break;
        case '\\': out << "\\\\"; break;
        case '\n': out << "\\n"; break;
        case '\r': out << "\\r"; break;
        case '\t': out << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof buf, "\\u%04x", c);
            out << buf;
          } else {
            out << c;
          }
      }
    }
    out << '"';
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  Array arr_;
  Object obj_;
};

class Parser {
 public:
  explicit Parser(const std::string& text) : text_(text) {}

  Value parse() {
    Value v = parse_value();
    skip_ws();
    if (pos_ != text_.size()) throw std::runtime_error("trailing JSON");
    return v;
  }

 private:
  void skip_ws() {
    while (pos_ < text_.size() &&
           std::isspace(static_cast<unsigned char>(text_[pos_]))) {
      ++pos_;
    }
  }

  char peek() {
    skip_ws();
    if (pos_ >= text_.size()) throw std::runtime_error("truncated JSON");
    return text_[pos_];
  }

  void expect(char c) {
    if (peek() != c) {
      throw std::runtime_error(std::string("expected '") + c + "'");
    }
    ++pos_;
  }

  Value parse_value() {
    switch (peek()) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Value(parse_string());
      case 't': expect_word("true"); return Value(true);
      case 'f': expect_word("false"); return Value(false);
      case 'n': expect_word("null"); return Value();
      default: return parse_number();
    }
  }

  void expect_word(const char* word) {
    skip_ws();
    for (const char* p = word; *p; ++p) {
      if (pos_ >= text_.size() || text_[pos_++] != *p) {
        throw std::runtime_error("bad literal");
      }
    }
  }

  Value parse_object() {
    expect('{');
    Object obj;
    if (peek() == '}') { ++pos_; return Value(std::move(obj)); }
    while (true) {
      std::string key = parse_string();
      expect(':');
      obj.emplace(std::move(key), parse_value());
      char c = peek();
      ++pos_;
      if (c == '}') break;
      if (c != ',') throw std::runtime_error("expected , or }");
    }
    return Value(std::move(obj));
  }

  Value parse_array() {
    expect('[');
    Array arr;
    if (peek() == ']') { ++pos_; return Value(std::move(arr)); }
    while (true) {
      arr.push_back(parse_value());
      char c = peek();
      ++pos_;
      if (c == ']') break;
      if (c != ',') throw std::runtime_error("expected , or ]");
    }
    return Value(std::move(arr));
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (pos_ < text_.size()) {
      char c = text_[pos_++];
      if (c == '"') return out;
      if (c == '\\') {
        if (pos_ >= text_.size()) break;
        char esc = text_[pos_++];
        switch (esc) {
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {
            if (pos_ + 4 > text_.size()) throw std::runtime_error("bad \\u");
            unsigned code = std::stoul(text_.substr(pos_, 4), nullptr, 16);
            pos_ += 4;
            // BMP-only UTF-8 encoding (ample for cook payloads)
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: out += esc;
        }
      } else {
        out += c;
      }
    }
    throw std::runtime_error("unterminated string");
  }

  Value parse_number() {
    skip_ws();
    size_t start = pos_;
    while (pos_ < text_.size() &&
           (std::isdigit(static_cast<unsigned char>(text_[pos_])) ||
            strchr("+-.eE", text_[pos_]))) {
      ++pos_;
    }
    if (start == pos_) throw std::runtime_error("bad number");
    return Value(std::stod(text_.substr(start, pos_ - start)));
  }

  const std::string& text_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

}  // namespace json
}  // namespace cook
