// cook_native: C++ implementations of the host-side sequential solvers.
//
// Two roles (see cook_tpu/ops/cpu_reference.py for the Python/numpy
// equivalents):
//   1. the strongest honest CPU baseline for the benchmarks — the same
//      sequential greedy decisions as Fenzo-style scheduleOnce
//      (reference behavior: scheduler.clj:617-687) at native speed;
//   2. a production fallback path for deployments without accelerators.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: make -C native   (produces libcook_native.so)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// Sequential greedy match, cpuMemBinPacker fitness.
//   demands:  [j, 3] (mem, cpus, gpus) in schedule order
//   avail:    [n, 3] available resources (mutated copy internally)
//   totals:   [n, 2] (mem, cpus) capacities
//   feasible: [j, n] uint8 constraint mask, may be null
//   out:      [j] chosen node index or -1
void greedy_match(const double* demands, int64_t j, const double* avail_in,
                  const double* totals, int64_t n, const uint8_t* feasible,
                  int64_t* out) {
  std::vector<double> avail(avail_in, avail_in + n * 3);
  std::vector<double> used(n * 2);
  for (int64_t i = 0; i < n; ++i) {
    used[i * 2 + 0] = totals[i * 2 + 0] - avail[i * 3 + 0];
    used[i * 2 + 1] = totals[i * 2 + 1] - avail[i * 3 + 1];
  }
  for (int64_t a = 0; a < j; ++a) {
    const double dm = demands[a * 3 + 0];
    const double dc = demands[a * 3 + 1];
    const double dg = demands[a * 3 + 2];
    double best_fit = -1.0;
    int64_t best = -1;
    for (int64_t i = 0; i < n; ++i) {
      if (feasible != nullptr && !feasible[a * n + i]) continue;
      if (avail[i * 3 + 0] < dm || avail[i * 3 + 1] < dc ||
          avail[i * 3 + 2] < dg)
        continue;
      const double tm = totals[i * 2 + 0];
      const double tc = totals[i * 2 + 1];
      const double fit_mem = tm > 0 ? (used[i * 2 + 0] + dm) / tm : 0.0;
      const double fit_cpu = tc > 0 ? (used[i * 2 + 1] + dc) / tc : 0.0;
      const double fit = 0.5 * (fit_mem + fit_cpu);
      if (fit > best_fit) {
        best_fit = fit;
        best = i;
      }
    }
    out[a] = best;
    if (best >= 0) {
      avail[best * 3 + 0] -= dm;
      avail[best * 3 + 1] -= dc;
      avail[best * 3 + 2] -= dg;
      used[best * 2 + 0] += dm;
      used[best * 2 + 1] += dc;
    }
  }
}

// DRU scoring + global fair-share order (reference dru.clj semantics):
// per-user cumulative max(mem/mem_div, cpus/cpu_div) over tasks sorted by
// order_key, then a global stable sort by (dru, order_key).
//   user:      [t] user index
//   mem/cpus/gpus: [t]
//   order_key: [t]
//   *_div:     [u]
//   out_dru:   [t]
//   out_order: [t] task indices in schedule order
void dru_rank(const int32_t* user, const double* mem, const double* cpus,
              const double* gpus, const double* order_key, int64_t t,
              const double* mem_div, const double* cpu_div,
              const double* gpu_div, int64_t u, int32_t gpu_mode,
              double* out_dru, int64_t* out_order) {
  std::vector<int64_t> idx(t);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    if (user[a] != user[b]) return user[a] < user[b];
    return order_key[a] < order_key[b];
  });
  double cm = 0, cc = 0, cg = 0;
  int32_t current = -1;
  for (int64_t k = 0; k < t; ++k) {
    const int64_t i = idx[k];
    if (user[i] != current) {
      current = user[i];
      cm = cc = cg = 0;
    }
    cm += mem[i];
    cc += cpus[i];
    cg += gpus[i];
    const int32_t uu = user[i] < u ? user[i] : (int32_t)(u - 1);
    if (gpu_mode) {
      out_dru[i] = cg / gpu_div[uu];
    } else {
      const double a = cm / mem_div[uu];
      const double b = cc / cpu_div[uu];
      out_dru[i] = a > b ? a : b;
    }
  }
  std::iota(out_order, out_order + t, 0);
  std::stable_sort(out_order, out_order + t, [&](int64_t a, int64_t b) {
    if (out_dru[a] != out_dru[b]) return out_dru[a] < out_dru[b];
    return order_key[a] < order_key[b];
  });
}

// Preemption victim search (reference rebalancer.clj:320-407 semantics):
// per host, tasks in descending dru accumulate on top of spare; first
// feasible prefix per host is that host's candidate (score = min dru in
// prefix; spare-only scores +inf); best candidate across hosts wins.
//   returns chosen host or -1; out_tasks/out_ntasks receive the victim
//   task indices.
int64_t find_preemption(const int32_t* task_host, const double* task_dru,
                        const double* task_res /*[t,3]*/,
                        const uint8_t* eligible, int64_t t,
                        const double* spare /*[h,3]*/,
                        const uint8_t* host_ok, int64_t h,
                        const double* demand /*[3]*/, double pending_dru,
                        double safe_dru_threshold, double min_dru_diff,
                        int64_t* out_tasks, int64_t* out_ntasks) {
  *out_ntasks = 0;
  const double dm = demand[0], dc = demand[1], dg = demand[2];
  // group eligible tasks by host
  std::vector<std::vector<int64_t>> by_host(h);
  for (int64_t i = 0; i < t; ++i) {
    const int32_t hh = task_host[i];
    if (hh < 0 || hh >= h || !eligible[i]) continue;
    if (task_dru[i] < safe_dru_threshold) continue;
    if (task_dru[i] - pending_dru <= min_dru_diff) continue;
    by_host[hh].push_back(i);
  }
  double best_score = -1.0;
  int64_t best_host = -1;
  std::vector<int64_t> best_tasks;
  bool best_is_spare = false;
  for (int64_t hh = 0; hh < h; ++hh) {
    if (!host_ok[hh]) continue;
    double cm = spare[hh * 3 + 0], cc = spare[hh * 3 + 1],
           cg = spare[hh * 3 + 2];
    if (cm >= dm && cc >= dc && cg >= dg) {
      if (!best_is_spare) {  // +inf beats every finite score; first wins
        best_is_spare = true;
        best_host = hh;
        best_tasks.clear();
      }
      continue;
    }
    if (best_is_spare) continue;
    auto& tasks = by_host[hh];
    std::stable_sort(tasks.begin(), tasks.end(), [&](int64_t a, int64_t b) {
      if (task_dru[a] != task_dru[b]) return task_dru[a] > task_dru[b];
      return a < b;
    });
    std::vector<int64_t> chosen;
    for (int64_t i : tasks) {
      cm += task_res[i * 3 + 0];
      cc += task_res[i * 3 + 1];
      cg += task_res[i * 3 + 2];
      chosen.push_back(i);
      if (cm >= dm && cc >= dc && cg >= dg) {
        const double score = task_dru[i];
        if (score > best_score) {
          best_score = score;
          best_host = hh;
          best_tasks = chosen;
        }
        break;
      }
    }
  }
  for (size_t k = 0; k < best_tasks.size(); ++k) out_tasks[k] = best_tasks[k];
  *out_ntasks = (int64_t)best_tasks.size();
  return best_host;
}

}  // extern "C"
