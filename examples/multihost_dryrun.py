"""Multi-host dry run: the DCN scale-out path on CPU processes.

Launches N processes (jax.distributed + a coordinator), forms one global
mesh spanning all processes' devices, and runs the pool-sharded match solve
across it — the exact recipe a multi-slice TPU deployment uses, with DCN
standing in for the cross-process axis (docs/tpu-design.md "Multi-host").

    python examples/multihost_dryrun.py            # spawns 2 workers
    python examples/multihost_dryrun.py --workers 4
"""
import argparse
import os
import subprocess
import sys


def worker(process_id: int, num_processes: int, coordinator: str) -> int:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from cook_tpu.ops.match import MatchProblem
    from cook_tpu.parallel.mesh import pool_sharded_match

    devices = np.array(jax.devices())  # all processes' devices
    mesh = Mesh(devices, ("pool",))
    n_pools = devices.size
    rng = np.random.default_rng(0)
    j, n = 32, 16
    demands = rng.uniform(1, 100, (n_pools, j, 3)).astype(np.float32)
    demands[:, :, 2] = 0.0
    totals = rng.uniform(500, 1000, (n_pools, n, 2)).astype(np.float32)
    avail = np.concatenate(
        [totals, np.zeros((n_pools, n, 1), np.float32)], axis=-1)

    def make_global(x):
        sharding = NamedSharding(mesh, P("pool"))
        return jax.make_array_from_process_local_data(sharding, x)

    problems = MatchProblem(
        demands=make_global(demands),
        job_valid=make_global(np.ones((n_pools, j), bool)),
        avail=make_global(avail),
        totals=make_global(totals),
        node_valid=make_global(np.ones((n_pools, n), bool)),
        feasible=make_global(np.ones((n_pools, j, n), bool)),
    )
    result = pool_sharded_match(mesh, problems)
    local = [s.data for s in result.assignment.addressable_shards]
    placed = int(sum((np.asarray(x) >= 0).sum() for x in local))
    print(f"[proc {process_id}] mesh {devices.size} devices across "
          f"{num_processes} processes; local shards placed {placed} jobs",
          flush=True)
    jax.distributed.shutdown()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--worker-id", type=int, default=None)
    parser.add_argument("--coordinator", default="127.0.0.1:29400")
    args = parser.parse_args()
    if args.worker_id is not None:
        return worker(args.worker_id, args.workers, args.coordinator)
    procs = [
        subprocess.Popen(
            [sys.executable, __file__, "--workers", str(args.workers),
             "--worker-id", str(i), "--coordinator", args.coordinator],
        )
        for i in range(args.workers)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait(timeout=300)
    print("multihost dryrun", "OK" if rc == 0 else f"FAILED rc={rc}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
