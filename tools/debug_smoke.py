#!/usr/bin/env python
"""Debug-surface smoke: GET every /debug/* endpoint, assert 200 + JSON.

Unit tests pin individual handler behaviors; what nothing pinned before
this tool is the whole surface at once — a schema-breaking refactor (a
renamed field, a handler raising on an empty ring, a route dropped from
build_app) ships silently until an operator mid-incident discovers the
endpoint 500s.  This harness boots a REAL full-stack node — JobStore +
MockCluster + Scheduler (one match cycle run, so rings hold data) +
CookApi on a ServerThread — then walks the route table from the
generated OpenAPI doc, GETs every `/debug` path (plus the per-job
timeline), and asserts every answer is the expected status with a
parseable JSON body.

A second rig boots the multi-process analog in-process — `MpRuntime`
(supervisor + shard-group workers + front end) — drives single- and
cross-group submits through the front end, and walks ITS debug
surface: /debug/shards, /debug/frontend (per-hop latency splits must
be non-zero), the federated /debug/trace?txn_id= (the merged Chrome
trace must carry front-end + coordinator + both participants'
tracks), and the federated incident routes.

    python tools/debug_smoke.py

Wired into `tools/ci_checks.py` as the `debug_smoke` step (subprocess:
the scheduler initializes jax, which does not belong in the ci_checks
driver process).
"""
from __future__ import annotations

import json
import os
import sys
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

ADMIN = {"X-Cook-Requesting-User": "admin"}


def build_rig():
    """A full-stack node with data in every debug ring."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig

    from cook_tpu.models.entities import DEFAULT_USER, Share

    store = JobStore()
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "smoke",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
         for i in range(2)],
        clock=store.clock)
    # device residency on: /debug/device must serve populated
    # device_state residency fields (mirror pools, resident bytes)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(
                              chunk=0, device_residency=True)))
    store.submit_jobs([
        Job(uuid=f"smoke-{i}", user="smoke", pool="default", command="true",
            resources=Resources(mem=200, cpus=1)) for i in range(3)])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    # force a rebalance so /debug/fairness serves a POPULATED ledger:
    # a finite share makes DRU meaningful, a hog user fills the hosts,
    # then a starved user's job that no longer fits drives the
    # preemption search to a transacted victim kill
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=500, cpus=4)))
    store.submit_jobs([
        Job(uuid=f"smoke-hog-{i}", user="hog", pool="default",
            command="true", resources=Resources(mem=1600, cpus=2))
        for i in range(4)])
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    store.submit_jobs([
        Job(uuid="smoke-starved", user="starved", pool="default",
            command="true", resources=Resources(mem=1000, cpus=1))])
    scheduler.rank_cycle(pool)
    scheduler.rebalance_cycle(pool)
    # fault_injection on so GET /debug/faults serves its (disarmed) state
    # instead of the production 403
    api = CookApi(store, scheduler, ApiConfig(fault_injection=True))
    # force metrics-history ticks so /debug/history serves NON-EMPTY
    # series (two ticks: counters/histograms need a previous tick to
    # difference against)
    api.history.sample_once()
    api.history.sample_once()
    # a zero-peer fleet observatory so /debug/fleet serves the real
    # merged-verdict shape (self row included), not the disabled stub
    from cook_tpu.obs.fleet import FleetObservatory

    api.fleet = FleetObservatory(self_url="http://smoke.local",
                                 incidents=api.incidents,
                                 self_verdict_fn=api.health_verdict)
    api.fleet.poll_once()
    # mint one incident so /debug/incidents/{id} has a real id to serve
    incident = api.incidents.capture(
        {"healthy": False, "reasons": ["debug-smoke"]}, trigger="smoke")
    return api, incident["id"]


def smoke_paths(api, incident_id: str) -> list[str]:
    """Every GET /debug route from the generated OpenAPI doc, templates
    substituted with ids that exist in this rig, plus the per-job
    timeline (the debug surface that lives under /jobs)."""
    substitutions = {"{cycle_id}": "1", "{incident_id}": incident_id}
    paths = []
    for path, methods in sorted(api._openapi["paths"].items()):
        if "get" not in methods or not path.startswith("/debug"):
            continue
        for template, value in substitutions.items():
            path = path.replace(template, value)
        if "{" in path:
            raise AssertionError(
                f"debug route {path} has a path parameter this smoke "
                f"doesn't know how to substitute — teach smoke_paths()")
        paths.append(path)
    return paths + ["/jobs/smoke-0/timeline"]


def _http(url: str, body: dict | None = None,
          headers: dict | None = None):
    """One request, JSON in/out: (status, parsed_or_None, n_bytes)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method="POST" if data else "GET",
        headers={**ADMIN, "Content-Type": "application/json",
                 **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            status, raw = r.status, r.read()
    except urllib.error.HTTPError as e:
        status, raw = e.code, e.read()
    try:
        return status, json.loads(raw), len(raw)
    except ValueError:
        return status, None, len(raw)


def mp_smoke() -> list[str]:
    """Boot an in-process MpRuntime, push traffic through the front
    end, then walk its debug surface — the cross-process tracing /
    incident routes that no single-node rig exercises."""
    from cook_tpu.mp.supervisor import MpRuntime

    failures: list[str] = []

    def check(path: str, ok: bool, problem: str, n_bytes: int) -> None:
        if ok:
            print(f"debug_smoke[mp]: {path}: 200 OK ({n_bytes} bytes)")
        else:
            failures.append(f"mp {path}: {problem}")
            print(f"debug_smoke[mp]: {path}: FAIL ({problem})")

    def spec(uuid: str, pool: str) -> dict:
        return {"uuid": uuid, "command": "true", "pool": pool,
                "mem": 64, "cpus": 1}

    runtime = MpRuntime(n_groups=2, standbys=0, inprocess=True,
                        poll_s=30.0)
    try:
        pool_a, pool_b = runtime.pools[1], runtime.pools[2]
        # single-group forwards: the hop reservoirs need samples before
        # /debug/frontend can report non-zero splits
        for i in range(3):
            status, _, _ = _http(f"{runtime.url}/jobs", body={
                "jobs": [spec(f"mp-hop-{i}", pool_a)]})
            if status != 201:
                failures.append(f"mp submit mp-hop-{i}: status {status}")
        # cross-group submit under a known txn id: the 2PC spans this
        # mints are what /debug/trace must stitch into one trace
        txn_id = "smoke-mp-trace"
        status, _, _ = _http(
            f"{runtime.url}/jobs",
            body={"jobs": [spec("mp-tr-a", pool_a),
                           spec("mp-tr-b", pool_b)]},
            headers={"X-Cook-Txn-Id": txn_id})
        if status != 201:
            failures.append(f"mp cross-group submit: status {status}")
        # mint one incident through the FRONT END's recorder so the
        # federated routes have a bundle (mp collectors embed the 2PC
        # decision-log tail, breaker states, and the route map)
        incident = runtime.frontend.incidents.capture(
            {"healthy": False, "reasons": ["debug-smoke"]},
            trigger="smoke")

        status, shards, n = _http(f"{runtime.url}/debug/shards")
        check("/debug/shards",
              status == 200 and isinstance(shards, dict)
              and shards.get("groups"),
              f"status {status} / no groups in route map", n)

        status, fe, n = _http(f"{runtime.url}/debug/frontend")
        g = str(runtime.supervisor.topology.group_for_pool(pool_a))
        hops = ((fe or {}).get("per_group", {}).get(g) or {}).get(
            "hops", {})
        flat = (status == 200) and [
            hop for hop in ("queue", "transport", "apply", "fsync")
            if not (hops.get(hop, {}).get("count", 0) > 0
                    and hops.get(hop, {}).get("p99_ms", 0.0) > 0.0)]
        check("/debug/frontend",
              status == 200 and flat == [],
              f"status {status} / zero hop splits {flat}", n)

        status, raw, n = _http(
            f"{runtime.url}/debug/trace?txn_id={txn_id}&format=raw")
        procs = {s.get("process") for s in (raw or {}).get("spans", [])}
        workers = {p for p in procs if str(p).startswith("worker-g")}
        check("/debug/trace?format=raw",
              status == 200 and raw.get("groups_failed") == []
              and "frontend" in procs and "coordinator" in procs
              and len(workers) >= 2,
              f"status {status} / merged processes {sorted(map(str, procs))}",
              n)

        status, chrome, n = _http(
            f"{runtime.url}/debug/trace?txn_id={txn_id}")
        pids = {e["args"]["name"]: e["pid"]
                for e in (chrome or {}).get("traceEvents", [])
                if e.get("name") == "process_name"}
        check("/debug/trace",
              status == 200 and pids.get("frontend") == 0
              and pids.get("coordinator") == 1
              and sum(1 for name, pid in pids.items()
                      if name.startswith("worker-g") and pid >= 2) >= 2,
              f"status {status} / pid tracks {pids}", n)

        status, _, n = _http(f"{runtime.url}/debug/trace")
        check("/debug/trace (no txn_id)", status == 400,
              f"expected 400, got {status}", n)

        # fairness scatter-merge: feed each shard group's worker
        # observatory one ledger entry for a pool it OWNS (the same
        # public call the scheduler makes), then the front end's
        # /debug/fairness must merge both groups' pool-keyed bodies
        for pool in (pool_a, pool_b):
            g_idx = runtime.supervisor.topology.group_for_pool(pool)
            worker = runtime.supervisor.workers[g_idx].worker
            worker.api.fairness.record_decisions(pool, [{
                "t_ms": 0,
                "preemptor_job": f"job-{pool}",
                "preemptor_user": "alice",
                "hostname": "h0",
                "min_preempted_dru": 1.5,
                "victims": [{"task_id": f"t-{pool}", "user": "bob",
                             "dru": 2.0, "wasted_s": 12.5,
                             "mem": 100.0, "cpus": 1.0, "gpus": 0.0}],
                "freed": {"mem": 100.0, "cpus": 1.0, "gpus": 0.0},
                "wasted_s": 12.5,
            }])
        status, fairness, n = _http(f"{runtime.url}/debug/fairness")
        merged_pools = (fairness or {}).get("pools", {})
        missing_pools = (status == 200) and [
            pool for pool in (pool_a, pool_b)
            if not (merged_pools.get(pool) or {}).get("ledger")]
        check("/debug/fairness",
              status == 200 and missing_pools == [],
              f"status {status} / pools missing ledger entries "
              f"{missing_pools}", n)

        status, index, n = _http(f"{runtime.url}/debug/incidents")
        ids = {b.get("id") for b in (index or {}).get("incidents", [])}
        check("/debug/incidents",
              status == 200 and incident["id"] in ids,
              f"status {status} / bundle index {sorted(map(str, ids))}",
              n)

        status, bundle, n = _http(
            f"{runtime.url}/debug/incidents/{incident['id']}")
        missing = (status == 200) and [
            k for k in ("decision_log", "breakers", "route_map")
            if not isinstance((bundle or {}).get(k), dict)]
        check(f"/debug/incidents/{incident['id']}",
              status == 200 and missing == [],
              f"status {status} / missing evidence {missing}", n)
    finally:
        runtime.stop()
    return failures


def main(argv=None) -> int:
    from cook_tpu.rest.server import ServerThread

    api, incident_id = build_rig()
    server = ServerThread(api).start()
    failures = []
    try:
        for path in smoke_paths(api, incident_id):
            url = server.url + path
            try:
                req = urllib.request.Request(url, headers=ADMIN)
                with urllib.request.urlopen(req, timeout=10) as r:
                    status, body = r.status, r.read()
            except urllib.error.HTTPError as e:
                status, body = e.code, e.read()
            except OSError as e:
                failures.append(f"{path}: {e}")
                print(f"debug_smoke: {path}: FAIL ({e})")
                continue
            problem = ""
            if status != 200:
                problem = f"status {status}"
            else:
                try:
                    parsed = json.loads(body)
                except ValueError as e:
                    problem = f"unparseable JSON: {e}"
                else:
                    if path == "/debug/device":
                        # residency fields: the rig runs with
                        # device_residency on, so the device_state
                        # section must exist AND carry a mirror
                        ds = parsed.get("device_state") or {}
                        if not ds.get("enabled"):
                            problem = ("device_state residency section "
                                       "missing/empty")
                        elif not any(s.get("pools")
                                     for s in ds.get("states", [])):
                            problem = ("device_state has no resident "
                                       "pool mirrors")
                    elif path == "/debug/history":
                        # the rig forced sample ticks, so the series
                        # index must be NON-EMPTY — an empty history
                        # after a forced tick is a broken sampler, not
                        # a quiet system
                        if not parsed.get("series"):
                            problem = ("history series index empty "
                                       "after forced sample ticks")
                    elif path == "/debug/fairness":
                        # the rig forced a rebalance: the ledger,
                        # rollups, and trajectories must all be
                        # populated — an empty body after a transacted
                        # preemption is a broken feed, not a fair pool
                        view = (parsed.get("pools") or {}).get(
                            "default") or {}
                        rollups = view.get("rollups") or {}
                        if not view.get("ledger"):
                            problem = ("fairness ledger empty after a "
                                       "forced rebalance")
                        elif rollups.get("tasks_preempted", 0) < 1:
                            problem = ("fairness rollups show no "
                                       "preempted tasks")
                        elif not view.get("trajectories"):
                            problem = ("fairness trajectories empty "
                                       "after rank cycles")
                    elif path == "/debug/fleet":
                        # the rig wired a fleet observatory: the merged
                        # verdict must render (self row at minimum)
                        if not parsed.get("enabled") \
                                or not parsed.get("nodes"):
                            problem = ("fleet verdict missing/empty "
                                       "nodes despite a wired "
                                       "observatory")
            if problem:
                failures.append(f"{path}: {problem}")
                print(f"debug_smoke: {path}: FAIL ({problem})")
            else:
                print(f"debug_smoke: {path}: 200 OK "
                      f"({len(body)} bytes)")
    finally:
        server.stop()
    failures += mp_smoke()
    if failures:
        print(f"debug_smoke: FAILED: {len(failures)} endpoint(s)")
        return 1
    print("debug_smoke: all debug endpoints healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
