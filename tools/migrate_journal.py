#!/usr/bin/env python
"""Convert a single-journal data_dir to the per-shard segment layout.

The sharded control plane (cook_tpu/shard/) persists per shard:
`data_dir/shards/shard-NN/{snapshot.json,journal.jsonl}` plus a
versioned `manifest.json`.  A node started with `shards > 1` against an
old single-journal data_dir auto-migrates at startup; this tool is the
OFFLINE form — run it once against a stopped node's data_dir, inspect
the summary, then start the sharded node.

Idempotent: the manifest is the exactly-once marker — re-running
reports `already-sharded` and changes nothing.  The original
snapshot.json / journal.jsonl are renamed `*.premigrate` (kept for
rollback and audit, never replayed).

    python tools/migrate_journal.py DATA_DIR --shards 4
    python tools/migrate_journal.py DATA_DIR --shards 4 --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="convert a single-journal data_dir to per-shard "
                    "journal segments (exactly once)")
    parser.add_argument("data_dir", help="the node's data directory")
    parser.add_argument("--shards", type=int, default=4,
                        help="shard count to partition into (>= 2)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary on stdout")
    args = parser.parse_args(argv)

    if not os.path.isdir(args.data_dir):
        print(f"migrate_journal: {args.data_dir} is not a directory",
              file=sys.stderr)
        return 2
    from cook_tpu.shard.journal import migrate_single_journal

    try:
        summary = migrate_single_journal(args.data_dir, args.shards)
    except ValueError as e:
        print(f"migrate_journal: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=1))
    elif summary["migrated"]:
        print(f"migrate_journal: {args.data_dir} -> {summary['shards']} "
              f"segments ({summary.get('jobs', 0)} jobs, "
              f"{summary.get('instances', 0)} instances; per-shard jobs "
              f"{summary.get('per_shard_jobs')}); originals kept as "
              f"*.premigrate")
    else:
        print(f"migrate_journal: nothing to do "
              f"({summary['reason']}, {summary['shards']} shards)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
