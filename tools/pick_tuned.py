"""Pick the best measured match config from a sweep file.

Reads tpu_sweep JSONL records, filters to packing efficiency >= the
parity bar (0.99 vs the sequential-greedy baseline), and writes the
lowest-p50 config to tuned_match.json at the repo root — which bench.py
picks up, so the round-end bench automatically runs the best
hardware-measured configuration:

    python tools/pick_tuned.py [--sweep tpu_sweep_r2.jsonl] [--min-eff 0.99]
"""
import argparse
import json
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", default="tpu_sweep_r2.jsonl")
    parser.add_argument("--out", default="tuned_match.json")
    parser.add_argument("--min-eff", type=float, default=0.99)
    args = parser.parse_args()

    best = None
    try:
        with open(args.sweep) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if ("p50_ms" not in r or r.get("platform") == "cpu"
                        or r.get("packing_eff", 0) < args.min_eff):
                    continue
                if best is None or r["p50_ms"] < best["p50_ms"]:
                    best = r
    except FileNotFoundError:
        print(f"no sweep file {args.sweep}", file=sys.stderr)
        return 1
    if best is None:
        print("no config met the efficiency bar; keeping defaults",
              file=sys.stderr)
        return 1
    tuned = {
        "backend": best.get("backend", "xla"),
        "chunk": best["chunk"],
        "rounds": best["rounds"],
        "passes": best["passes"],
        "kc": best["kc"],
        "measured_p50_ms": best["p50_ms"],
        "measured_packing_eff": best["packing_eff"],
        "source": args.sweep,
    }
    with open(args.out, "w") as f:
        json.dump(tuned, f, indent=1)
    print(json.dumps(tuned))
    return 0


if __name__ == "__main__":
    sys.exit(main())
