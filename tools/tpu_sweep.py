"""TPU tuning sweep for the match kernel at the headline config.

Run when a real device is attached; writes JSON lines to tpu_sweep.jsonl
so results survive short device windows:

    python tools/tpu_sweep.py [--out tpu_sweep.jsonl]
"""
import argparse
import itertools
import json
import sys
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="tpu_sweep.jsonl")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--pallas", action="store_true",
                        help="alias for --backend pallas")
    parser.add_argument("--backend", default="xla",
                        choices=["xla", "pallas", "bucketed"],
                        help="candidate-pass backend to sweep")
    args = parser.parse_args()
    if args.pallas:
        if args.backend not in ("xla", "pallas"):
            parser.error("--pallas conflicts with --backend "
                         f"{args.backend}; drop the legacy flag")
        args.backend = "pallas"

    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from bench import make_problem
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.match import MatchProblem, backend_flags, chunked_match

    platform = jax.devices()[0].platform
    print(f"device: {jax.devices()[0]}", file=sys.stderr)

    J, N = 131072, 16384
    j_real, n_real = 100_000, 10_000
    demands, avail, totals = make_problem(J, N, seed=2)
    job_valid = np.zeros(J, bool)
    job_valid[:j_real] = True
    node_valid = np.zeros(N, bool)
    node_valid[:n_real] = True
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.asarray(job_valid),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.asarray(node_valid),
        feasible=None,
    )
    from cook_tpu.ops import native
    t0 = time.perf_counter()
    cpu_assign, kind = (
        (native.greedy_match(demands[:j_real].astype(np.float64),
                             avail[:n_real].astype(np.float64),
                             totals[:n_real].astype(np.float64)), "c++")
        if native.available()
        else (ref.np_greedy_match(demands[:j_real], avail[:n_real],
                                  totals[:n_real]), "numpy"))
    cpu_ms = (time.perf_counter() - t0) * 1000
    q_cpu = ref.packing_quality(demands[:j_real], cpu_assign)
    print(f"cpu[{kind}] {cpu_ms:.0f} ms placed {q_cpu['num_placed']}",
          file=sys.stderr)

    if args.backend == "pallas":
        # kc is fixed at 1 by the backend; passes do the heavy lifting
        grid = list(itertools.product(
            [4096, 8192, 16384, 32768, 131072],  # chunk
            [4, 8, 12, 16],                      # passes
            [1, 2, 3],                           # rounds
            [1],                                 # kc (unused)
        ))
    elif args.backend == "bucketed":
        # early passes are ~chunk/128 x cheaper, so larger chunks and one
        # extra pass (the exact cleanup) are the interesting region
        grid = list(itertools.product(
            [1024, 2048, 4096, 8192],  # chunk
            [2, 3, 4],                 # passes (last one is exact)
            [2, 3],                    # rounds
            [64, 128],                 # kc
        ))
    else:
        grid = list(itertools.product(
            [1024, 2048, 4096, 8192],  # chunk
            [1, 2, 3],                 # passes
            [2, 3, 4],                 # rounds
            [32, 64, 128],             # kc
        ))
    # resume: skip configs already recorded (the tunnel can wedge mid-sweep;
    # the watcher restarts us and we pick up where we left off).  A config
    # is also skipped once it has 2 "started" markers without a result —
    # a config that deterministically hangs the process would otherwise
    # livelock the watcher's restart loop forever.
    done = set()
    started: dict = {}
    try:
        with open(args.out) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    key = (r.get("backend", "xla"), r["chunk"], r["passes"],
                           r["rounds"], r["kc"])
                except (json.JSONDecodeError, KeyError, TypeError):
                    continue  # truncated line from a killed writer
                if "p50_ms" in r or "error" in r:
                    done.add(key)
                elif r.get("started"):
                    started[key] = started.get(key, 0) + 1
    except FileNotFoundError:
        pass
    backend = args.backend
    with open(args.out, "a") as out:
        for chunk, passes, rounds, kc in grid:
            key = (backend, chunk, passes, rounds, kc)
            if key in done:
                continue
            if started.get(key, 0) >= 2:
                # leave a terminal record so grid-completeness analysis can
                # tell "gave up after hangs" from "never ran"
                rec = {"backend": backend, "chunk": chunk, "passes": passes,
                       "rounds": rounds, "kc": kc,
                       "error": "abandoned after 2 hung attempts"}
                print(json.dumps(rec), flush=True)
                out.write(json.dumps(rec) + "\n")
                out.flush()
                done.add(key)
                continue
            out.write(json.dumps({
                "backend": backend, "chunk": chunk, "passes": passes,
                "rounds": rounds, "kc": kc, "started": True}) + "\n")
            out.flush()
            try:
                # time must include a D2H fetch: over the remote-device
                # tunnel block_until_ready returns without waiting
                solve = lambda: np.asarray(chunked_match(
                    problem, chunk=chunk, rounds=rounds, kc=kc,
                    passes=passes, **backend_flags(backend)).assignment)
                t0 = time.perf_counter()
                a = solve()
                compile_ms = (time.perf_counter() - t0) * 1000
                times = []
                for _ in range(args.repeats):
                    t0 = time.perf_counter()
                    a = solve()
                    times.append((time.perf_counter() - t0) * 1000)
                q = ref.packing_quality(demands[:j_real], a[:j_real])
                eff = (q["cpus_placed"] / q_cpu["cpus_placed"]
                       if q_cpu["cpus_placed"] else 1.0)
                record = {
                    "platform": platform,
                    "backend": backend,
                    "chunk": chunk, "passes": passes, "rounds": rounds,
                    "kc": kc,
                    "p50_ms": round(float(np.percentile(times, 50)), 1),
                    "compile_ms": round(compile_ms),
                    "placed": q["num_placed"],
                    "packing_eff": round(eff, 4),
                    "cpu_ms": round(cpu_ms),
                }
            except Exception as e:  # noqa: BLE001 — record and continue
                record = {"backend": backend, "chunk": chunk,
                          "passes": passes, "rounds": rounds, "kc": kc,
                          "error": str(e)[:200]}
            print(json.dumps(record), flush=True)
            out.write(json.dumps(record) + "\n")
            out.flush()


if __name__ == "__main__":
    main()
