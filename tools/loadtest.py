#!/usr/bin/env python
"""Sustained control-plane load harness: p50/p99 commit-ack at target RPS.

ROADMAP item 2 (sharded control plane) is judged by one number — p99
commit-ack latency under sustained concurrent submit/query/kill traffic
— and by WHERE the time goes when it degrades.  This driver produces
both: it replays a seeded bursty traffic schedule
(`cook_tpu.sim.loadgen.rest_traffic_trace`, shared with the simulator so
load shapes reproduce across bench rounds) against a live server, and
closes by scraping `GET /debug/contention` so the report attributes the
run's latency to store-lock wait, journal fsync stalls, and replication
lag.

Two loop disciplines:

  * ``open``  (default) — requests start at the trace's arrival offsets
    regardless of completions: constant-rate pressure, what "p99 at
    target RPS" means.  A saturated server grows client-side queueing,
    which the latency numbers then honestly include.
  * ``closed`` — N workers issue back-to-back with no pacing: the
    throughput ceiling probe.

    python tools/loadtest.py --url http://host:port --rps 100 --duration 10
    python tools/loadtest.py --smoke      # tiny run against an
                                          # in-process control plane
                                          # (rest/server.InprocessControlPlane)

The smoke form is what `bench.py`'s `control_plane` phase (full and
`--smoke` tiers, run from `tools/ci_checks.py`) wraps, so
`tools/bench_gate.py` tracks commit-ack latency round over round.

Commit-ack latency here is CLIENT-observed POST /jobs wall time — apply
under the store lock + journal group-fsync + (sync-ack mode) the
replication wait — the same interval the server-side
`cook_job_latency_submit_commit_ack` histogram measures from its end.
"""
from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import threading
import time
import uuid as uuid_mod

# runnable as `python tools/loadtest.py` from anywhere: the repo root
# (one level up) carries the cook_tpu package
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _percentile(sorted_values, q):
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1,
              max(0, round(q / 100 * (len(sorted_values) - 1))))
    return sorted_values[idx]


class _Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.latency_ms: dict[str, list] = {}
        self.errors: dict[str, int] = {}      # transport + 5xx
        self.not_found: dict[str, int] = {}   # 4xx races (kill-before-
        #                                       submit-visible etc.)

    def note(self, kind: str, ms: float, status: int,
             transport_error: bool = False) -> None:
        with self._lock:
            if transport_error or status >= 500:
                self.errors[kind] = self.errors.get(kind, 0) + 1
            elif status >= 400:
                self.not_found[kind] = self.not_found.get(kind, 0) + 1
            else:
                self.latency_ms.setdefault(kind, []).append(ms)

    def kind_summary(self) -> dict:
        out = {}
        with self._lock:
            kinds = set(self.latency_ms) | set(self.errors) \
                | set(self.not_found)
            for kind in sorted(kinds):
                lat = sorted(self.latency_ms.get(kind, []))
                out[kind] = {
                    "count": len(lat),
                    "errors": self.errors.get(kind, 0),
                    "rejected_4xx": self.not_found.get(kind, 0),
                    "p50_ms": _percentile(lat, 50),
                    "p99_ms": _percentile(lat, 99),
                    "max_ms": lat[-1] if lat else None,
                }
        return out


def _execute_op(session_factory, url, op, uuids, recorder):
    import requests

    session = session_factory()
    headers = {"X-Cook-Requesting-User": op.user}
    t0 = time.perf_counter()
    status, transport_error = 0, False
    try:
        if op.kind == "submit":
            spec = dict(op.spec)
            spec["uuid"] = uuids[op.index]
            if op.pool:
                spec["pool"] = op.pool
            r = session.post(f"{url}/jobs", json={"jobs": [spec]},
                             headers=headers, timeout=30)
            status = r.status_code
            if op.pool:
                # per-pool split (a per-SHARD split when the pools were
                # drawn from ShardRouter.pools_for_distinct_shards):
                # skew and wedged-shard isolation show in one run
                recorder.note(f"submit@{op.pool}",
                              (time.perf_counter() - t0) * 1000, status)
        elif op.kind == "query":
            r = session.get(f"{url}/jobs", params={"uuid": uuids[op.ref]},
                            headers=headers, timeout=30)
            status = r.status_code
        else:  # kill — admin impersonates nobody; the submitting user
            # owns the job, so kill as that user
            r = session.delete(f"{url}/jobs",
                               params={"uuid": uuids[op.ref]},
                               headers=headers, timeout=30)
            status = r.status_code
    except requests.RequestException:
        transport_error = True
    recorder.note(op.kind, (time.perf_counter() - t0) * 1000, status,
                  transport_error)


def _thread_sessions():
    """One requests.Session per worker thread (sessions are not
    thread-safe; per-op sessions would pay a TCP handshake each)."""
    import requests

    local = threading.local()

    def factory():
        session = getattr(local, "session", None)
        if session is None:
            session = local.session = requests.Session()
        return session

    return factory


def run_loadtest(url: str, *, rps: float = 50.0, duration_s: float = 5.0,
                 mode: str = "open", workers: int = 32,
                 mix: tuple = (0.7, 0.2, 0.1), n_users: int = 8,
                 seed: int = 0, pool=None, pools=None,
                 admin_user: str = "admin",
                 warmup: int = 0, log=lambda *a: None) -> dict:
    """Drive the trace against a live server; return the report dict.
    `warmup` serial submits are issued first and NOT recorded — they pay
    the connection setup and first-touch code paths (JSON, route
    resolution, journal open) that would otherwise skew a short run's
    percentiles."""
    import requests

    from cook_tpu.sim.loadgen import rest_traffic_trace

    if warmup:
        session = requests.Session()
        for i in range(warmup):
            try:
                session.post(
                    f"{url}/jobs",
                    json={"jobs": [{"command": "true", "mem": 64,
                                    "cpus": 0.5,
                                    "uuid": str(uuid_mod.uuid4()),
                                    **({"pool": pool} if pool else {})}]},
                    headers={"X-Cook-Requesting-User": "warmup"},
                    timeout=30)
            except requests.RequestException:
                break

    ops = rest_traffic_trace(duration_s=duration_s, rps=rps, mix=mix,
                             n_users=n_users, seed=seed, pool=pool)
    # pre-assign every submit's uuid so query/kill ops can target their
    # referenced submit even while it is still in flight (a lost race
    # shows up as a 4xx, counted separately from real failures)
    uuids: dict[int, str] = {
        i: str(uuid_mod.uuid4()) for i, op in enumerate(ops)
        if op.kind == "submit"}

    class _Op:
        __slots__ = ("index", "offset_s", "kind", "user", "spec", "ref",
                     "pool")

        def __init__(self, index, src):
            self.index = index
            self.offset_s = src.offset_s
            self.kind = src.kind
            self.user = src.user
            self.spec = src.spec
            self.ref = src.ref
            self.pool = None

    run_ops = [_Op(i, op) for i, op in enumerate(ops)]
    if pools:
        # spread submits round-robin over the pool list (with pools
        # drawn per shard, this is the per-shard traffic split the
        # sharded control plane is judged on)
        submit_i = 0
        for op in run_ops:
            if op.kind == "submit":
                op.pool = pools[submit_i % len(pools)]
                submit_i += 1
    for op in run_ops:
        if op.kind == "kill":
            # only the owner (or an admin) may kill: issue the kill as
            # the user who submitted the referenced job
            op.user = ops[op.ref].user
    recorder = _Recorder()
    session_factory = _thread_sessions()
    start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as pool_:
        if mode == "open":
            for op in run_ops:
                lag = op.offset_s - (time.perf_counter() - start)
                if lag > 0:
                    time.sleep(lag)
                pool_.submit(_execute_op, session_factory, url, op, uuids,
                             recorder)
        else:  # closed loop: no pacing, back-to-back pressure
            for op in run_ops:
                pool_.submit(_execute_op, session_factory, url, op, uuids,
                             recorder)
    wall_s = time.perf_counter() - start
    kinds = recorder.kind_summary()
    submit = kinds.get("submit", {})
    # "submit@pool" rows are the per-pool SPLIT of the "submit" row,
    # not extra traffic — exclude them from the volume totals
    total = sum(k["count"] + k["errors"] + k["rejected_4xx"]
                for name, k in kinds.items() if "@" not in name)
    report = {
        "mode": mode,
        "target_rps": rps,
        "achieved_rps": round(total / wall_s, 2) if wall_s else 0.0,
        "duration_s": round(wall_s, 3),
        "ops": kinds,
        "commit_ack": {"p50_ms": submit.get("p50_ms"),
                       "p99_ms": submit.get("p99_ms"),
                       "count": submit.get("count", 0)},
        "errors": sum(k["errors"] for name, k in kinds.items()
                      if "@" not in name),
    }
    # close with the server's own attribution: where the run's write-
    # path time went (store lock / fsync / replication / per-endpoint)
    try:
        import requests

        r = requests.get(f"{url}/debug/contention",
                         headers={"X-Cook-Requesting-User": admin_user},
                         timeout=10)
        if r.status_code == 200:
            report["contention"] = r.json()
    except Exception as e:  # noqa: BLE001 — attribution is best-effort;
        # the latency numbers stand on their own
        log(f"loadtest: /debug/contention scrape failed: {e}")
    shard_summary = per_shard_summary(report.get("contention"))
    if shard_summary is not None:
        report["per_shard"] = shard_summary
    # ... and the server's retained history for the run's window: the
    # commit-ack p99 TREND (obs/tsdb.py sampled it while we drove load),
    # so a mid-run regression is visible as a slope, not hidden inside
    # one final percentile
    try:
        import requests

        r = requests.get(
            f"{url}/debug/history",
            params={"metric": "job.latency.submit_commit_ack.p99",
                    "since": -(wall_s + 5.0)},
            headers={"X-Cook-Requesting-User": admin_user}, timeout=10)
        if r.status_code == 200:
            trend = commit_ack_trend(r.json(), wall_s)
            if trend is not None:
                report["commit_ack_trend"] = trend
    except Exception as e:  # noqa: BLE001 — best-effort, same as above
        log(f"loadtest: /debug/history scrape failed: {e}")
    return report


def commit_ack_trend(history_body, duration_s: float,
                     n_buckets: int = 5) -> "dict | None":
    """Bucket the server-side commit-ack p99 series over the run's
    window: [{offset_s, p99_ms, samples}] oldest-first, plus the
    first->last delta.  The window is clamped to the run's duration
    (the scrape's `since` carries slack, and a long-lived server
    retains pre-run samples that must not read as this run's trend).
    None when the server retained no points in the window (history
    sampler off, or a run shorter than one sample tick)."""
    points = []
    for series_points in (history_body.get("series") or {}).values():
        points.extend(series_points)
    if not points:
        return None
    points.sort()
    cutoff = points[-1][0] - duration_s
    points = [p for p in points if p[0] >= cutoff]
    t0, t1 = points[0][0], points[-1][0]
    span = max(t1 - t0, 1e-9)
    buckets: list[list[float]] = [[] for _ in range(n_buckets)]
    for t, v in points:
        idx = min(n_buckets - 1, int((t - t0) / span * n_buckets))
        buckets[idx].append(v * 1000.0)  # histogram points are seconds
    rows = [{"offset_s": round(i * span / n_buckets, 2),
             "p99_ms": round(max(vals), 3), "samples": len(vals)}
            for i, vals in enumerate(buckets) if vals]
    return {
        "buckets": rows,
        "first_p99_ms": rows[0]["p99_ms"],
        "last_p99_ms": rows[-1]["p99_ms"],
        "delta_p99_ms": round(rows[-1]["p99_ms"] - rows[0]["p99_ms"], 3),
        "window_s": round(span, 2),
    }


def per_shard_summary(contention) -> "dict | None":
    """Per-shard commit-ack breakdown from a /debug/contention scrape
    (the sharded control plane's `shards` section): p50/p99 commit
    service time, commits, lock contention — and the hottest-shard
    attribution, so skew is visible in one loadtest run."""
    if not contention or "shards" not in contention:
        return None
    rows = {}
    hottest, hottest_p99 = None, -1.0
    for row in contention["shards"]:
        shard = row.get("shard")
        ack = row.get("commit_ack") or {}
        lock = row.get("lock") or {}
        p99 = float(ack.get("p99_ms") or 0.0)
        rows[str(shard)] = {
            "commit_p50_ms": ack.get("p50_ms"),
            "commit_p99_ms": ack.get("p99_ms"),
            "commits": ack.get("slow_samples", 0),
            "jobs": row.get("jobs", 0),
            "lock_contention_ratio": lock.get("contention_ratio", 0.0),
        }
        if p99 > hottest_p99:
            hottest, hottest_p99 = shard, p99
    return {"shards": rows, "hottest_shard": hottest,
            "hottest_commit_p99_ms": hottest_p99}


def run_inprocess(shards: int = 1, **kw) -> dict:
    """Smoke form: spin an InprocessControlPlane (real store lock, real
    journal fsyncs, real REST stack — no scheduler/device) and drive it.
    What bench.py's `control_plane` (shards=1) and `control_plane_sharded`
    phases wrap.  shards > 1 builds the sharded plane and spreads the
    submit traffic over one pool per shard, so the summary's per-shard
    breakdown covers every shard."""
    from cook_tpu.rest.server import InprocessControlPlane

    if shards > 1:
        from cook_tpu.shard import ShardRouter

        pools = ShardRouter(shards).pools_for_distinct_shards()
        # "default" stays for warmup traffic; the trace rides the
        # per-shard pools
        plane = InprocessControlPlane(
            shards=shards, pools=("default", *pools)).start()
        kw.setdefault("pools", pools)
    else:
        plane = InprocessControlPlane().start()
    try:
        return run_loadtest(plane.url, **kw)
    finally:
        plane.stop()


def run_mp(groups: int = 4, standbys: int = 1, *,
           inprocess: bool = False, **kw) -> dict:
    """Multi-process form: an `MpRuntime` fleet (one worker process per
    shard-group behind the shard-aware front end) driven through the
    front end's public port — forwarding, 2PC, and breaker costs are
    all in the measured path.  Traffic rides one pool per GROUP
    (`pools_for_distinct_groups`), so the per-worker breakdown in
    report["mp"] covers every worker.  What bench.py's
    `control_plane_mp` phase wraps; `inprocess=True` embeds the workers
    (tier-1 tests — no subprocess boots)."""
    import urllib.request as _url

    from cook_tpu.mp.supervisor import MpRuntime
    from cook_tpu.mp.topology import ShardGroupTopology

    pools = ShardGroupTopology(groups, groups).pools_for_distinct_groups()
    kw.setdefault("pools", pools)
    runtime = MpRuntime(n_groups=groups, standbys=standbys,
                        inprocess=inprocess,
                        pools=("default", *pools))
    try:
        report = run_loadtest(runtime.url, **kw)
        # per-worker accounting from the front end's own ledger:
        # forwarded counts + forwarded-request percentiles per group
        req = _url.Request(runtime.url + "/debug/frontend")
        with _url.urlopen(req, timeout=10) as r:
            front = json.loads(r.read())
        wall = max(report["duration_s"], 1e-6)
        per_worker = {}
        for g, row in front.get("per_group", {}).items():
            per_worker[g] = {
                "forwarded": row["forwarded"],
                "rps": round(row["forwarded"] / wall, 1),
                "forward_p50_ms": row["p50_ms"],
                "forward_p99_ms": row["p99_ms"],
                "breaker": row["breaker"],
            }
        report["mp"] = {"groups": groups,
                        "map_seq": front.get("map_seq"),
                        "per_worker": per_worker,
                        "twopc": front.get("twopc", {})}
        return report
    finally:
        runtime.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="sustained control-plane load harness")
    parser.add_argument("--url", default="",
                        help="target server; omit with --smoke to use an "
                             "in-process control plane")
    parser.add_argument("--rps", type=float, default=50.0,
                        help="target request rate (open-loop pacing)")
    parser.add_argument("--duration", type=float, default=5.0)
    parser.add_argument("--mode", choices=("open", "closed"),
                        default="open")
    parser.add_argument("--workers", type=int, default=32)
    parser.add_argument("--users", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pool", default=None)
    parser.add_argument("--mix", default="0.7,0.2,0.1",
                        help="submit:query:kill fractions")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny in-process run (rps 40, 2 s)")
    parser.add_argument("--shards", type=int, default=1,
                        help="with --smoke: drive a SHARDED in-process "
                             "control plane (one traffic pool per "
                             "shard; per-shard breakdown in the "
                             "summary)")
    parser.add_argument("--mp", type=int, default=0, metavar="N",
                        help="drive an N-worker multi-process fleet "
                             "through its front end (one traffic pool "
                             "per worker; per-worker RPS and "
                             "forwarded-request p99 in the summary)")
    parser.add_argument("--mp-standbys", type=int, default=1)
    parser.add_argument("--out", default="",
                        help="write the JSON report here too")
    args = parser.parse_args(argv)

    mix = tuple(float(x) for x in args.mix.split(","))
    kw = dict(rps=args.rps, duration_s=args.duration, mode=args.mode,
              workers=args.workers, mix=mix, n_users=args.users,
              seed=args.seed, pool=args.pool,
              log=lambda *a: print(*a, file=sys.stderr))
    if args.mp:
        if args.smoke:
            kw.update(rps=min(args.rps, 40.0),
                      duration_s=min(args.duration, 2.0))
        report = run_mp(groups=args.mp, standbys=args.mp_standbys, **kw)
    elif args.smoke:
        kw.update(rps=min(args.rps, 40.0), duration_s=min(args.duration, 2.0))
        report = run_inprocess(shards=args.shards, **kw)
    elif args.url:
        report = run_loadtest(args.url, **kw)
    else:
        parser.error("--url required (or --smoke for in-process)")
    summary = {k: report[k] for k in ("mode", "target_rps", "achieved_rps",
                                      "duration_s", "commit_ack", "errors")}
    if "per_shard" in report:
        summary["per_shard"] = report["per_shard"]
    if "commit_ack_trend" in report:
        # the trend next to the hottest-shard attribution: a mid-run
        # regression reads as a slope here, not just a final percentile
        summary["commit_ack_trend"] = report["commit_ack_trend"]
    if "mp" in report:
        summary["mp"] = report["mp"]
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    return 1 if report["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
