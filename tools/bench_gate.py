#!/usr/bin/env python
"""Bench regression gate: diff the last two bench records, fail on slowdown.

bench.py writes structured per-phase records ({"schema": "cook-bench/v1",
"phases": {"match": {"p50_ms": ...}, ...}}) to BENCH_r*.json files —
BENCH_r{NN}_phases.json per full round, BENCH_rsmoke.json for the smoke
tier.  This gate:

  1. collects records (explicit file args, or the BENCH_r*.json glob in
     --dir, sorted by round number then mtime);
  2. keeps only comparable pairs — same schema, same mode, same platform
     (a CPU-fallback round must not "regress" against a real-TPU round);
  3. REFUSES to diff two records taken on different resolved JAX
     backends (record-level `backend`, and per phase when phases carry
     their own): a silent CPU-fallback round diffed against a real
     accelerator round is not a regression signal, it is a measurement
     error — the gate fails loudly instead of comparing.  Records
     predating the backend stamp compare as before;
  4. compares each phase's p50_ms in the newest record against the
     previous comparable one; any phase slower by more than --threshold
     (default 20%) AND by more than --min-delta-ms (default 2 ms,
     absolute) fails the gate — the absolute floor keeps sub-10 ms
     phases, whose 20% band sits inside OS scheduler jitter on a loaded
     box, from flapping the gate;
  5. diffs the data-plane byte columns (`h2d_bytes`/`d2h_bytes`,
     obs/data_plane.py) on every shared phase that carries them —
     BEFORE and regardless of the backend refusal, because logical
     bytes are backend-stable (a CPU-fallback round moves the same
     bytes a TPU round would).  Byte diffs are informational by
     default; --bytes-threshold makes a relative H2D/D2H growth past
     it fail the gate, and --bytes-only restricts the whole gate to the
     byte columns (the cross-backend-safe mode: compare a CPU-fallback
     round against an accelerator round by traffic alone).

Exit codes: 0 pass / nothing to compare, 1 regression, 2 usage error.

    python tools/bench_gate.py [--dir ROOT] [--threshold 0.2]
                               [--min-delta-ms 2.0]
                               [--bytes-threshold R] [--bytes-only]
                               [files...]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

SCHEMA = "cook-bench/v1"

# phases whose byte columns are ALWAYS gated (at the timing threshold)
# even without --bytes-threshold: the match_resident tier's whole point
# is its warm-cycle transfer floor — bytes growing back on warm cycles
# is the regression the phase exists to catch, not an informational diff
BYTE_GATED_PREFIXES = ("match_resident", "rebalance_resident",
                       "elastic_resident")

# the control_plane_mp phase records `cores` and
# `rps_speedup_vs_sharded`: worker PROCESSES only beat the in-process
# sharded plane when they actually get cores, so the >= 2.5x target
# SELF-GATES (newest record, no pair needed) only when the run had the
# cores to meet it; below the floor the comparison stays recorded, not
# gated (bench.py bench_control_plane_mp)
MP_PHASE_PREFIX = "control_plane_mp"
MP_GATE_MIN_CORES = 4
MP_SPEEDUP_TARGET = 2.5


def load_record(path: str) -> dict | None:
    """Parse one bench artifact; returns a normalized record or None for
    files this gate can't judge (the driver's wrapper records carry only
    the headline line, no per-phase results)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(data, dict) or data.get("schema") != SCHEMA:
        return None
    phases = data.get("phases")
    if not isinstance(phases, dict) or not phases:
        return None
    return {
        "path": path,
        "mode": data.get("mode", "full"),
        "platform": data.get("platform", "unknown"),
        # resolved JAX backend of the run (None on records predating the
        # stamp); kept per phase too, so one phase measured on a
        # different backend refuses on its own
        "backend": data.get("backend"),
        "phases": {
            name: {"p50_ms": float(info["p50_ms"]),
                   "backend": info.get("backend"),
                   # data-plane byte stamps (optional: records predating
                   # the ledger simply diff nothing); warm_cycles feeds
                   # bench_history's warm/cold residency split
                   **{col: int(info[col]) for col in
                      ("h2d_bytes", "d2h_bytes", "warm_cycles", "cores")
                      if col in info},
                   # the mp phase's recorded fleet-vs-sharded speedup
                   # (self-gated when cores allow; see gate_mp_speedup)
                   **({"rps_speedup_vs_sharded":
                       float(info["rps_speedup_vs_sharded"])}
                      if "rps_speedup_vs_sharded" in info else {})}
            for name, info in phases.items()
            if isinstance(info, dict) and "p50_ms" in info
        },
    }


def _round_key(path: str):
    m = re.match(r"BENCH_r(\d+)", os.path.basename(path))
    return (0, int(m.group(1))) if m else (1, 0)


def collect_records(paths: list[str]) -> list[dict]:
    records = []
    for path in paths:
        record = load_record(path)
        if record is not None:
            records.append(record)
    return records


def diff_bytes(old: dict, new: dict, bytes_threshold,
               messages: list[str], regressions: list[str],
               require: bool = False,
               gated_threshold: float = None) -> None:
    """Diff the data-plane byte columns of every shared phase carrying
    them.  Bytes are DETERMINISTIC (same code -> same logical bytes) and
    backend-stable, so this runs even for pairs the timing gate refuses.
    Informational unless `bytes_threshold` is set, in which case a
    relative byte GROWTH past it regresses the phase.  `require=True`
    (the --bytes-only mode, where this IS the whole gate) additionally
    counts a byte column or whole phase that VANISHED from the new
    record as regressed — the same silently-dropped-measurement rule
    the timing gate applies to missing phases.  Phases named in
    BYTE_GATED_PREFIXES gate their byte growth at `gated_threshold`
    even when no --bytes-threshold was given."""
    if require:
        for phase in sorted(set(old["phases"]) - set(new["phases"])):
            messages.append(f"bench_gate:   {phase}: missing from the "
                            f"new record — counted as regressed")
            regressions.append(f"{phase} (missing)")
    for phase in sorted(set(old["phases"]) & set(new["phases"])):
        oinfo, ninfo = old["phases"][phase], new["phases"][phase]
        byte_gated = phase.startswith(BYTE_GATED_PREFIXES)
        threshold = bytes_threshold
        if threshold is None and byte_gated:
            threshold = gated_threshold
        for col in ("h2d_bytes", "d2h_bytes"):
            if col not in oinfo:
                continue
            if col not in ninfo:
                if require or byte_gated:
                    messages.append(
                        f"bench_gate:   {phase}: {col} dropped from the "
                        f"new record — counted as regressed")
                    regressions.append(f"{phase} ({col} missing)")
                continue
            before, after = oinfo[col], ninfo[col]
            if before > 0:
                delta = (after - before) / before
                delta_txt = f"{delta:+.1%}"
            elif after > 0:
                # growth from a zero baseline is unbounded, not 0%: it
                # must trip any threshold (a phase that moved no bytes
                # suddenly moving megabytes is the largest possible
                # regression, not a non-event)
                delta = float("inf")
                delta_txt = "from zero"
            else:
                delta = 0.0
                delta_txt = "+0.0%"
            regressed = threshold is not None and delta > threshold
            status = "REGRESSION" if regressed else (
                "ok" if after == before else "changed")
            messages.append(
                f"bench_gate:   {phase}: {col} {before} -> {after} "
                f"({delta_txt}) {status}")
            if regressed:
                regressions.append(f"{phase} ({col})")


def gate_mp_speedup(record: dict, messages: list[str],
                    regressions: list[str]) -> bool:
    """Self-gate the newest record's control_plane_mp phase(s): when the
    run had >= MP_GATE_MIN_CORES cores, a fleet-vs-sharded speedup below
    MP_SPEEDUP_TARGET regresses; on fewer cores worker processes cannot
    win (forwarding overhead, no parallelism), so the speedup stays
    recorded-not-gated.  Returns True when any phase was evaluated."""
    evaluated = False
    for phase in sorted(record["phases"]):
        if not phase.startswith(MP_PHASE_PREFIX):
            continue
        info = record["phases"][phase]
        cores = info.get("cores")
        speedup = info.get("rps_speedup_vs_sharded")
        if cores is None or speedup is None:
            continue
        evaluated = True
        if cores < MP_GATE_MIN_CORES:
            messages.append(
                f"bench_gate:   {phase}: {speedup:.2f}x vs sharded on "
                f"{cores} core(s) — recorded, not gated (the "
                f"{MP_SPEEDUP_TARGET}x target needs >= "
                f"{MP_GATE_MIN_CORES} cores)")
        elif speedup < MP_SPEEDUP_TARGET:
            messages.append(
                f"bench_gate:   {phase}: {speedup:.2f}x vs sharded on "
                f"{cores} cores REGRESSION (target >= "
                f"{MP_SPEEDUP_TARGET}x at >= {MP_GATE_MIN_CORES} cores)")
            regressions.append(f"{phase} (mp speedup)")
        else:
            messages.append(
                f"bench_gate:   {phase}: {speedup:.2f}x vs sharded on "
                f"{cores} cores ok (target {MP_SPEEDUP_TARGET}x)")
    return evaluated


def gate(records: list[dict], threshold: float,
         min_delta_ms: float = 2.0, bytes_threshold: float = None,
         bytes_only: bool = False) -> tuple[int, list[str]]:
    """(exit_code, messages).  Records are grouped by (mode, platform) —
    a CPU-fallback round must not "regress" against a real-TPU round,
    and the singleton smoke record must not shadow the full-round family
    — then EVERY family with >= 2 records compares its newest pair.  Any
    family regressing fails the gate."""
    families: dict[tuple, list[dict]] = {}
    for record in records:
        families.setdefault((record["mode"], record["platform"]),
                            []).append(record)
    messages: list[str] = []
    regressed_families = 0
    compared = False
    for (mode, platform), family in sorted(families.items()):
        if len(family) < 2:
            # a singleton family still self-gates its mp speedup: the
            # target is within ONE record (fleet vs its own inline
            # sharded baseline), no pair needed
            if bytes_only:
                continue
            regressions: list[str] = []
            mp_msgs: list[str] = []
            if not gate_mp_speedup(family[-1], mp_msgs, regressions):
                continue
            compared = True
            messages.append(
                f"bench_gate: {family[-1]['path']} (mode={mode}, "
                f"platform={platform}): mp speedup self-gate")
            messages.extend(mp_msgs)
            if regressions:
                regressed_families += 1
                messages.append(
                    f"bench_gate: FAIL — {len(regressions)} phase(s) "
                    f"regressed: {', '.join(regressions)}")
            continue
        compared = True
        old, new = family[-2], family[-1]
        messages.append(
            f"bench_gate: {old['path']} -> {new['path']} "
            f"(mode={mode}, platform={platform}, "
            f"threshold {threshold:.0%})")
        regressions: list[str] = []
        # byte columns diff FIRST — they are backend-stable, so they
        # survive the cross-backend refusal below.  match_resident*
        # phases byte-gate at the timing threshold unconditionally
        diff_bytes(old, new, bytes_threshold, messages, regressions,
                   require=bytes_only, gated_threshold=threshold)
        cross_backend = (old.get("backend") and new.get("backend")
                         and old["backend"] != new["backend"])
        if bytes_only:
            if regressions:
                regressed_families += 1
                messages.append(
                    f"bench_gate: FAIL — {len(regressions)} byte "
                    f"column(s) regressed: {', '.join(regressions)}")
            continue
        if cross_backend:
            # diffing TIMINGS across backends is a measurement error,
            # not a regression signal; refuse the pair loudly (the byte
            # diff above already ran — use --bytes-only to gate such
            # pairs on traffic alone)
            messages.append(
                f"bench_gate: REFUSED — records were taken on different "
                f"resolved JAX backends ({old['backend']} vs "
                f"{new['backend']}); re-run the bench on matching "
                f"hardware before gating (or pass --bytes-only)")
            regressed_families += 1
            continue
        # the newest record's mp speedup target is gated here too — the
        # self-gate needs no pair, but a family WITH a pair must not
        # skip it
        gate_mp_speedup(new, messages, regressions)
        for phase in sorted(set(old["phases"]) & set(new["phases"])):
            oinfo, ninfo = old["phases"][phase], new["phases"][phase]
            if (oinfo.get("backend") and ninfo.get("backend")
                    and oinfo["backend"] != ninfo["backend"]):
                messages.append(
                    f"bench_gate:   {phase}: REFUSED — measured on "
                    f"different backends ({oinfo['backend']} vs "
                    f"{ninfo['backend']})")
                regressions.append(f"{phase} (cross-backend)")
                continue
            if (oinfo.get("cores") and ninfo.get("cores")
                    and oinfo["cores"] != ninfo["cores"]):
                # p50 on 1 core vs 8 cores is a hardware diff, not a
                # regression signal — skip the timing pair, keep the
                # phase visible
                messages.append(
                    f"bench_gate:   {phase}: timing comparison skipped "
                    f"— records taken on differing core counts "
                    f"({oinfo['cores']} vs {ninfo['cores']})")
                continue
            before, after = oinfo["p50_ms"], ninfo["p50_ms"]
            if before <= 0:
                continue
            delta = (after - before) / before
            # both bounds must trip: the relative band alone would flap
            # on sub-10 ms phases whose 20% is inside scheduler jitter
            regressed = (delta > threshold
                         and after - before > min_delta_ms)
            status = ("REGRESSION" if regressed
                      else "ok (within min-delta)"
                      if delta > threshold else "ok")
            messages.append(
                f"bench_gate:   {phase}: {before:.2f} ms -> {after:.2f} ms "
                f"({delta:+.1%}) {status}")
            if regressed:
                regressions.append(phase)
        dropped = sorted(set(old["phases"]) - set(new["phases"]))
        if dropped:
            # a silently vanished phase must not read as "no regression":
            # an arbitrarily large slowdown in (or total loss of) a phase
            # the new record simply omits would otherwise pass the gate
            messages.append(f"bench_gate:   phases missing from the new "
                            f"record: {dropped} — counted as regressed")
            regressions.extend(f"{p} (missing)" for p in dropped)
        if regressions:
            regressed_families += 1
            messages.append(
                f"bench_gate: FAIL — {len(regressions)} phase(s) regressed "
                f"past {threshold:.0%}: {', '.join(regressions)}")
    if not compared:
        return 0, ["bench_gate: no (mode, platform) family has two "
                   "structured records; nothing to compare"]
    if regressed_families:
        return 1, messages
    messages.append("bench_gate: PASS")
    return 0, messages


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="fail when the newest bench record regressed")
    parser.add_argument("files", nargs="*",
                        help="explicit record paths (oldest first); "
                             "default: BENCH_r*.json in --dir")
    parser.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parser.add_argument("--threshold", type=float, default=0.2,
                        help="max tolerated relative slowdown (0.2 = 20%%)")
    parser.add_argument("--min-delta-ms", type=float, default=2.0,
                        help="absolute slowdown below this never counts "
                             "as a regression (jitter floor for tiny "
                             "phases)")
    parser.add_argument("--bytes-threshold", type=float, default=None,
                        help="fail when a phase's h2d/d2h bytes GREW by "
                             "more than this fraction (default: byte "
                             "diffs are informational)")
    parser.add_argument("--bytes-only", action="store_true",
                        help="gate ONLY the data-plane byte columns — "
                             "bytes are backend-stable, so this mode "
                             "compares across CPU-fallback/accelerator "
                             "pairs the timing gate refuses; inherits "
                             "--threshold when --bytes-threshold is "
                             "not given (a gate must be able to fail)")
    args = parser.parse_args(argv)
    if args.threshold <= 0:
        print("bench_gate: --threshold must be positive", file=sys.stderr)
        return 2
    if args.min_delta_ms < 0:
        print("bench_gate: --min-delta-ms must be >= 0", file=sys.stderr)
        return 2
    if args.bytes_only and args.bytes_threshold is None:
        # --bytes-only IS a gate: without an enforcing threshold it
        # would print informational diffs and pass unconditionally —
        # inherit the timing threshold so the mode fails on real growth
        args.bytes_threshold = args.threshold
    paths = args.files or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_r*.json")),
        key=lambda p: (_round_key(p), os.path.getmtime(p)))
    code, messages = gate(collect_records(paths), args.threshold,
                          args.min_delta_ms,
                          bytes_threshold=args.bytes_threshold,
                          bytes_only=args.bytes_only)
    for message in messages:
        print(message)
    return code


if __name__ == "__main__":
    sys.exit(main())
