#!/usr/bin/env python
"""Static metrics + tracing lint: every registration site must agree.

The registry raises at RUNTIME when one name is requested as two
different metric types — but only when the second call site actually
executes, which for cold paths can be mid-incident.  This linter walks
the source tree instead and fails when:

  * the same metric name is registered with conflicting types
    (e.g. `counter("match.matched")` in one file and
    `gauge("match.matched")` in another);
  * a literal metric name does not render to a valid Prometheus
    identifier under the exposition mapping
    (`cook_` + name with `.`/`-` -> `_`);
  * a metric name is registered WITHOUT HELP text anywhere (every name
    needs at least one site passing the help argument — an exposition
    without `# HELP` is a metric nobody can interpret mid-incident);
  * a tracing span name (`span(...)` / `record_event(...)` literal)
    doesn't match `^[a-z0-9_.]+$` (span names become
    `cook_span_<name>` histograms and ring entries — one flat grammar);
  * the same span name is introduced from more than one module (each
    span has one owner; a shared name would merge two different
    sections into one histogram with nobody noticing);
  * **doc drift** — a literal metric name registered in code does not
    appear in the docs/observability.md catalog (exact backticked name,
    or a documented `prefix.*` wildcard).  A metric nobody documented
    is a metric nobody can interpret mid-incident; the catalog is the
    contract, so it must grow WITH the code.  Only checked when the
    linted root carries docs/observability.md (arbitrary-directory
    lints skip it);
  * **reverse doc drift** — the mirror direction: a metric-catalog
    TABLE row (the "## Metric catalog" section only; prose backticks
    elsewhere are not rows) whose family is no longer registered
    anywhere in the code fails, honoring the same `family.*` wildcard
    convention — a stale row sends the mid-incident reader hunting for
    a metric that no longer exists, so the catalog must also SHRINK
    with the code.

Aliased registrations (`g = global_registry.gauge; g("name", ...)`) are
resolved file-locally, so the monitor-gauge idiom stays covered.
Dynamic names (f-strings like `f"span.{name}"`) can't be validated
statically; their constant fragments are still checked for characters
that could never be valid, and dynamic metric sites must each carry
help (they can't be vouched for by a sibling site).

Wired into the tier-1 test run via tests/test_lint_metrics.py.

    python tools/lint_metrics.py [root]
"""
from __future__ import annotations

import ast
import pathlib
import re
import sys
from dataclasses import dataclass, field

METRIC_FACTORIES = ("counter", "gauge", "histogram")
SPAN_FUNCTIONS = ("span", "record_event")
_VALID_RENDERED = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
# characters a name fragment may use pre-mapping (".", "-" map to "_")
_VALID_FRAGMENT = re.compile(r"[a-zA-Z0-9_:.\-]*$")
_VALID_SPAN = re.compile(r"[a-z0-9_.]+$")
_VALID_SPAN_FRAGMENT = re.compile(r"[a-z0-9_.]*$")


def rendered_name(name: str) -> str:
    """The exposition-time mapping — a standalone copy of
    cook_tpu/utils/metrics.prometheus_name (this linter must run
    against arbitrary trees without importing the package)."""
    return "cook_" + name.replace(".", "_").replace("-", "_")


@dataclass
class CallSite:
    path: str
    line: int
    metric_type: str
    name: str            # literal, or the constant fragments of an f-string
    dynamic: bool = False
    has_help: bool = False


@dataclass
class SpanSite:
    path: str
    line: int
    name: str
    dynamic: bool = False


@dataclass
class LintResult:
    sites: list[CallSite] = field(default_factory=list)
    span_sites: list[SpanSite] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def _is_global_registry(node: ast.expr) -> bool:
    # global_registry.counter(...) or <mod>.global_registry.counter(...)
    if isinstance(node, ast.Name):
        return node.id == "global_registry"
    if isinstance(node, ast.Attribute):
        return node.attr == "global_registry"
    return False


def _name_arg(call: ast.Call,
              consts: dict[str, str] | None = None) -> tuple[str, bool] | None:
    """(name, dynamic) from the first positional arg; None when it isn't
    a string-ish literal at all (a variable — nothing to check).  A bare
    name bound to a file-local string constant (`_NAME = "a.b"` ...
    `gauge(_NAME, ...)`) resolves through `consts` — without this the
    constant-name idiom hides a registration from BOTH doc-drift
    directions."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.Name) and consts and arg.id in consts:
        return consts[arg.id], False
    if isinstance(arg, ast.JoinedStr):
        fragments = [v.value for v in arg.values
                     if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "".join(fragments), True
    return None


def _has_help(call: ast.Call) -> bool:
    """True when the registration passes non-empty help (2nd positional
    or help_= keyword) — "can't tell statically" (a variable) counts as
    help, only a knowably-empty/missing argument fails."""
    arg = None
    if len(call.args) >= 2:
        arg = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "help_":
                arg = kw.value
    if arg is None:
        return False
    if isinstance(arg, ast.Constant):
        return bool(arg.value)
    return True


def _registry_aliases(tree: ast.AST) -> dict[str, str]:
    """File-local names bound to a registry factory
    (`g = global_registry.gauge` -> {"g": "gauge"})."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if (isinstance(value, ast.Attribute)
                and value.attr in METRIC_FACTORIES
                and _is_global_registry(value.value)):
            aliases[node.targets[0].id] = value.attr
    return aliases


def _string_constants(tree: ast.AST) -> dict[str, str]:
    """File-local names bound (once) to a string literal
    (`_NAME = "shard.x"` -> {"_NAME": "shard.x"}).  Re-bound names are
    dropped — an ambiguous binding must not vouch for a name."""
    consts: dict[str, str] = {}
    rebound: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        target = node.targets[0].id
        if target in consts or target in rebound:
            rebound.add(target)
            consts.pop(target, None)
            continue
        if isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[target] = node.value.value
    return consts


def _is_span_call(func: ast.expr) -> bool:
    # span(...) / record_event(...) / tracing.span(...) /
    # <mod>.tracing.record_event(...)
    if isinstance(func, ast.Name):
        return func.id in SPAN_FUNCTIONS
    if isinstance(func, ast.Attribute) and func.attr in SPAN_FUNCTIONS:
        value = func.value
        if isinstance(value, ast.Name):
            return value.id == "tracing"
        if isinstance(value, ast.Attribute):
            return value.attr == "tracing"
    return False


def collect_sites(source: str, path: str) -> list[CallSite]:
    sites: list[CallSite] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return sites
    aliases = _registry_aliases(tree)
    consts = _string_constants(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        metric_type = None
        if (isinstance(func, ast.Attribute)
                and func.attr in METRIC_FACTORIES
                and _is_global_registry(func.value)):
            metric_type = func.attr
        elif isinstance(func, ast.Name) and func.id in aliases:
            metric_type = aliases[func.id]
        if metric_type is None:
            continue
        parsed = _name_arg(node, consts)
        if parsed is None:
            continue
        name, dynamic = parsed
        sites.append(CallSite(path=path, line=node.lineno,
                              metric_type=metric_type, name=name,
                              dynamic=dynamic, has_help=_has_help(node)))
    return sites


def collect_span_sites(source: str, path: str) -> list[SpanSite]:
    sites: list[SpanSite] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return sites
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_span_call(node.func)):
            continue
        parsed = _name_arg(node)
        if parsed is None:
            continue
        name, dynamic = parsed
        sites.append(SpanSite(path=path, line=node.lineno, name=name,
                              dynamic=dynamic))
    return sites


def lint_sites(sites: list[CallSite],
               span_sites: list[SpanSite] = ()) -> LintResult:
    result = LintResult(sites=sites, span_sites=list(span_sites))
    by_name: dict[str, dict[str, list[CallSite]]] = {}
    for site in sites:
        where = f"{site.path}:{site.line}"
        if site.dynamic:
            # can't validate the whole name; the constant fragments must
            # still be mappable — and help can't be vouched for by a
            # sibling site, so each dynamic site carries its own
            if not _VALID_FRAGMENT.match(site.name):
                result.errors.append(
                    f"{where}: dynamic metric name has invalid constant "
                    f"fragment {site.name!r}")
            if not site.has_help:
                result.errors.append(
                    f"{where}: dynamic metric f\"...{site.name}...\" "
                    f"registered without HELP text")
            continue
        pname = rendered_name(site.name)
        if not _VALID_RENDERED.match(pname):
            result.errors.append(
                f"{where}: metric {site.name!r} renders to invalid "
                f"Prometheus identifier {pname!r}")
        by_name.setdefault(site.name, {}).setdefault(
            site.metric_type, []).append(site)
    for name, types in sorted(by_name.items()):
        if len(types) > 1:
            locations = "; ".join(
                f"{t}@" + ",".join(f"{s.path}:{s.line}" for s in ss)
                for t, ss in sorted(types.items()))
            result.errors.append(
                f"metric {name!r} registered with conflicting types: "
                f"{locations}")
        all_sites = [s for ss in types.values() for s in ss]
        if not any(s.has_help for s in all_sites):
            locations = ",".join(f"{s.path}:{s.line}" for s in all_sites)
            result.errors.append(
                f"metric {name!r} registered without HELP text at every "
                f"site ({locations}); add help to at least one")
    _lint_spans(result)
    return result


def _lint_spans(result: LintResult) -> None:
    """Span-name rules: flat `^[a-z0-9_.]+$` grammar, one owning module
    per name (a span name reused across files merges two different code
    sections into one histogram)."""
    by_name: dict[str, list[SpanSite]] = {}
    for site in result.span_sites:
        where = f"{site.path}:{site.line}"
        if site.dynamic:
            if not _VALID_SPAN_FRAGMENT.match(site.name):
                result.errors.append(
                    f"{where}: dynamic span name has invalid constant "
                    f"fragment {site.name!r}")
            continue
        if not _VALID_SPAN.match(site.name):
            result.errors.append(
                f"{where}: span name {site.name!r} does not match "
                f"^[a-z0-9_.]+$")
        by_name.setdefault(site.name, []).append(site)
    for name, sites in sorted(by_name.items()):
        files = sorted({s.path for s in sites})
        if len(files) > 1:
            result.errors.append(
                f"span {name!r} opened from multiple modules "
                f"({', '.join(files)}); give each span one owner (or "
                f"hoist a shared helper)")


DOC_CATALOG = pathlib.Path("docs") / "observability.md"
# a backticked doc token that can name a registry metric: the literal
# name, or a trailing-`*` wildcard row covering a family
# (`monitor.*`, `obs.device.mem_*`)
_DOC_NAME = re.compile(r"`([a-zA-Z0-9_][a-zA-Z0-9_.\-]*\*?)`")


def documented_names(doc_text: str) -> tuple[set[str], list[str]]:
    """(exact names, wildcard prefixes) the catalog vouches for.  A
    `monitor.*` row covers every `monitor.`-prefixed registration."""
    exact: set[str] = set()
    prefixes: list[str] = []
    for token in _DOC_NAME.findall(doc_text):
        if token.endswith("*"):
            prefixes.append(token[:-1])
        else:
            exact.add(token)
    return exact, prefixes


def lint_doc_coverage(result: LintResult, doc_text: str,
                      doc_path: str) -> None:
    """Fail literal metric registrations missing from the catalog.
    Dynamic names can't be matched exactly and are skipped (their
    fragments were already character-checked)."""
    exact, prefixes = documented_names(doc_text)
    missing: dict[str, CallSite] = {}
    for site in result.sites:
        if site.dynamic or site.name in exact or site.name in missing:
            continue
        if any(site.name.startswith(p) for p in prefixes):
            continue
        missing[site.name] = site
    for name, site in sorted(missing.items()):
        result.errors.append(
            f"{site.path}:{site.line}: metric {name!r} is not in the "
            f"{doc_path} catalog (add a row, or a `family.*` wildcard)")


# the reverse direction is scoped to the catalog TABLE (the section
# below this heading): the rest of the doc backticks plenty of
# non-metric tokens (paths, config keys) that must not be "checked"
_CATALOG_HEADING = "## Metric catalog"
# a catalog-row token: a metric name, optionally with an
# `<angle-bracket>` placeholder segment (`span.<name>`,
# `obs.device.mem_<kind>`) or a trailing `*` — either marks the
# constant head as a wildcard prefix
_ROW_NAME = re.compile(
    r"`([a-zA-Z0-9_][a-zA-Z0-9_.\-]*)(<[a-zA-Z_]+>[a-zA-Z0-9_.\-]*|\*)?`")


def catalog_rows(doc_text: str) -> list[tuple[int, list[str]]]:
    """(line number, first-cell metric tokens) for every table row in
    the metric-catalog section.  A row's first cell may carry several
    names (`journal.appends` / `journal.bytes_written`); a placeholder
    segment (`span.<name>`) normalizes to a `head.*` wildcard token so
    the dynamic-family idiom is actually checked, not skipped."""
    rows: list[tuple[int, list[str]]] = []
    in_section = False
    for lineno, line in enumerate(doc_text.splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.startswith(_CATALOG_HEADING)
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 2:
            continue
        first = cells[1]
        if set(first.strip()) <= {"-", " ", ":"}:
            continue  # the header separator row
        tokens = [head + ("*" if tail else "")
                  for head, tail in _ROW_NAME.findall(first)]
        if tokens:
            rows.append((lineno, tokens))
    return rows


def lint_reverse_doc_drift(result: LintResult, doc_text: str,
                           doc_path: str) -> None:
    """The code->docs check's mirror: a metric-catalog row whose family
    is no longer registered ANYWHERE in the linted tree fails — a stale
    row sends the mid-incident reader hunting for a metric that no
    longer exists.  A token is vouched for by a literal registration
    (exact, or prefix-covered for wildcard rows — both `family.*` and
    `span.<name>`-style placeholder rows normalize to wildcards in
    catalog_rows) or by a dynamic registration whose constant fragment
    overlaps it (the doc token and the f-string prefix share a
    prefix)."""
    literals = {s.name for s in result.sites if not s.dynamic}
    fragments = [s.name for s in result.sites if s.dynamic and s.name]

    def covered(token: str) -> bool:
        if token.endswith("*"):
            prefix = token[:-1]
            return (any(name.startswith(prefix) for name in literals)
                    or any(f.startswith(prefix) or prefix.startswith(f)
                           for f in fragments))
        if token in literals:
            return True
        # `span.<name>`-style rows parse to their constant head ("span.");
        # match against dynamic sites' constant fragments either way round
        return any(f.startswith(token) or token.startswith(f)
                   for f in fragments)

    flagged: set[str] = set()
    for lineno, tokens in catalog_rows(doc_text):
        for token in tokens:
            if token in flagged or covered(token):
                continue
            flagged.add(token)
            result.errors.append(
                f"{doc_path}:{lineno}: catalog row names {token!r} but "
                f"no registration in the code matches it — prune the "
                f"row (or restore the metric)")


def lint_tree(root: str) -> LintResult:
    root_path = pathlib.Path(root)
    sites: list[CallSite] = []
    span_sites: list[SpanSite] = []
    scan_dirs = [d for d in (root_path / "cook_tpu", root_path / "tools")
                 if d.is_dir()]
    if not scan_dirs:   # linting an arbitrary directory
        scan_dirs = [root_path]
    for scan in scan_dirs:
        for path in sorted(scan.rglob("*.py")):
            try:
                source = path.read_text()
            except OSError:
                continue
            sites.extend(collect_sites(source, str(path)))
            span_sites.extend(collect_span_sites(source, str(path)))
    result = lint_sites(sites, span_sites)
    doc = root_path / DOC_CATALOG
    if doc.is_file():
        try:
            doc_text = doc.read_text()
        except OSError:
            return result
        lint_doc_coverage(result, doc_text, str(doc))
        lint_reverse_doc_drift(result, doc_text, str(doc))
    return result


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else str(pathlib.Path(__file__).parent.parent)
    result = lint_tree(root)
    for error in result.errors:
        print(f"lint_metrics: {error}", file=sys.stderr)
    literal = sum(1 for s in result.sites if not s.dynamic)
    print(f"lint_metrics: {len(result.sites)} metric call sites "
          f"({literal} literal), {len(result.span_sites)} span sites, "
          f"{len(result.errors)} errors")
    return 1 if result.errors else 0


if __name__ == "__main__":
    sys.exit(main())
