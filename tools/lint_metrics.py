#!/usr/bin/env python
"""Static metrics lint: every `global_registry.*` call site must agree.

The registry raises at RUNTIME when one name is requested as two
different metric types — but only when the second call site actually
executes, which for cold paths can be mid-incident.  This linter walks
the source tree instead and fails when:

  * the same metric name is registered with conflicting types
    (e.g. `counter("match.matched")` in one file and
    `gauge("match.matched")` in another);
  * a literal metric name does not render to a valid Prometheus
    identifier under the exposition mapping
    (`cook_` + name with `.`/`-` -> `_`).

Dynamic names (f-strings like `f"span.{name}"`) can't be validated
statically; their constant fragments are still checked for characters
that could never be valid.

Wired into the tier-1 test run via tests/test_lint_metrics.py.

    python tools/lint_metrics.py [root]
"""
from __future__ import annotations

import ast
import pathlib
import re
import sys
from dataclasses import dataclass, field

METRIC_FACTORIES = ("counter", "gauge", "histogram")
_VALID_RENDERED = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
# characters a name fragment may use pre-mapping (".", "-" map to "_")
_VALID_FRAGMENT = re.compile(r"[a-zA-Z0-9_:.\-]*$")


def rendered_name(name: str) -> str:
    """The exposition-time mapping from utils/metrics.py render_prometheus."""
    return "cook_" + name.replace(".", "_").replace("-", "_")


@dataclass
class CallSite:
    path: str
    line: int
    metric_type: str
    name: str            # literal, or the constant fragments of an f-string
    dynamic: bool = False


@dataclass
class LintResult:
    sites: list[CallSite] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def _is_global_registry(node: ast.expr) -> bool:
    # global_registry.counter(...) or <mod>.global_registry.counter(...)
    if isinstance(node, ast.Name):
        return node.id == "global_registry"
    if isinstance(node, ast.Attribute):
        return node.attr == "global_registry"
    return False


def _name_arg(call: ast.Call) -> tuple[str, bool] | None:
    """(name, dynamic) from the first positional arg; None when it isn't
    a string-ish literal at all (a variable — nothing to check)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        fragments = [v.value for v in arg.values
                     if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "".join(fragments), True
    return None


def collect_sites(source: str, path: str) -> list[CallSite]:
    sites: list[CallSite] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return sites
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in METRIC_FACTORIES
                and _is_global_registry(func.value)):
            continue
        parsed = _name_arg(node)
        if parsed is None:
            continue
        name, dynamic = parsed
        sites.append(CallSite(path=path, line=node.lineno,
                              metric_type=func.attr, name=name,
                              dynamic=dynamic))
    return sites


def lint_sites(sites: list[CallSite]) -> LintResult:
    result = LintResult(sites=sites)
    by_name: dict[str, dict[str, list[CallSite]]] = {}
    for site in sites:
        where = f"{site.path}:{site.line}"
        if site.dynamic:
            # can't validate the whole name; the constant fragments must
            # still be mappable
            if not _VALID_FRAGMENT.match(site.name):
                result.errors.append(
                    f"{where}: dynamic metric name has invalid constant "
                    f"fragment {site.name!r}")
            continue
        pname = rendered_name(site.name)
        if not _VALID_RENDERED.match(pname):
            result.errors.append(
                f"{where}: metric {site.name!r} renders to invalid "
                f"Prometheus identifier {pname!r}")
        by_name.setdefault(site.name, {}).setdefault(
            site.metric_type, []).append(site)
    for name, types in sorted(by_name.items()):
        if len(types) > 1:
            locations = "; ".join(
                f"{t}@" + ",".join(f"{s.path}:{s.line}" for s in ss)
                for t, ss in sorted(types.items()))
            result.errors.append(
                f"metric {name!r} registered with conflicting types: "
                f"{locations}")
    return result


def lint_tree(root: str) -> LintResult:
    root_path = pathlib.Path(root)
    sites: list[CallSite] = []
    scan_dirs = [d for d in (root_path / "cook_tpu", root_path / "tools")
                 if d.is_dir()]
    if not scan_dirs:   # linting an arbitrary directory
        scan_dirs = [root_path]
    for scan in scan_dirs:
        for path in sorted(scan.rglob("*.py")):
            try:
                source = path.read_text()
            except OSError:
                continue
            sites.extend(collect_sites(source, str(path)))
    return lint_sites(sites)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else str(pathlib.Path(__file__).parent.parent)
    result = lint_tree(root)
    for error in result.errors:
        print(f"lint_metrics: {error}", file=sys.stderr)
    literal = sum(1 for s in result.sites if not s.dynamic)
    print(f"lint_metrics: {len(result.sites)} call sites "
          f"({literal} literal), {len(result.errors)} errors")
    return 1 if result.errors else 0


if __name__ == "__main__":
    sys.exit(main())
