#!/usr/bin/env python
"""Chaos scenario suite: inject faults, observe the verdict AND the
reaction, assert full recovery.

Each scenario drives the REAL control plane (rest/server.py
InprocessControlPlane: real store lock, real journal fsyncs, real REST
stack) or the REAL scheduler (JobStore + MockCluster + Scheduler) with a
seeded `cook_tpu.faults.FaultSchedule` armed, and asserts three things
in order:

  1. the fault is OBSERVED — the matching `/debug/health` reason (or
     telemetry verdict) appears;
  2. the automatic REACTION engages — 429 shedding, circuit-breaker
     open + `cluster-circuit-open` skips, CPU solve fallback, degraded-
     async journal, follower backoff;
  3. after the fault clears, the system RECOVERS — health returns to
     ok, the queue drains, no acked transaction is lost, no task is
     launched twice.

    python tools/chaos.py --smoke          # the 3 fast CI scenarios
    python tools/chaos.py                  # the full matrix
    python tools/chaos.py --scenario launch-breaker
    python tools/chaos.py --list

Wired into `tools/ci_checks.py` as the `chaos_smoke` step; the full
matrix is the operator's chaos-drill entry point
(docs/operations.md "running a chaos drill").
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

ADMIN = {"X-Cook-Requesting-User": "admin",
         "Content-Type": "application/json"}


class ChaosFailure(AssertionError):
    """A scenario invariant did not hold."""


@dataclass
class ScenarioResult:
    name: str
    passed: bool
    seconds: float
    steps: list = field(default_factory=list)
    error: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "passed": self.passed,
                "seconds": round(self.seconds, 2), "steps": self.steps,
                "error": self.error}


def _check(cond, message: str) -> None:
    if not cond:
        raise ChaosFailure(message)


def _wait_until(pred, *, timeout_s: float, interval_s: float = 0.1,
                what: str = "condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        value = pred()
        if value:
            return value
        time.sleep(interval_s)
    raise ChaosFailure(f"timed out after {timeout_s}s waiting for {what}")


# ----------------------------------------------------------- http helpers


def _get(url: str, timeout: float = 10.0):
    req = urllib.request.Request(url, headers=ADMIN)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), {}


def _post(url: str, payload: dict, timeout: float = 30.0):
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=ADMIN, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, {}


def _submit_jobs(url: str, n: int, prefix: str) -> list:
    uuids = []
    for i in range(n):
        uuid = f"{prefix}-{i:04d}"
        status, _ = _post(f"{url}/jobs", {"jobs": [{
            "uuid": uuid, "command": "true", "mem": 64, "cpus": 0.1}]})
        _check(status == 201, f"submit {uuid} -> {status}")
        uuids.append(uuid)
    return uuids


# -------------------------------------------------------- scheduler rig


class _Clock:
    """Manually-advanced ms clock for the scheduler scenarios."""

    def __init__(self):
        self.ms = 0

    def __call__(self) -> int:
        return self.ms


def _scheduler_rig(*, n_hosts: int, n_jobs: int, fallback_cycles: int = 8,
                   job_prefix: str = "job"):
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig

    clock = _Clock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
             for i in range(n_hosts)]
    cluster = MockCluster("chaos", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0,
                          device_fallback_cycles=fallback_cycles)))
    jobs = [Job(uuid=f"{job_prefix}-{i:03d}", user=f"u{i % 3}",
                pool="default", command="true",
                resources=Resources(mem=200, cpus=1), max_retries=5)
            for i in range(n_jobs)]
    store.submit_jobs(jobs)
    return clock, store, cluster, scheduler, jobs


def _match_once(scheduler, store, clock) -> object:
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    clock.ms += 1000
    return outcome


# -------------------------------------------------------------- scenarios


def scenario_fsync_stall_sheds() -> list:
    """journal.fsync delay -> fsync-stall + commit-ack-slo-burn -> heavy
    reads shed 429 + Retry-After -> clear -> health ok, every acked job
    survives, reads serve again."""
    from cook_tpu import faults
    from cook_tpu.obs.contention import ContentionParams, SloBurnTracker
    from cook_tpu.rest.api import ApiConfig
    from cook_tpu.rest.server import InprocessControlPlane

    steps = []
    # thresholds sized so an honest-but-loaded CI disk (tens of ms per
    # real fsync) never trips them, while the injected 300ms stall
    # clears both by 3x
    params = ContentionParams(
        fsync_stall_s=0.25, commit_ack_slo_s=0.10, commit_ack_budget=0.05,
        burn_fast_s=1.5, burn_slow_s=3.0, burn_threshold=1.0,
        lock_min_acquisitions=1_000_000_000)
    cp = InprocessControlPlane(config=ApiConfig(contention=params)).start()
    try:
        # fine-grained burn buckets + a snappy shed cache so the
        # scenario observes engagement AND recovery in seconds
        cp.api.contention.commit_ack = SloBurnTracker(bucket_s=0.5,
                                                      retention_s=120.0)
        cp.api.shedder.ttl_s = 0.2
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.JOURNAL_FSYNC, mode="delay", delay_s=0.3)]))
        acked = _submit_jobs(cp.url, 10, "stall")
        steps.append(f"submitted {len(acked)} jobs under a 300ms fsync "
                     f"stall (all acked)")

        status, _, health = _get(f"{cp.url}/debug/health")
        reasons = set(health.get("reasons", []))
        _check("fsync-stall" in reasons,
               f"expected fsync-stall in {sorted(reasons)}")
        _check("commit-ack-slo-burn" in reasons,
               f"expected commit-ack-slo-burn in {sorted(reasons)}")
        steps.append(f"health degraded: {sorted(reasons)}")

        status, headers, _ = _get(f"{cp.url}/queue")
        _check(status == 429, f"expected 429 from /queue, got {status}")
        _check("Retry-After" in headers, "429 without Retry-After")
        steps.append(f"reaction: /queue shed 429, Retry-After="
                     f"{headers['Retry-After']}s")

        faults.disarm()
        time.sleep(3.6)  # both burn windows roll past the bad buckets
        # fresh clean commits roll the fsync-stall window (64 fsyncs)
        acked += _submit_jobs(cp.url, 70, "post")

        def healthy():
            _, _, h = _get(f"{cp.url}/debug/health")
            return not h.get("reasons")
        _wait_until(healthy, timeout_s=20.0, what="health ok")
        steps.append("fault cleared: health back to ok")

        status, _, _ = _get(f"{cp.url}/queue")
        _check(status != 429, f"/queue still shed after recovery "
                              f"({status})")
        for uuid in acked:
            status, _, _ = _get(f"{cp.url}/jobs/{uuid}")
            _check(status == 200, f"acked job {uuid} lost ({status})")
        steps.append(f"invariant: all {len(acked)} acked jobs present, "
                     f"reads serving")
        return steps
    finally:
        faults.disarm()
        cp.stop()


def scenario_launch_breaker() -> list:
    """cluster.launch failures -> mea-culpa launch-failed flow-back ->
    breaker opens (accepts_work False, jobs skip cluster-circuit-open,
    no instance churn) -> cooldown -> half-open probe launch succeeds ->
    breaker closes, queue drains, no task launched twice."""
    from cook_tpu import faults
    from cook_tpu.faults.breaker import BreakerParams, BreakerState
    from cook_tpu.models.entities import JobState
    from cook_tpu.scheduler import flight_recorder as flight_codes

    steps = []
    clock, store, cluster, scheduler, jobs = _scheduler_rig(
        n_hosts=6, n_jobs=8, job_prefix="brk")
    breaker = cluster.configure_breaker(BreakerParams(
        window=4, min_samples=2, error_threshold=0.5, cooldown_s=0.3))
    faults.arm(faults.FaultSchedule([faults.FaultRule(
        point=faults.CLUSTER_LAUNCH, mode="error", times=2,
        match={"cluster": "chaos"})]))
    try:
        for _ in range(2):
            _match_once(scheduler, store, clock)
        _check(breaker.state is BreakerState.OPEN,
               f"breaker should be open, is {breaker.state}")
        _check(not cluster.accepts_work, "open breaker still accepts work")
        failed_attempts = len(store.instances)
        steps.append(f"2 launch RPC failures -> {failed_attempts} "
                     f"mea-culpa launch-failed attempts, breaker OPEN")

        _match_once(scheduler, store, clock)  # open cycle: jobs skip
        _check(len(store.instances) == failed_attempts,
               "open breaker cycle still transacted launches")
        code = scheduler.recorder.job_reason(jobs[0].uuid)[1]
        _check(code == flight_codes.CLUSTER_CIRCUIT_OPEN,
               f"expected cluster-circuit-open skip, got {code}")
        steps.append("reaction: offers withheld, jobs skip "
                     "cluster-circuit-open (no mea-culpa burn)")

        faults.disarm()  # (rule exhausted anyway: times=2)
        time.sleep(0.35)  # cooldown -> half-open on next accepts_work
        for _ in range(4):
            _match_once(scheduler, store, clock)
            if all(store.jobs[j.uuid].state is JobState.RUNNING
                   for j in jobs):
                break
        _check(breaker.state is BreakerState.CLOSED,
               f"probe should close the breaker, is {breaker.state}")
        for j in jobs:
            _check(store.jobs[j.uuid].state is JobState.RUNNING,
                   f"{j.uuid} not running after recovery "
                   f"({store.jobs[j.uuid].state})")
        steps.append("recovery: half-open probe launch succeeded, "
                     "breaker CLOSED, all 8 jobs running (queue drained)")

        # no duplicate launch: every live backend task belongs to exactly
        # one store instance, and each job has exactly one live attempt
        live = [i for i in store.instances.values()
                if not i.status.terminal]
        _check(len(live) == len(jobs),
               f"{len(live)} live instances for {len(jobs)} jobs")
        _check(len({i.task_id for i in live}) == len(live),
               "duplicate task ids among live instances")
        _check(set(cluster.running) == {i.task_id for i in live},
               "backend running set diverges from store live set")
        steps.append("invariant: no duplicate launch (backend running "
                     "set == store live set)")
        return steps
    finally:
        faults.disarm()


def scenario_device_fallback() -> list:
    """device.solve error -> the SAME cycle re-solves on the CPU
    reference (placements equal the healthy run's), health says
    device-degraded -> fallback window elapses -> device probe succeeds
    -> health clears."""
    from cook_tpu import faults
    from cook_tpu.models.entities import Job, JobState, Resources

    steps = []
    # healthy twin: same trace, no fault — the parity baseline
    _, store_a, _, sched_a, _ = _scheduler_rig(
        n_hosts=3, n_jobs=6, fallback_cycles=2, job_prefix="dev")
    clock_b, store_b, _, sched_b, jobs = _scheduler_rig(
        n_hosts=3, n_jobs=6, fallback_cycles=2, job_prefix="dev")
    try:
        # the healthy baseline runs BEFORE arming — the times=1 rule
        # must fire on the degraded twin's solve, not this one
        pool_a = store_a.pools["default"]
        sched_a.rank_cycle(pool_a)
        healthy = sched_a.match_cycle(pool_a)
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.DEVICE_SOLVE, mode="error", times=1)]))
        degraded = _match_once(sched_b, store_b, clock_b)
        _check(len(degraded.matched) == len(jobs),
               f"fallback cycle matched {len(degraded.matched)}/"
               f"{len(jobs)} — a cycle was lost to the sick device")
        a = {(j.uuid, o.hostname) for j, o in healthy.matched}
        b = {(j.uuid, o.hostname) for j, o in degraded.matched}
        _check(a == b, f"CPU fallback placements diverge: {a ^ b}")
        steps.append(f"solve raised; same cycle re-solved on CPU with "
                     f"placement parity ({len(b)} jobs)")

        reasons = set(sched_b.telemetry.health().get("reasons", []))
        _check("device-degraded" in reasons,
               f"expected device-degraded in {sorted(reasons)}")
        steps.append("health: device-degraded (with pool evidence)")

        # diagnosis: the ok->degraded transition must have captured an
        # incident bundle with the evidence an operator needs
        bundles = sched_b.incidents.bundles()
        _check(len(bundles) == 1,
               f"expected exactly 1 incident bundle, got {len(bundles)}")
        bundle = sched_b.incidents.get(bundles[0]["id"])
        _check("device-degraded" in bundle["reasons"],
               f"bundle reasons missing device-degraded: "
               f"{bundle['reasons']}")
        _check(bundle.get("cycles"),
               "incident bundle carries no cycle records")
        _check("traceEvents" in (bundle.get("trace") or {}),
               "incident bundle carries no chrome-trace export")
        armed = bundle.get("faults") or {}
        _check(any(r.get("point") == "device.solve"
                   for r in armed.get("rules", [])),
               f"bundle fault schedule missing device.solve: {armed}")
        steps.append(f"diagnosis: incident bundle {bundle['id']} captured "
                     f"(verdict + cycle records + chrome trace + armed "
                     f"faults)")

        # keep the pool solvable through the fallback window + probe
        extra = 0
        for cycle in range(3):
            more = [Job(uuid=f"dev-x{cycle}-{i}", user="u0",
                        pool="default", command="true",
                        resources=Resources(mem=100, cpus=0.5),
                        max_retries=5) for i in range(2)]
            store_b.submit_jobs(more)
            extra += len(more)
            _match_once(sched_b, store_b, clock_b)
        reasons = set(sched_b.telemetry.health().get("reasons", []))
        _check("device-degraded" not in reasons,
               f"device probe did not clear the reason: {sorted(reasons)}")
        steps.append("recovery: fallback window elapsed, device probe "
                     "succeeded, health ok")

        running = sum(1 for j in store_b.jobs.values()
                      if j.state is JobState.RUNNING)
        _check(running == len(jobs) + extra,
               f"{running}/{len(jobs) + extra} jobs running")
        steps.append(f"invariant: queue drained ({running} jobs running)")
        return steps
    finally:
        faults.disarm()


def scenario_fsync_degrade() -> list:
    """journal.fsync ERROR under the degrade-async policy -> commits
    still ack, health says journal-fsync-degraded -> disk recovers ->
    reason clears; the journal holds every acked commit."""
    from cook_tpu import faults
    from cook_tpu.models import persistence
    from cook_tpu.rest.server import InprocessControlPlane

    steps = []
    cp = InprocessControlPlane(journal_kw={
        "fsync_policy": "degrade-async", "degraded_retry_s": 0.2}).start()
    try:
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.JOURNAL_FSYNC, mode="error")]))
        acked = _submit_jobs(cp.url, 5, "deg")
        steps.append("5 jobs acked while every fsync FAILED "
                     "(degrade-async)")
        _, _, health = _get(f"{cp.url}/debug/health")
        _check("journal-fsync-degraded" in health.get("reasons", []),
               f"expected journal-fsync-degraded in {health.get('reasons')}")
        steps.append("health: journal-fsync-degraded")

        faults.disarm()
        time.sleep(0.25)  # past degraded_retry_s: next sync re-probes
        acked += _submit_jobs(cp.url, 1, "deg-post")

        def cleared():
            _, _, h = _get(f"{cp.url}/debug/health")
            return "journal-fsync-degraded" not in h.get("reasons", [])
        _wait_until(cleared, timeout_s=5.0,
                    what="journal-fsync-degraded to clear")
        steps.append("recovery: disk probe succeeded, reason cleared")

        cp.journal.sync()
        events = persistence.read_journal(cp.journal.path)
        journaled = {e.get("data", {}).get("uuid")
                     for e in events if e.get("kind") == "job/created"}
        missing = [u for u in acked if u not in journaled]
        _check(not missing, f"acked jobs missing from the journal: "
                            f"{missing}")
        steps.append(f"invariant: all {len(acked)} acked commits on disk")
        return steps
    finally:
        faults.disarm()
        cp.stop()


def scenario_replication_lag() -> list:
    """replication.fetch dropped -> follower backs off (jittered, capped;
    reconnects counted) and the leader's health says replication-lag ->
    drop clears -> follower catches up, health ok, stores converge."""
    from cook_tpu import faults
    from cook_tpu.control.replication import JournalFollower
    from cook_tpu.models.store import JobStore
    from cook_tpu.obs.contention import ContentionParams
    from cook_tpu.rest.api import ApiConfig
    from cook_tpu.rest.server import InprocessControlPlane
    from cook_tpu.utils.retry import RetryPolicy

    steps = []
    params = ContentionParams(replication_lag_events=5,
                              replication_ack_age_s=0.4)
    cp = InprocessControlPlane(config=ApiConfig(contention=params)).start()
    store2 = JobStore()
    follower = JournalFollower(
        store2, leader_url_fn=lambda: cp.url, self_url="http://standby",
        member_id="standby", poll_s=0.05, timeout_s=2.0, long_poll_s=0.1,
        reconnect_policy=RetryPolicy(base_s=0.05, cap_s=0.3)).start()
    try:
        _submit_jobs(cp.url, 3, "rep")
        _wait_until(lambda: store2.last_seq() == cp.store.last_seq(),
                    timeout_s=5.0, what="initial follower sync")
        steps.append("follower synced 3 jobs")

        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.REPLICATION_FETCH, mode="error")]))
        _submit_jobs(cp.url, 10, "rep-lag")

        def lagging():
            _, _, h = _get(f"{cp.url}/debug/health")
            return "replication-lag" in h.get("reasons", [])
        _wait_until(lagging, timeout_s=5.0, what="replication-lag reason")
        steps.append("health: replication-lag (follower behind + silent)")
        _wait_until(lambda: follower.reconnect_attempts >= 2,
                    timeout_s=5.0, what="follower reconnect backoff")
        steps.append(f"reaction: follower backing off "
                     f"({follower.reconnect_attempts} reconnect attempts "
                     f"counted)")

        faults.disarm()
        _wait_until(lambda: store2.last_seq() == cp.store.last_seq(),
                    timeout_s=10.0, what="follower catch-up")
        _wait_until(lambda: not lagging(), timeout_s=5.0,
                    what="replication-lag to clear")
        _check(len(store2.jobs) == len(cp.store.jobs),
               f"stores diverge: {len(store2.jobs)} vs "
               f"{len(cp.store.jobs)} jobs")
        steps.append("recovery: follower caught up, stores converged, "
                     "health ok")
        return steps
    finally:
        faults.disarm()
        follower.stop()
        cp.stop()


def scenario_failover_fsync() -> list:
    """fsync fault (fail-stop) on the LEADER's journal while a durable
    follower tails it -> the failing commit errors to its client -> the
    leader "dies" -> a store recovered from the FOLLOWER's local disk
    holds every previously-acked transaction."""
    from cook_tpu import faults
    from cook_tpu.control.replication import JournalFollower
    from cook_tpu.models import persistence
    from cook_tpu.models.store import JobStore
    from cook_tpu.rest.server import InprocessControlPlane

    steps = []
    follower_dir = tempfile.mkdtemp(prefix="cook-chaos-standby-")
    cp = InprocessControlPlane().start()
    store2 = JobStore()
    journal2 = persistence.attach_journal(
        store2, os.path.join(follower_dir, "journal.jsonl"))
    follower = JournalFollower(
        store2, leader_url_fn=lambda: cp.url, self_url="http://standby",
        member_id="standby", data_dir=follower_dir, journal=journal2,
        poll_s=0.05, timeout_s=2.0, long_poll_s=0.1).start()
    try:
        acked = _submit_jobs(cp.url, 5, "fo")
        _wait_until(lambda: store2.last_seq() == cp.store.last_seq(),
                    timeout_s=5.0, what="follower sync")
        steps.append("5 acked jobs replicated to the durable standby")

        # the leader's disk dies mid-fsync (the follower's own journal
        # is NOT matched by the rule — one process hosts both)
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.JOURNAL_FSYNC, mode="error",
            match={"path": cp.journal.path})]))
        status, _ = _post(f"{cp.url}/jobs", {"jobs": [{
            "uuid": "fo-doomed", "command": "true", "mem": 64,
            "cpus": 0.1}]})
        _check(status >= 500,
               f"fail-stop fsync should error the commit, got {status}")
        steps.append(f"fail-stop: commit during the fsync fault answered "
                     f"{status} (client knows it is not durable)")

        # leader crashes; promote from the follower's LOCAL copy
        cp.server.stop()
        follower.stop()
        journal2.sync()
        journal2.close()
        promoted = persistence.recover(follower_dir)
        _check(promoted is not None, "nothing recoverable on the standby")
        missing = [u for u in acked if u not in promoted.jobs]
        _check(not missing,
               f"acked txns lost across failover: {missing}")
        steps.append(f"invariant: promoted standby holds all "
                     f"{len(acked)} acked jobs")
        return steps
    finally:
        faults.disarm()
        cp.stop()
        shutil.rmtree(follower_dir, ignore_errors=True)


def scenario_wedged_shard() -> list:
    """journal.fsync delay on ONE shard's segment -> only that shard's
    keys degrade (slow-path commits), other shards' commit-ack p99 stays
    within SLO, health names the wedged shard -> a leader failover
    mid-drill (recover from the per-shard segments) loses no acked txn
    -> fault clears -> the wedged shard serves at full speed again."""
    import statistics as _stats

    from cook_tpu import faults
    from cook_tpu.obs.contention import ContentionParams
    from cook_tpu.rest.api import ApiConfig
    from cook_tpu.rest.server import InprocessControlPlane
    from cook_tpu.shard import ShardRouter
    from cook_tpu.shard import journal as shard_journal

    steps = []
    n_shards = 4
    router = ShardRouter(n_shards)
    pools = router.pools_for_distinct_shards()
    params = ContentionParams(
        fsync_stall_s=0.25, lock_min_acquisitions=1_000_000_000)
    cp = InprocessControlPlane(
        shards=n_shards, pools=tuple(pools),
        config=ApiConfig(contention=params)).start()
    wedged = 2
    wedged_pool = pools[wedged]
    delay_s = 0.3
    slo_ms = 150.0
    acked: list = []

    def submit_timed(pool: str, uuid: str) -> float:
        t0 = time.perf_counter()
        status, _ = _post(f"{cp.url}/jobs", {"jobs": [{
            "uuid": uuid, "command": "true", "mem": 64, "cpus": 0.1,
            "pool": pool}]})
        _check(status == 201, f"submit {uuid} -> {status}")
        acked.append(uuid)
        return (time.perf_counter() - t0) * 1000

    try:
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.JOURNAL_FSYNC, mode="delay", delay_s=delay_s,
            match={"path": cp.journals[wedged].path})]))
        walls: dict[str, list] = {p: [] for p in pools}
        for i in range(6):
            for p in pools:
                walls[p].append(submit_timed(p, f"wedge-{p}-{i:02d}"))
        wedged_p99 = max(walls[wedged_pool])
        other_p99 = max(max(walls[p]) for p in pools
                        if p != wedged_pool)
        _check(wedged_p99 >= delay_s * 1000 * 0.8,
               f"wedged shard commits were not slowed "
               f"({wedged_p99:.0f} ms)")
        _check(other_p99 < slo_ms,
               f"healthy shards degraded too: worst p99 "
               f"{other_p99:.0f} ms (SLO {slo_ms:.0f} ms)")
        healthy_p50 = _stats.median(
            w for p in pools if p != wedged_pool for w in walls[p])
        steps.append(
            f"shard {wedged} wedged ({delay_s * 1000:.0f} ms fsync "
            f"delay): its commits take {wedged_p99:.0f} ms while other "
            f"shards stay at p50 {healthy_p50:.1f} ms / worst "
            f"{other_p99:.0f} ms — blast radius is ONE shard")

        _, _, health = _get(f"{cp.url}/debug/health")
        stalls = [d for d in health.get("degradations", [])
                  if d.get("reason") == "fsync-stall"]
        _check(any(d.get("shard") == wedged for d in stalls),
               f"health does not attribute the stall to shard "
               f"{wedged}: {stalls}")
        _check(all(d.get("shard") in (None, wedged) for d in stalls),
               f"healthy shards flagged too: {stalls}")
        steps.append(f"health: fsync-stall names shard {wedged} (and "
                     f"only it)")

        # leader failover MID-DRILL: a promoted process recovers from
        # the per-shard segments — every acked txn must be there
        recovered = shard_journal.recover_sharded(cp.data_dir, n_shards)
        _check(recovered is not None, "nothing recoverable on disk")
        missing = [u for u in acked if u not in recovered.jobs]
        _check(not missing,
               f"acked txns lost across mid-drill failover: {missing}")
        steps.append(f"failover mid-drill: recovery from the segment "
                     f"layout holds all {len(acked)} acked jobs")

        faults.disarm()
        # roll the wedged segment's recent-fsync window (64 entries)
        # with clean commits, then health must clear
        for i in range(70):
            submit_timed(wedged_pool, f"wedge-post-{i:03d}")

        def cleared():
            _, _, h = _get(f"{cp.url}/debug/health")
            return "fsync-stall" not in h.get("reasons", [])
        _wait_until(cleared, timeout_s=20.0, what="fsync-stall to clear")
        fast = submit_timed(wedged_pool, "wedge-final")
        _check(fast < slo_ms,
               f"wedged shard still slow after recovery ({fast:.0f} ms)")
        for uuid in acked:
            status, _, _ = _get(f"{cp.url}/jobs/{uuid}")
            _check(status == 200, f"acked job {uuid} lost ({status})")
        steps.append(f"recovery: shard {wedged} back to "
                     f"{fast:.1f} ms commits, health ok, all "
                     f"{len(acked)} acked jobs present")
        return steps
    finally:
        faults.disarm()
        cp.stop()


def scenario_killed_worker() -> list:
    """SIGKILL one shard-group WORKER PROCESS mid-traffic -> only that
    group's keys degrade (the other worker keeps acking submits at 201
    throughout) -> the supervisor promotes a standby, which adopts the
    dead worker's journal segments -> the killed group serves again and
    EVERY acked submit — including ones acked moments before the kill —
    reads back through the front end.  The multi-process analog of
    wedged-shard: process death instead of a wedged fsync, standby
    adoption instead of in-place recovery."""
    import signal as _signal

    from cook_tpu.mp.supervisor import MpRuntime

    steps = []
    n_groups = 2
    victim = 0
    runtime = MpRuntime(n_groups=n_groups, standbys=1, poll_s=0.3)
    acked: dict[str, list] = {}
    try:
        pools = [p for p in runtime.pools if p != "default"]
        url = runtime.url

        def submit(pool: str, uuid: str, timeout: float = 10.0) -> int:
            status, _ = _post(f"{url}/jobs", {"jobs": [{
                "uuid": uuid, "command": "true", "mem": 64,
                "cpus": 0.1, "pool": pool}]}, timeout=timeout)
            if status == 201:
                acked.setdefault(pool, []).append(uuid)
            return status

        # baseline: both groups acking
        for i in range(4):
            for pool in pools:
                _check(submit(pool, f"kw-{pool}-{i:02d}") == 201,
                       f"baseline submit to {pool} failed")
        victim_pool, healthy_pool = pools[victim], pools[1 - victim]
        baseline = sum(len(v) for v in acked.values())
        steps.append(f"baseline: {baseline} submits acked across "
                     f"{n_groups} worker processes")

        runtime.supervisor.kill_worker(victim, _signal.SIGKILL)

        # blast radius: the healthy group keeps acking while the
        # victim's keys fail (fast 5xx via breaker/dead-map, or a
        # transport error) until the standby adopts
        degraded = False
        for i in range(20):
            _check(submit(healthy_pool, f"kw-live-{i:02d}",
                          timeout=5.0) == 201,
                   f"healthy group stopped acking after the kill "
                   f"(submit {i})")
            status = submit(victim_pool, f"kw-dead-{i:02d}",
                            timeout=3.0)
            if status != 201:
                degraded = True
            time.sleep(0.05)
        _check(degraded, "killing a worker degraded nothing — the "
                         "drill saw no blast radius at all")
        steps.append(f"SIGKILL group {victim}: only pool "
                     f"{victim_pool!r} degraded; {healthy_pool!r} "
                     f"acked every submit throughout")

        # supervisor: standby adopts the dead worker's segments
        def adopted():
            _, _, shards = _get(f"{url}/debug/shards")
            groups = shards.get("groups", [])
            return (shards.get("map_seq", 0) >= 3
                    and all(e["alive"] for e in groups) and shards)
        shards = _wait_until(adopted, timeout_s=60.0,
                             what="standby adoption in the route map")
        steps.append(f"standby adopted group {victim}'s journal "
                     f"segments (map_seq {shards['map_seq']})")

        # diagnosis: the fleet poller saw the victim's ok->degraded edge
        # and captured a FEDERATED incident through the front end's
        # recorder — one bundle embedding the 2PC decision-log tail, the
        # breaker states, and the route map (obs/distributed.py)
        def federated_bundle():
            status, _, index = _get(f"{url}/debug/incidents")
            if status != 200:
                return None
            fed = [b for b in index.get("incidents", [])
                   if b.get("trigger") == "fleet-peer"]
            return fed[-1] if fed else None
        summary = _wait_until(federated_bundle, timeout_s=30.0,
                              interval_s=0.3,
                              what="a fleet-peer incident bundle at "
                                   "the front end")
        status, _, bundle = _get(f"{url}/debug/incidents/{summary['id']}")
        _check(status == 200,
               f"federated bundle {summary['id']} not served by id")
        for evidence in ("decision_log", "breakers", "route_map"):
            _check(isinstance(bundle.get(evidence), dict)
                   and "error" not in bundle[evidence],
                   f"federated bundle missing {evidence} evidence: "
                   f"{bundle.get(evidence)}")
        _check(bundle["decision_log"].get("records") is not None,
               "decision_log evidence carries no records field")
        _check(bundle["route_map"].get("groups"),
               "route_map evidence carries no groups")
        steps.append(f"diagnosis: federated incident {summary['id']} "
                     f"(trigger fleet-peer) embeds decision-log tail, "
                     f"breaker states, and the route map")

        # recovery: the victim pool acks again...
        def victim_acks():
            return submit(victim_pool, f"kw-post-{int(time.monotonic()*1e3)%100000}",
                          timeout=5.0) == 201
        _wait_until(victim_acks, timeout_s=30.0, interval_s=0.3,
                    what="the adopted group to ack submits")
        # ...and NO acked txn was lost: every 201 ever returned reads
        # back through the front end, including pre-kill acks whose
        # only durable copy was the dead worker's journal segment
        missing = []
        for pool, uuids in acked.items():
            for uuid in uuids:
                status, _, _ = _get(f"{url}/jobs/{uuid}")
                if status != 200:
                    missing.append(uuid)
        _check(not missing,
               f"acked submits lost across worker death: {missing}")
        total = sum(len(v) for v in acked.values())
        steps.append(f"recovery: adopted group acks; all {total} acked "
                     f"submits (both groups) read back — no acked txn "
                     f"lost")
        return steps
    finally:
        runtime.stop()


SCENARIOS = {
    "fsync-stall-sheds": scenario_fsync_stall_sheds,
    "launch-breaker": scenario_launch_breaker,
    "device-fallback": scenario_device_fallback,
    "fsync-degrade": scenario_fsync_degrade,
    "replication-lag": scenario_replication_lag,
    "failover-fsync": scenario_failover_fsync,
    "wedged-shard": scenario_wedged_shard,
    "killed-worker": scenario_killed_worker,
}

# the fast set ci_checks runs on every build (the original trio plus
# the sharded control plane's blast-radius drill and the mp runtime's
# worker-death drill)
SMOKE = ("fsync-stall-sheds", "launch-breaker", "device-fallback",
         "wedged-shard", "killed-worker")


def run_scenario(name: str) -> ScenarioResult:
    from cook_tpu import faults

    fn = SCENARIOS[name]
    t0 = time.monotonic()
    try:
        steps = fn()
        return ScenarioResult(name=name, passed=True,
                              seconds=time.monotonic() - t0, steps=steps)
    except Exception as e:  # noqa: BLE001 — a scenario failure is data
        return ScenarioResult(name=name, passed=False,
                              seconds=time.monotonic() - t0,
                              error=f"{type(e).__name__}: {e}")
    finally:
        faults.disarm()  # never leak an armed schedule across scenarios


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="fault-injection chaos scenarios with recovery "
                    "invariants")
    parser.add_argument("--smoke", action="store_true",
                        help=f"run the fast CI trio: {', '.join(SMOKE)}")
    parser.add_argument("--scenario", action="append", default=[],
                        help="run one scenario by name (repeatable)")
    parser.add_argument("--list", action="store_true")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable results on stdout")
    args = parser.parse_args(argv)

    if args.list:
        for name in SCENARIOS:
            tag = " [smoke]" if name in SMOKE else ""
            print(f"{name}{tag}")
        return 0
    if args.scenario:
        unknown = [s for s in args.scenario if s not in SCENARIOS]
        if unknown:
            print(f"chaos: unknown scenario(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        selected = args.scenario
    elif args.smoke:
        selected = list(SMOKE)
    else:
        selected = list(SCENARIOS)

    results = []
    for name in selected:
        print(f"chaos: === {name} ===", flush=True)
        result = run_scenario(name)
        results.append(result)
        if result.passed:
            for step in result.steps:
                print(f"chaos:   - {step}")
            print(f"chaos: {name}: PASS ({result.seconds:.1f}s)",
                  flush=True)
        else:
            print(f"chaos: {name}: FAIL ({result.error})", flush=True)
    if args.json:
        print(json.dumps([r.to_dict() for r in results], indent=1))
    failed = [r.name for r in results if not r.passed]
    if failed:
        print(f"chaos: FAILED: {', '.join(failed)}")
        return 1
    print(f"chaos: all {len(results)} scenarios passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
