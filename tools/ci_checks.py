#!/usr/bin/env python
"""One entry point for the repo's standing checks.

Builders and CI previously ran three commands by hand — the static
metrics/tracing lint, the smoke bench tier, and the bench regression
gate — each with its own invocation and exit-code convention.  This
wrapper runs them as one pipeline with one verdict:

  1. `tools/lint_metrics.py`   — metric/span registration lint + the
     docs/observability.md catalog drift check, BOTH directions: a
     registered metric missing from the catalog fails, and a catalog
     row whose family is no longer registered anywhere fails
     (`family.*` wildcards honored);
  2. `python bench.py --smoke` — the tiny bench tier:
     match/dru/rebalance/elastic solves, the `match_xl` hierarchical
     two-level solve (coarse/fine/refine phases, the 100k x 10k tier's
     smoke variant), the pipelined-vs-serial match-cycle comparison,
     the `speculation` phase (prediction-assisted speculative-cycle
     A/B on the completion-heavy trace: cycle-start-to-first-launch
     p50 + fraction of cycles served from speculation),
     the `gang` phase (topology-aware gang scheduling on the seeded
     gang/topology trace: gated p50 is the gang admission latency —
     submit to all-members-running, in VIRTUAL ms so the figure is
     deterministic — with the placed fraction, assembled share, and
     mean block spread recorded alongside),
     the `match_resident` tier (device-resident match state: one cold
     rebuild + three warm delta cycles; the warm phase's p50 AND its
     h2d_bytes column are gate-enforced — warm-cycle byte growth is a
     regression, not informational),
     AND the `control_plane` phase — the loadtest (`tools/loadtest.py`,
     serial closed-loop so the gated p50 is commit SERVICE time, not
     same-process queueing jitter) against an in-process control plane,
     so commit-ack p50/p99 is measured every CI run,
     AND the `control_plane_sharded` phase — the same seeded trace
     against a 4-shard partitioned plane (cook_tpu/shard/) at
     concurrency, with a concurrency-matched single-shard baseline
     recorded alongside (`single_shard` / `rps_speedup_vs_single`) so
     the sharded-vs-single comparison is measured every run,
     AND the `control_plane_mp` phase — the same trace through the
     MULTI-PROCESS fleet (cook_tpu/mp/: shard-group worker processes
     behind the forwarding front end, 2PC in the measured path), with
     `rps_speedup_vs_sharded` against the in-process sharded phase and
     a `cores` stamp recorded alongside (the speedup claim only means
     anything with >= as many cores as workers); the gate
     enforces the sharded run's commit-ack p50 round over round (writes
     BENCH_rsmoke.json, rotating the previous record to
     BENCH_rsmoke_prev.json so step 3 has a pair to diff);
  3. `tools/bench_gate.py`     — phase-by-phase regression gate over
     the latest comparable record pair (commit-ack p50 and the
     match_xl phases included), refusing pairs whose resolved JAX
     backend differs (a CPU-fallback record never gates an
     accelerator record);
  4. `tools/chaos.py --smoke`  — the fast chaos set (fsync stall ->
     shed, launch failures -> breaker, device error -> CPU fallback,
     wedged shard -> single-shard blast radius + mid-drill failover,
     killed worker -> SIGKILL one shard-group process mid-traffic:
     only its keys degrade, a standby adopts its journal segments, no
     acked txn lost):
     each scenario injects its fault, observes the /debug/health reason
     AND the automatic reaction, then asserts full recovery invariants
     (docs/resilience.md);
  5. `tools/debug_smoke.py`    — boots a full-stack node and GETs every
     /debug/* endpoint (plus /jobs/{uuid}/timeline), asserting 200 +
     parseable JSON — catches schema-breaking regressions no
     per-handler unit test sees.  `/debug/history` must serve a
     NON-EMPTY series index after the rig's forced sample ticks, and
     `/debug/fleet` must render the merged verdict (self row) through
     the rig's zero-peer fleet observatory.

    python tools/ci_checks.py [--root DIR] [--threshold 0.2]
                              [--skip-bench]

`--skip-bench` runs the lint only (for docs-only changes / machines
without a working accelerator stack).  Exit code: 0 when every step
passed, 1 when any failed; each step's verdict is printed either way
(a later failure never masks an earlier one).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TOOLS_DIR)


def run_lint(root: str) -> int:
    import lint_metrics  # sibling script (tools/ is not a package)

    return lint_metrics.main([root])


def run_smoke_bench(root: str) -> int:
    """Smoke bench in a SUBPROCESS: bench.py initializes jax, and a
    wedged accelerator plugin must kill the step's budget, not this
    process (the same isolation bench.py's own probe uses).  The smoke
    tier includes the pipelined-vs-serial match-cycle phases AND the
    control_plane loadtest phase by default, so bench_gate diffs
    pipeline walls and commit-ack latency run to run.

    The written record must carry the match_xxl superblock phases (with
    their per-level walls) and the resident-mirror tiers: a smoke run
    that silently dropped them would also drop the gated byte columns,
    and bench_gate would read the NEXT regression as a baseline."""
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--smoke"],
        cwd=root,
        timeout=float(os.environ.get("CI_SMOKE_TIMEOUT_S", "600")),
    )
    if proc.returncode != 0:
        return proc.returncode
    import json

    try:
        with open(os.path.join(root, "BENCH_rsmoke.json")) as f:
            phases = json.load(f).get("phases", {})
    except (OSError, ValueError) as e:
        print(f"ci_checks: smoke record unreadable: {e}", file=sys.stderr)
        return 1
    required = ("match_xxl", "match_xxl_super_coarse", "match_xxl_coarse",
                "match_xxl_fine", "match_xxl_refine",
                "rebalance_resident", "elastic_resident")
    missing = [p for p in required if p not in phases]
    if missing:
        print(f"ci_checks: smoke record missing phases: {missing}",
              file=sys.stderr)
        return 1
    return 0


def run_bench_gate(root: str, threshold: float) -> int:
    import bench_gate  # sibling script (tools/ is not a package)

    return bench_gate.main(["--dir", root, "--threshold", str(threshold)])


def run_chaos_smoke(root: str) -> int:
    """Chaos smoke in a SUBPROCESS (same isolation rationale as the
    bench: scenarios initialize jax and arm the process-global fault
    plane — neither belongs in this process)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "chaos.py"),
         "--smoke"],
        cwd=root,
        timeout=float(os.environ.get("CI_CHAOS_TIMEOUT_S", "300")),
    )
    return proc.returncode


def run_debug_smoke(root: str) -> int:
    """Debug-surface smoke in a SUBPROCESS (boots a full scheduler, which
    initializes jax): GET every /debug/* endpoint of a live node and
    assert 200 + parseable JSON — the schema-regression tripwire no
    per-handler unit test provides."""
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "debug_smoke.py")],
        cwd=root,
        timeout=float(os.environ.get("CI_DEBUG_SMOKE_TIMEOUT_S", "180")),
    )
    return proc.returncode


def main(argv: list[str] | None = None, *,
         steps: dict | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="run the repo's standing checks as one pipeline")
    parser.add_argument("--root", default=REPO_ROOT)
    parser.add_argument("--threshold", type=float, default=0.2,
                        help="bench-gate max tolerated slowdown")
    parser.add_argument("--skip-bench", action="store_true",
                        help="lint only (no smoke bench, no gate)")
    args = parser.parse_args(argv)

    # injectable steps so the orchestration is testable without paying
    # a real bench run (tests/test_ci_checks.py)
    steps = steps or {
        "lint_metrics": lambda: run_lint(args.root),
        "smoke_bench": lambda: run_smoke_bench(args.root),
        "bench_gate": lambda: run_bench_gate(args.root, args.threshold),
        "chaos_smoke": lambda: run_chaos_smoke(args.root),
        "debug_smoke": lambda: run_debug_smoke(args.root),
    }
    selected = (["lint_metrics"] if args.skip_bench
                else ["lint_metrics", "smoke_bench", "bench_gate",
                      "chaos_smoke", "debug_smoke"])

    failures = []
    for name in selected:
        print(f"ci_checks: === {name} ===", flush=True)
        try:
            code = steps[name]()
        except Exception as e:  # noqa: BLE001 — report, keep checking
            print(f"ci_checks: {name} raised {type(e).__name__}: {e}",
                  file=sys.stderr)
            code = 1
        status = "PASS" if code == 0 else f"FAIL (exit {code})"
        print(f"ci_checks: {name}: {status}", flush=True)
        if code != 0:
            failures.append(name)
    if failures:
        print(f"ci_checks: FAILED: {', '.join(failures)}")
        return 1
    print("ci_checks: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
