#!/usr/bin/env python
"""Bench trajectory table: every BENCH_r*.json round at a glance.

The repo accumulates one structured bench record per round
(BENCH_r{NN}_phases.json, BENCH_rsmoke.json) plus the driver's wrapper
artifacts, but nothing rendered the TRAJECTORY — which rounds ran on
which backend, how each phase's p50 moved, and (since the data-plane
observatory) how many bytes each phase pushes across the host<->device
boundary.  `tools/bench_gate.py` judges the newest pair; this tool
prints the whole history as one compact aligned table:

    round              mode   backend  phase         p50_ms  h2d_bytes  d2h_bytes
    BENCH_r01.json     full   cpu      match        16234.0          -          -
    ...

Byte columns render `-` for records predating the ledger; the backend
stamp makes CPU-fallback rounds legible in the same view (all five
seed rounds are exactly that).  See docs/operations.md for the
reporting recipe.

    python tools/bench_history.py [--dir ROOT] [--phases match,match_xl]
                                  [--markdown] [files...]
"""
from __future__ import annotations

import argparse
import glob
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from bench_gate import _round_key, collect_records  # noqa: E402

COLUMNS = ("round", "mode", "backend", "cores", "phase", "p50_ms",
           "levels", "h2d_bytes", "d2h_bytes", "vs_cold")

# hierarchical per-level wall phases folded into the parent row's
# `levels` column (short labels keep the table scannable)
LEVEL_SUFFIXES = (("_super_coarse", "sc"), ("_coarse", "co"),
                  ("_fine", "fi"), ("_refine", "re"))


def _level_split(record: dict, name: str) -> str:
    """The parent tier's per-level wall split: `sc 64/co 207/fi 127/
    re 1552` when the record carries `<name>_super_coarse` etc. sibling
    phases (the match_xl / match_xxl hierarchical tiers)."""
    parts = []
    for suffix, label in LEVEL_SUFFIXES:
        sub = record["phases"].get(name + suffix)
        if sub and "p50_ms" in sub:
            parts.append(f"{label} {sub['p50_ms']:.0f}")
    return "/".join(parts) if parts else "-"


def history_rows(records: list[dict],
                 phases: list[str] | None = None) -> list[dict]:
    """One row per (record, phase), record order preserved (callers pass
    round-sorted records).  `phases` filters; None keeps everything.

    The residency warm/cold split: a record carrying both a `<name>`
    and `<name>_cold` phase (the match_resident tier) gets a `vs_cold`
    column on the warm row — warm-cycle H2D as a fraction of the cold
    rebuild's, the transfer cliff device residency exists to create.

    Hierarchical tiers (match_xl, match_xxl) get a `levels` column on
    the parent row: per-level solve walls from the sibling `_coarse` /
    `_super_coarse` / `_fine` / `_refine` phases — so a CPU-fallback
    1M x 100k round reads at a glance which level dominates.  The
    `cores` column echoes the phase's cores stamp (match_xxl and
    control_plane_mp record one): a backend=cpu wall only means
    something next to the core count it ran on."""
    rows = []
    for record in records:
        for name, info in sorted(record["phases"].items()):
            if phases and name not in phases:
                continue
            vs_cold = "-"
            cold = record["phases"].get(name + "_cold")
            if (cold and cold.get("h2d_bytes") and "h2d_bytes" in info
                    and "warm_cycles" in info):
                per_warm = info["h2d_bytes"] / max(info["warm_cycles"], 1)
                vs_cold = f"{per_warm / cold['h2d_bytes']:.1%}"
            rows.append({
                "round": os.path.basename(record["path"]),
                "mode": record["mode"],
                # phase-level stamp wins (one phase can be measured on a
                # different backend than the record's resolved one)
                "backend": (info.get("backend") or record.get("backend")
                            or "?"),
                "cores": (str(info["cores"])
                          if "cores" in info else "-"),
                "phase": name,
                "p50_ms": f"{info['p50_ms']:.1f}",
                "levels": _level_split(record, name),
                "h2d_bytes": (str(info["h2d_bytes"])
                              if "h2d_bytes" in info else "-"),
                "d2h_bytes": (str(info["d2h_bytes"])
                              if "d2h_bytes" in info else "-"),
                "vs_cold": vs_cold,
            })
    return rows


def render_table(rows: list[dict], markdown: bool = False) -> str:
    if not rows:
        return "bench_history: no structured bench records found"
    widths = {col: max(len(col), *(len(r[col]) for r in rows))
              for col in COLUMNS}
    if markdown:
        lines = ["| " + " | ".join(COLUMNS) + " |",
                 "|" + "|".join("---" for _ in COLUMNS) + "|"]
        lines += ["| " + " | ".join(r[col] for col in COLUMNS) + " |"
                  for r in rows]
        return "\n".join(lines)
    lines = ["  ".join(col.ljust(widths[col]) for col in COLUMNS)]
    for r in rows:
        lines.append("  ".join(r[col].ljust(widths[col])
                               for col in COLUMNS))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="print the bench-record trajectory as one table")
    parser.add_argument("files", nargs="*",
                        help="explicit record paths (oldest first); "
                             "default: BENCH_r*.json in --dir")
    parser.add_argument("--dir", default=os.path.dirname(_TOOLS))
    parser.add_argument("--phases", default="",
                        help="comma-separated phase filter "
                             "(default: every phase)")
    parser.add_argument("--markdown", action="store_true",
                        help="emit a markdown table (paste into docs/"
                             "status reports)")
    args = parser.parse_args(argv)
    paths = args.files or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_r*.json")),
        key=lambda p: (_round_key(p), os.path.getmtime(p)))
    phases = [p.strip() for p in args.phases.split(",") if p.strip()] \
        or None
    rows = history_rows(collect_records(paths), phases)
    print(render_table(rows, markdown=args.markdown))
    return 0


if __name__ == "__main__":
    sys.exit(main())
