#!/bin/bash
# Probe the accelerator until it answers, then run the tuning sweeps and a
# fresh bench log.  The tunnel wedges when a client dies mid-session and
# the chip grant is held server-side; it recovers asynchronously.  Probe in
# a subprocess (in-process jax.devices() hangs unkillably), stagger 7 min
# apart.  Sweeps resume: configs already in the out file are skipped, so a
# mid-sweep wedge just sends us back to the probe loop to finish later.
cd "$(dirname "$0")/.."
OUT=${SWEEP_OUT:-tpu_sweep_r4.jsonl}
# promote the best measured config after EVERY successful sweep (not only
# once all three finish): a wedge or deadline after sweep N must not strand
# sweep N's fresh measurements un-promoted (0.995 bar: keep a margin above
# the 0.99 parity target rather than sitting on it)
promote() { python tools/pick_tuned.py --sweep "$OUT" --min-eff 0.995 || true; }
# hard deadline (default 6h): the driver runs bench.py itself at round
# end — a still-looping watcher would race it for the single chip grant,
# which is exactly how the tunnel wedges.  Every step's timeout is capped
# at the time remaining so nothing overruns the deadline.
DEADLINE=$(( $(date +%s) + ${WATCH_MAX_S:-21600} ))
left() { echo $(( DEADLINE - $(date +%s) )); }
while true; do
  if [ "$(left)" -le 0 ]; then
    echo "$(date +%H:%M:%S) deadline reached — exiting"
    exit 0
  fi
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    [ "$(left)" -le 0 ] && continue
    # order: bucketed and pallas first — they have zero TPU measurements
    # and are the identified levers for the <200 ms target; the partially
    # complete xla grid resumes last
    echo "$(date +%H:%M:%S) device healthy — bucketed sweep"
    timeout $(( $(left) > 5400 ? 5400 : ($(left) > 1 ? $(left) : 1) )) \
      python tools/tpu_sweep.py --out "$OUT" --repeats 3 --backend bucketed
    rc=$?
    echo "$(date +%H:%M:%S) bucketed sweep rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    promote
    [ "$(left)" -le 0 ] && continue
    timeout $(( $(left) > 5400 ? 5400 : ($(left) > 1 ? $(left) : 1) )) \
      python tools/tpu_sweep.py --out "$OUT" --repeats 3 --backend pallas
    rc=$?
    echo "$(date +%H:%M:%S) pallas sweep rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    promote
    [ "$(left)" -le 0 ] && continue
    timeout $(( $(left) > 5400 ? 5400 : ($(left) > 1 ? $(left) : 1) )) \
      python tools/tpu_sweep.py --out "$OUT" --repeats 3
    rc=$?
    echo "$(date +%H:%M:%S) xla sweep rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    promote
    [ "$(left)" -le 0 ] && continue
    timeout $(( $(left) > 1800 ? 1800 : ($(left) > 1 ? $(left) : 1) )) \
      python bench.py > bench_tpu_latest.json.tmp 2> bench_tpu_latest.log.tmp
    rc=$?
    echo "$(date +%H:%M:%S) bench rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    # only replace the last good results on success — a wedge mid-bench
    # must not truncate them
    mv bench_tpu_latest.json.tmp bench_tpu_latest.json
    mv bench_tpu_latest.log.tmp bench_tpu_latest.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) device unreachable; retrying in 7 min"
  sleep 420
done
