#!/bin/bash
# Probe the accelerator until it answers, then run the tuning sweep.
# The tunnel wedges when a client dies mid-session and the chip grant is
# held server-side; it recovers asynchronously.  Probe in a subprocess
# (in-process jax.devices() hangs unkillably), stagger 7 min apart.
cd "$(dirname "$0")/.."
while true; do
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) device healthy — starting sweep"
    timeout 5400 python tools/tpu_sweep.py --out tpu_sweep.jsonl --repeats 3
    rc=$?
    echo "$(date +%H:%M:%S) sweep done rc=$rc"
    exit $rc
  fi
  echo "$(date +%H:%M:%S) device unreachable; retrying in 7 min"
  sleep 420
done
