#!/bin/bash
# Probe the accelerator until it answers, then run the tuning sweeps and a
# fresh bench log.  The tunnel wedges when a client dies mid-session and
# the chip grant is held server-side; it recovers asynchronously.  Probe in
# a subprocess (in-process jax.devices() hangs unkillably), stagger 7 min
# apart.  Sweeps resume: configs already in the out file are skipped, so a
# mid-sweep wedge just sends us back to the probe loop to finish later.
cd "$(dirname "$0")/.."
OUT=${SWEEP_OUT:-tpu_sweep_r2.jsonl}
while true; do
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) device healthy — xla sweep"
    timeout 5400 python tools/tpu_sweep.py --out "$OUT" --repeats 3
    rc=$?
    echo "$(date +%H:%M:%S) xla sweep rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    timeout 5400 python tools/tpu_sweep.py --out "$OUT" --repeats 3 --pallas
    rc=$?
    echo "$(date +%H:%M:%S) pallas sweep rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    timeout 1800 python bench.py > bench_tpu_latest.json.tmp 2> bench_tpu_latest.log.tmp
    rc=$?
    echo "$(date +%H:%M:%S) bench rc=$rc"
    if [ $rc -ne 0 ]; then sleep 420; continue; fi
    # only replace the last good results on success — a wedge mid-bench
    # must not truncate them
    mv bench_tpu_latest.json.tmp bench_tpu_latest.json
    mv bench_tpu_latest.log.tmp bench_tpu_latest.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) device unreachable; retrying in 7 min"
  sleep 420
done
