"""The job/instance state machine as pure transition functions.

Reference semantics: the `:instance/update-state` / `:job/update-state` /
`:job/allowed-to-start?` Datomic db-fns
(/root/reference/scheduler/src/cook/schema.clj:1112-1413).  Those run inside
the Datomic transactor to get atomicity; here they are pure functions applied
under the store's transaction lock (`cook_tpu.models.store`), which gives the
same serializability with far less machinery.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from cook_tpu.models import reasons as reasons_mod
from cook_tpu.models.entities import Instance, InstanceStatus, Job, JobState

# Valid instance status transitions (schema.clj:1259-1264).
INSTANCE_TRANSITIONS: dict[InstanceStatus, frozenset[InstanceStatus]] = {
    InstanceStatus.UNKNOWN: frozenset(
        {InstanceStatus.RUNNING, InstanceStatus.FAILED, InstanceStatus.SUCCESS}
    ),
    InstanceStatus.RUNNING: frozenset({InstanceStatus.FAILED, InstanceStatus.SUCCESS}),
    InstanceStatus.SUCCESS: frozenset(),
    InstanceStatus.FAILED: frozenset(),
}


def valid_instance_transition(old: InstanceStatus, new: InstanceStatus) -> bool:
    return new in INSTANCE_TRANSITIONS[old]


def attempts_consumed(
    job: Job,
    instances: Sequence[Instance],
    *,
    mea_culpa_limit: int = reasons_mod.DEFAULT_MEA_CULPA_FAILURE_LIMIT,
) -> int:
    """Retry attempts the job has used: one per terminal instance, except
    mea-culpa failures under their limit (schema.clj:1175-1191)."""
    codes = [
        inst.reason_code
        for inst in instances
        if inst.status.terminal
    ]
    return reasons_mod.attempts_consumed_by_reasons(
        codes,
        mea_culpa_limit=mea_culpa_limit,
        disable_mea_culpa_retries=job.disable_mea_culpa_retries,
    )


def all_attempts_consumed(
    job: Job,
    instances: Sequence[Instance],
    *,
    mea_culpa_limit: int = reasons_mod.DEFAULT_MEA_CULPA_FAILURE_LIMIT,
) -> bool:
    return job.max_retries <= attempts_consumed(
        job, instances, mea_culpa_limit=mea_culpa_limit
    )


def derive_job_state(
    job: Job,
    instance_statuses: Sequence[InstanceStatus],
    exhausted: bool,
) -> JobState:
    """Job-state derivation given its instances' statuses
    (schema.clj:1294-1310):

    - completed stays completed (terminal)
    - any success, or all failed with retries exhausted -> completed
    - any running/unknown -> running
    - otherwise -> waiting
    """
    if job.state == JobState.COMPLETED:
        return JobState.COMPLETED
    statuses = list(instance_statuses)
    any_success = any(s == InstanceStatus.SUCCESS for s in statuses)
    any_live = any(
        s in (InstanceStatus.RUNNING, InstanceStatus.UNKNOWN) for s in statuses
    )
    all_failed = bool(statuses) and all(s == InstanceStatus.FAILED for s in statuses)
    if any_success or (all_failed and exhausted):
        return JobState.COMPLETED
    if any_live:
        return JobState.RUNNING
    return JobState.WAITING


@dataclass(frozen=True)
class StateUpdate:
    """Result of applying `update_instance_state`."""

    applied: bool
    new_instance_status: Optional[InstanceStatus] = None
    new_job_state: Optional[JobState] = None
    job_newly_waiting: bool = False  # job (re)entered WAITING -> stamp time


def update_instance_state(
    job: Job,
    instances: Sequence[Instance],
    task_id: str,
    new_status: InstanceStatus,
    reason_code: Optional[int],
    *,
    mea_culpa_limit: int = reasons_mod.DEFAULT_MEA_CULPA_FAILURE_LIMIT,
) -> StateUpdate:
    """The `:instance/update-state` transition (schema.clj:1240-1310), pure.

    Validates the instance transition; if valid, computes the new job state
    considering all sibling instances with this instance at its new status.
    Returns `applied=False` for invalid transitions (they are silently
    ignored, as in the reference).
    """
    by_id = {inst.task_id: inst for inst in instances}
    inst = by_id.get(task_id)
    if inst is None or not valid_instance_transition(inst.status, new_status):
        return StateUpdate(applied=False)

    updated = inst.with_(status=new_status, reason_code=reason_code)
    siblings = [updated if i.task_id == task_id else i for i in instances]
    exhausted = all_attempts_consumed(
        job, siblings, mea_culpa_limit=mea_culpa_limit
    )
    new_job_state = derive_job_state(
        job, [i.status for i in siblings], exhausted
    )
    return StateUpdate(
        applied=True,
        new_instance_status=new_status,
        new_job_state=new_job_state,
        job_newly_waiting=(
            new_job_state == JobState.WAITING and job.state != JobState.WAITING
        ),
    )


class JobNotAllowedToStart(Exception):
    """Raised to veto a launch transaction (reference:
    `:job/allowed-to-start?`, schema.clj:1311-1330)."""


def check_allowed_to_start(job: Job, instances: Sequence[Instance]) -> None:
    """A job may only start if it is WAITING and has no live instances."""
    if job.state != JobState.WAITING:
        raise JobNotAllowedToStart(
            f"job {job.uuid} is {job.state.value}, not waiting"
        )
    live = [
        i.task_id
        for i in instances
        if i.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING)
    ]
    if live:
        raise JobNotAllowedToStart(
            f"job {job.uuid} has live instances: {live}"
        )


def retry_job_state(
    job: Job,
    instances: Sequence[Instance],
    new_max_retries: int,
    *,
    mea_culpa_limit: int = reasons_mod.DEFAULT_MEA_CULPA_FAILURE_LIMIT,
) -> JobState:
    """`:job/update-state-on-retry` (schema.clj:1370-1385): a completed job
    with retries remaining under the new budget goes back to WAITING."""
    consumed = attempts_consumed(job, instances, mea_culpa_limit=mea_culpa_limit)
    if consumed > new_max_retries:
        raise ValueError(
            f"cannot set retries to {new_max_retries}: {consumed} already consumed"
        )
    if job.state == JobState.COMPLETED and consumed < new_max_retries:
        # Only a failed-complete job can be revived; a successful job stays done.
        if not any(i.status == InstanceStatus.SUCCESS for i in instances):
            return JobState.WAITING
    return job.state
