"""Domain entities: jobs, instances, groups, pools, resources.

Mirrors the capability surface of the reference's Datomic schema
(`/root/reference/scheduler/src/cook/schema.clj:20-966`) as plain Python
dataclasses.  State lives in an event-sourced store (`cook_tpu.models.store`);
these objects are the *values* it holds, and all state transitions go through
the pure functions in `cook_tpu.models.state`.
"""
from __future__ import annotations

import dataclasses
import enum
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Any, Optional


class JobState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    COMPLETED = "completed"


class InstanceStatus(enum.Enum):
    UNKNOWN = "unknown"  # launched, not yet confirmed running
    RUNNING = "running"
    SUCCESS = "success"
    FAILED = "failed"

    @property
    def terminal(self) -> bool:
        return self in (InstanceStatus.SUCCESS, InstanceStatus.FAILED)


class DruMode(enum.Enum):
    """Per-pool fairness mode (reference: `:pool.dru-mode/default|gpu`)."""

    DEFAULT = "default"  # dominant of mem/cpu
    GPU = "gpu"          # cumulative gpu share


@dataclass(frozen=True)
class Resources:
    """A resource vector.  `mem` is MB, `cpus`/`gpus` are counts.

    Reference: resource attributes in schema.clj (`:resource/type` etc.).
    """

    mem: float = 0.0
    cpus: float = 0.0
    gpus: float = 0.0
    disk: float = 0.0
    ports: int = 0
    # requested disk type ("" = any); a typed request only matches hosts
    # advertising that type (disk-host-constraint, constraints.clj:164)
    disk_type: str = ""

    def __add__(self, other: "Resources") -> "Resources":
        return Resources(
            mem=self.mem + other.mem,
            cpus=self.cpus + other.cpus,
            gpus=self.gpus + other.gpus,
            disk=self.disk + other.disk,
            ports=self.ports + other.ports,
        )

    def __sub__(self, other: "Resources") -> "Resources":
        return Resources(
            mem=self.mem - other.mem,
            cpus=self.cpus - other.cpus,
            gpus=self.gpus - other.gpus,
            disk=self.disk - other.disk,
            ports=self.ports - other.ports,
        )

    def fits_within(self, other: "Resources") -> bool:
        return (
            self.mem <= other.mem
            and self.cpus <= other.cpus
            and self.gpus <= other.gpus
            and self.disk <= other.disk
            and self.ports <= other.ports
        )

    def to_dict(self) -> dict:
        return {"mem": self.mem, "cpus": self.cpus, "gpus": self.gpus,
                "disk": self.disk, "ports": self.ports}


@dataclass(frozen=True)
class Application:
    """Client application metadata (reference: `:job/application`)."""

    name: str = ""
    version: str = ""
    workload_class: str = ""
    workload_id: str = ""


@dataclass(frozen=True)
class Container:
    """Container spec (reference: container attributes in schema.clj)."""

    image: str = ""
    kind: str = "docker"
    volumes: tuple = ()
    ports: tuple = ()
    env: tuple = ()  # ((k, v), ...)


@dataclass(frozen=True)
class Checkpoint:
    """Job checkpointing config (reference: `:job/checkpoint`, schema.clj:84)."""

    mode: str = ""  # "auto" | "periodic" | "preemption"
    periodic_sec: int = 0
    preserve_paths: tuple = ()
    location: str = ""  # where the last checkpoint was written (locality hint)


class GroupPlacementType(enum.Enum):
    """Group host-placement constraint types (reference: `docs/groups.md`,
    constraints.clj:568-660)."""

    ALL = "all"                # no constraint
    UNIQUE = "unique"          # each member on a distinct host
    BALANCED = "balanced"      # spread across attribute values, max skew
    ATTRIBUTE_EQUALS = "attribute-equals"  # all members share an attribute value


@dataclass(frozen=True)
class HostPlacement:
    type: GroupPlacementType = GroupPlacementType.ALL
    attribute: str = ""
    minimum: int = 0  # for BALANCED: min distinct attr values to spread over


@dataclass(frozen=True)
class StragglerHandling:
    """Group straggler handling (reference: `docs/groups.md`)."""

    type: str = "none"  # "none" | "quantile-deviation"
    quantile: float = 0.5
    multiplier: float = 2.0


@dataclass(frozen=True)
class Group:
    uuid: str
    name: str = "defaultgroup"
    host_placement: HostPlacement = field(default_factory=HostPlacement)
    straggler_handling: StragglerHandling = field(default_factory=StragglerHandling)
    job_uuids: tuple = ()


class ConstraintOperator(enum.Enum):
    """User-specified job constraint operators
    (reference: constraints.clj:356-430 `build-constraint`)."""

    EQUALS = "EQUALS"


@dataclass(frozen=True)
class JobConstraint:
    attribute: str
    operator: ConstraintOperator
    pattern: str


@dataclass(frozen=True)
class Job:
    """An immutable job description + its mutable scheduling state.

    Reference: job attributes, schema.clj (`:job/...`).
    """

    uuid: str
    user: str
    command: str = ""
    name: str = "cookjob"
    priority: int = 50
    max_retries: int = 1
    max_runtime_ms: int = 2**62
    expected_runtime_ms: int = 0
    resources: Resources = field(default_factory=lambda: Resources(mem=128.0, cpus=1.0))
    pool: str = ""
    state: JobState = JobState.WAITING
    submit_time_ms: int = 0
    user_provided_env: tuple = ()
    labels: tuple = ()
    constraints: tuple = ()  # tuple[JobConstraint]
    group_uuid: Optional[str] = None
    # gang scheduling (ROADMAP item 3): k > 0 marks this job one member
    # of a k-host gang — all members share `group_uuid` and must place
    # together inside ONE topology block or not at all (the matcher's
    # all-or-nothing rule; scheduler/gang.py).  0 = not a gang member.
    gang_size: int = 0
    container: Optional[Container] = None
    application: Optional[Application] = None
    checkpoint: Optional[Checkpoint] = None
    disable_mea_culpa_retries: bool = False
    instance_ids: tuple = ()  # ordered instance uuids
    custom_executor: bool = False
    last_waiting_start_time_ms: int = 0
    last_fenzo_placement_failure: str = ""  # json blob for /unscheduled_jobs

    def with_(self, **kw) -> "Job":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class Instance:
    """One attempt at running a job (reference: `:instance/...`)."""

    task_id: str
    job_uuid: str
    status: InstanceStatus = InstanceStatus.UNKNOWN
    hostname: str = ""
    node_id: str = ""  # reference: slave-id
    compute_cluster: str = ""
    start_time_ms: int = 0
    end_time_ms: int = 0
    reason_code: Optional[int] = None
    preempted: bool = False
    progress: int = 0
    progress_message: str = ""
    exit_code: Optional[int] = None
    sandbox_directory: str = ""
    backfilled: bool = False
    cancelled: bool = False

    def with_(self, **kw) -> "Instance":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class Pool:
    """A named scheduling domain (reference: pool.clj)."""

    name: str
    purpose: str = ""
    state: str = "active"  # "active" | "inactive"
    dru_mode: DruMode = DruMode.DEFAULT

    @property
    def schedules_jobs(self) -> bool:
        return self.state == "active"

    @property
    def accepts_submissions(self) -> bool:
        return self.state == "active"


@dataclass(frozen=True)
class Share:
    """Per-user per-pool fair-share divisors (reference: share.clj)."""

    user: str
    pool: str
    resources: Resources
    reason: str = ""


@dataclass(frozen=True)
class Quota:
    """Per-user per-pool hard caps (reference: quota.clj). `count` caps the
    number of concurrently running jobs."""

    user: str
    pool: str
    resources: Resources
    count: int = 2**31
    launch_rate_saved: float = 0.0
    launch_rate_per_minute: float = 0.0
    reason: str = ""


DEFAULT_USER = "default"  # fallback share/quota owner (reference: share.clj default-user)


def new_uuid() -> str:
    return str(uuid_mod.uuid4())


def job_display(job: Job) -> dict[str, Any]:
    """JSON-friendly view of a job, REST-response shaped."""
    return {
        "uuid": job.uuid,
        "user": job.user,
        "command": job.command,
        "name": job.name,
        "priority": job.priority,
        "max_retries": job.max_retries,
        "max_runtime": job.max_runtime_ms,
        "status": job.state.value,
        "pool": job.pool,
        "submit_time": job.submit_time_ms,
        "mem": job.resources.mem,
        "cpus": job.resources.cpus,
        "gpus": job.resources.gpus,
        "disk": job.resources.disk,
        "disk_type": job.resources.disk_type,
        "ports": job.resources.ports,
        "labels": dict(job.labels),
        "gang_size": job.gang_size,
        "env": dict(job.user_provided_env),
        "instances": list(job.instance_ids),
        "application": (
            {"name": job.application.name,
             "version": job.application.version,
             "workload-class": job.application.workload_class,
             "workload-id": job.application.workload_id}
            if job.application else None
        ),
    }
