"""Failure-reason registry with mea-culpa retry semantics.

A "mea-culpa" failure is the cluster's fault, not the job's: such failures do
not consume the job's retry budget until a per-reason failure limit is hit.
Reference: `reason-entities` + `:job/reasons->attempts-consumed`
(/root/reference/scheduler/src/cook/schema.clj:1155-1199,1413-1666) and
`docs/reason-code`.  Codes are kept API-compatible where behavior depends on
them (normal-exit, killed-by-user, preempted-by-rebalancer, max-runtime,
straggler, unknown).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# failure_limit semantics: None = use the scheduler-wide mea-culpa limit;
# -1 = unlimited free retries for this reason.
UNLIMITED = -1
DEFAULT_MEA_CULPA_FAILURE_LIMIT = 5


@dataclass(frozen=True)
class Reason:
    code: int
    name: str
    mea_culpa: bool
    description: str = ""
    failure_limit: Optional[int] = None


_REASONS: list[Reason] = [
    Reason(1000, "normal-exit", False, "Normal exit"),
    Reason(1001, "killed-by-user", False, "Killed by user"),
    Reason(1002, "preempted-by-rebalancer", True, "Preempted by rebalancer"),
    Reason(1003, "container-preempted", False, "Container preempted by cluster"),
    Reason(1004, "killed-during-launch", False, "Killed during launch"),
    Reason(1005, "running", False, "Task is (still) running"),
    Reason(1006, "scheduling-failed-on-host", True, "Scheduling failed on host",
           failure_limit=3),
    Reason(1007, "container-initialization-timed-out", False,
           "Container initialization timed out"),
    Reason(1008, "killed-externally", True, "Killed by an external entity"),
    Reason(1009, "container-readiness-timed-out", True,
           "Container readiness probe timed out"),
    Reason(1010, "pod-submission-api-error", True, "Backend API error at launch"),
    Reason(1011, "launch-failed", True,
           "Backend launch RPC failed after the match transacted",
           failure_limit=5),
    Reason(2000, "container-limitation", False, "Container resource limitation"),
    Reason(2001, "container-limitation-disk", False, "Container disk limit exceeded"),
    Reason(2002, "container-limitation-memory", False, "Container memory limit exceeded"),
    Reason(2003, "max-runtime-exceeded", False, "Max runtime exceeded"),
    Reason(2004, "straggler", True, "Killed as a straggler"),
    Reason(3000, "reconciliation", False, "Task lost during reconciliation"),
    Reason(3006, "task-unknown", False, "Backend did not recognize the task"),
    Reason(3008, "could-not-reconstruct-state", True,
           "Could not reconstruct task state on failover"),
    Reason(4000, "node-removed", True, "Node was removed"),
    Reason(4001, "node-restarted", True, "Node restarted"),
    Reason(4003, "container-launch-failed", True, "Container launch failed",
           failure_limit=10),
    Reason(4005, "node-disconnected", True, "Node disconnected"),
    Reason(4006, "heartbeat-lost", True, "Executor heartbeat lost"),
    Reason(5001, "backend-disconnected", True, "Compute backend disconnected"),
    Reason(6000, "executor-registration-timeout", True,
           "Executor registration timed out"),
    Reason(6002, "executor-unregistered", False, "Executor unregistered"),
    Reason(99000, "unknown", False, "Unknown reason"),
    Reason(99002, "executor-terminated", True, "Executor terminated",
           failure_limit=3),
    Reason(99003, "command-executor-failed", False, "Command executor failed"),
]

REASONS_BY_CODE: dict[int, Reason] = {r.code: r for r in _REASONS}
REASONS_BY_NAME: dict[str, Reason] = {r.name: r for r in _REASONS}

NORMAL_EXIT = REASONS_BY_NAME["normal-exit"]
KILLED_BY_USER = REASONS_BY_NAME["killed-by-user"]
PREEMPTED_BY_REBALANCER = REASONS_BY_NAME["preempted-by-rebalancer"]
MAX_RUNTIME_EXCEEDED = REASONS_BY_NAME["max-runtime-exceeded"]
STRAGGLER = REASONS_BY_NAME["straggler"]
KILLED_DURING_LAUNCH = REASONS_BY_NAME["killed-during-launch"]
HEARTBEAT_LOST = REASONS_BY_NAME["heartbeat-lost"]
UNKNOWN = REASONS_BY_NAME["unknown"]


def get_reason(code_or_name) -> Reason:
    if isinstance(code_or_name, Reason):
        return code_or_name
    if isinstance(code_or_name, int):
        return REASONS_BY_CODE.get(code_or_name, UNKNOWN)
    return REASONS_BY_NAME.get(code_or_name, UNKNOWN)


def attempts_consumed_by_reasons(
    reason_codes: list[Optional[int]],
    *,
    mea_culpa_limit: int = DEFAULT_MEA_CULPA_FAILURE_LIMIT,
    disable_mea_culpa_retries: bool = False,
) -> int:
    """How many retry-budget attempts a list of failure reasons consumes.

    Non-mea-culpa failures (and unknown/None reasons) each consume one
    attempt.  Mea-culpa failures are free until their per-reason failure
    limit (or the global limit) is exceeded; a limit of -1 means always free.
    Reference: `:job/reasons->attempts-consumed` (schema.clj:1155-1174).
    """
    counts: dict[Optional[int], int] = {}
    for code in reason_codes:
        counts[code] = counts.get(code, 0) + 1
    consumed = 0
    for code, count in counts.items():
        reason = REASONS_BY_CODE.get(code) if code is not None else None
        if reason is not None and reason.mea_culpa:
            if disable_mea_culpa_retries:
                limit = 0
            elif reason.failure_limit is not None:
                limit = reason.failure_limit
            else:
                limit = mea_culpa_limit
            if limit == UNLIMITED:
                continue
            consumed += max(0, count - limit)
        else:
            # A missing/unknown reason counts as a plain failure.
            consumed += count
    return consumed
