"""Columnar job/instance index: O(delta) host-side state for the cycles.

At north-star scale (100k pending jobs) rebuilding numpy arrays from Python
job objects each rank cycle costs ~1 s of host time per cycle.  This index
subscribes to the store's event feed and maintains flat numpy columns
incrementally, so a cycle's tensor encoding is vectorized slicing instead
of Python loops (the role the reference's feature-vector/user caches play,
caches.clj + cached_queries.clj — but columnar, because our consumer is a
tensor kernel, not a comparator).

Guarantees: eventually consistent with the store at event granularity; safe
to rebuild from scratch at any time (`rebuild`); growth is amortized
doubling; job rows are never deleted (jobs are, at most, COMPLETED).
"""
from __future__ import annotations

import threading

import numpy as np

from cook_tpu.models.entities import InstanceStatus, Job, JobState
from cook_tpu.models.store import Event, JobStore

_STATE_CODE = {JobState.WAITING: 0, JobState.RUNNING: 1, JobState.COMPLETED: 2}


class _Interner:
    def __init__(self):
        self.by_name: dict[str, int] = {}
        self.names: list[str] = []

    def code(self, name: str) -> int:
        c = self.by_name.get(name)
        if c is None:
            c = len(self.names)
            self.by_name[name] = c
            self.names.append(name)
        return c


class ColumnarJobIndex:
    """Flat columns over all jobs + live instances of a store."""

    def __init__(self, store: JobStore, *, capacity: int = 1024):
        self.store = store
        self._lock = threading.Lock()
        self.users = _Interner()
        self.pools = _Interner()
        self._rows: dict[str, int] = {}
        self._n = 0
        self._alloc(capacity)
        # live instance columns (small: one per running task)
        self._inst_rows: dict[str, int] = {}
        self._inst_tids: list[str] = []
        self.inst_job_row: np.ndarray = np.empty(0, np.int64)
        self.inst_start: np.ndarray = np.empty(0, np.int64)
        self.rebuild()
        store.add_watcher(self._on_event)
        # snapshot bootstrap on a replicating standby replaces the whole
        # store at once (persistence.restore_into) — rebuild from scratch
        store.add_resync_listener(self.rebuild)

    # ------------------------------------------------------------ storage

    def _alloc(self, capacity: int) -> None:
        self.user_code = np.zeros(capacity, np.int32)
        self.pool_code = np.zeros(capacity, np.int16)
        self.mem = np.zeros(capacity, np.float32)
        self.cpus = np.zeros(capacity, np.float32)
        self.gpus = np.zeros(capacity, np.float32)
        self.disk = np.zeros(capacity, np.float32)
        self.priority = np.zeros(capacity, np.int32)
        self.submit_ms = np.zeros(capacity, np.int64)
        self.state = np.full(capacity, 2, np.int8)
        self.uuids: list[str] = [""] * capacity

    def _grow(self) -> None:
        cap = len(self.state) * 2
        for name in ("user_code", "pool_code", "mem", "cpus", "gpus", "disk",
                     "priority", "submit_ms", "state"):
            old = getattr(self, name)
            new = np.zeros(cap, old.dtype)
            if name == "state":
                new[:] = 2
            new[: self._n] = old[: self._n]
            setattr(self, name, new)
        self.uuids.extend([""] * (cap - len(self.uuids)))

    def _add_job(self, job: Job) -> int:
        row = self._rows.get(job.uuid)
        if row is not None:
            return row
        if self._n >= len(self.state):
            self._grow()
        row = self._n
        self._n += 1
        self._rows[job.uuid] = row
        self.uuids[row] = job.uuid
        self.user_code[row] = self.users.code(job.user)
        self.pool_code[row] = self.pools.code(job.pool)
        r = job.resources
        self.mem[row] = r.mem
        self.cpus[row] = r.cpus
        self.gpus[row] = r.gpus
        self.disk[row] = r.disk
        self.priority[row] = job.priority
        self.submit_ms[row] = job.submit_time_ms or self.store.clock()
        self.state[row] = _STATE_CODE[job.state]
        return row

    # ------------------------------------------------------------- events

    def _on_event(self, event: Event) -> None:
        with self._lock:
            kind = event.kind
            if kind == "job/created":
                job = self.store.jobs.get(event.data["uuid"])
                if job is not None:
                    self._add_job(job)
            elif kind == "job/state":
                row = self._rows.get(event.data["uuid"])
                if row is not None:
                    self.state[row] = {"waiting": 0, "running": 1,
                                       "completed": 2}[event.data["state"]]
            elif kind == "job/pool-moved":
                row = self._rows.get(event.data["uuid"])
                if row is not None:
                    self.pool_code[row] = self.pools.code(event.data["to"])
            elif kind == "instance/created":
                task_id = event.data["task_id"]
                job_row = self._rows.get(event.data["job"])
                if job_row is None:
                    return
                irow = len(self._inst_rows)
                self._inst_rows[task_id] = irow
                if irow >= len(self.inst_job_row):
                    grow = max(1024, len(self.inst_job_row) * 2)
                    self.inst_job_row = np.resize(self.inst_job_row, grow)
                    self.inst_start = np.resize(self.inst_start, grow)
                self.inst_job_row[irow] = job_row
                self.inst_start[irow] = self.store.clock()
                if irow < len(self._inst_tids):
                    self._inst_tids[irow] = task_id
                else:
                    self._inst_tids.append(task_id)
            elif kind == "instance/status":
                if event.data["status"] in ("success", "failed"):
                    # live-instance set shrinks: O(1) swap-remove
                    irow = self._inst_rows.pop(event.data["task_id"], None)
                    if irow is None:
                        return
                    last = len(self._inst_rows)
                    if irow != last:
                        tid = self._inst_tids[last]
                        self._inst_tids[irow] = tid
                        self._inst_rows[tid] = irow
                        self.inst_job_row[irow] = self.inst_job_row[last]
                        self.inst_start[irow] = self.inst_start[last]

    # ------------------------------------------------------------ rebuild

    def rebuild(self) -> None:
        """Full resync from the store (startup / invariant recovery)."""
        with self._lock:
            self._rows.clear()
            self._n = 0
            self._alloc(max(1024, len(self.store.jobs) * 2))
            self._inst_rows.clear()
            self._inst_tids = []
            for job in self.store.jobs.values():
                self._add_job(job)
            live = [
                inst for inst in self.store.instances.values()
                if not inst.status.terminal and inst.job_uuid in self._rows
            ]
            need = max(1024, len(live))
            self.inst_job_row = np.empty(need, np.int64)
            self.inst_start = np.empty(need, np.int64)
            for i, inst in enumerate(live):
                self._inst_rows[inst.task_id] = i
                self._inst_tids.append(inst.task_id)
                self.inst_job_row[i] = self._rows[inst.job_uuid]
                self.inst_start[i] = inst.start_time_ms

    # ------------------------------------------------------------- queries

    def pool_view(self, pool: str):
        """(pending_rows, live_inst_rows) for one pool — vectorized."""
        with self._lock:
            pcode = self.pools.by_name.get(pool)
            n = self._n
            if pcode is None or n == 0:
                return (np.empty(0, np.int64), np.empty(0, np.int64))
            mask = (self.pool_code[:n] == pcode)
            pending = np.nonzero(mask & (self.state[:n] == 0))[0]
            ninst = len(self._inst_rows)
            inst_rows = self.inst_job_row[:ninst]
            inst_sel = np.nonzero(mask[inst_rows])[0]
            return pending, inst_sel

    def consistent_with_store(self) -> bool:
        """Invariant check used by tests and anti-entropy."""
        with self._lock:
            for uuid, job in self.store.jobs.items():
                row = self._rows.get(uuid)
                if row is None or self.state[row] != _STATE_CODE[job.state]:
                    return False
            live_store = {
                i.task_id for i in self.store.instances.values()
                if not i.status.terminal
            }
            return live_store == set(self._inst_rows)
