"""Store durability: snapshots + append-only event journal.

The reference delegates durability to Datomic (state survives leader
failover; the new leader reads the DB and reconstructs backend expectations
— kubernetes/compute_cluster.clj:269).  Here the JobStore persists itself:

  * `JournalWriter` appends every committed event as a JSON line (the
    transaction log); fsync policy is the caller's choice.
  * `snapshot` / `load_snapshot` serialize full store state; a snapshot +
    the journal suffix after it reconstructs the store exactly.
  * `attach_journal` wires a live store to a journal file; `recover`
    rebuilds a store from snapshot+journal at startup.

Entities serialize via dataclasses.asdict with enum-aware encoding.
"""
from __future__ import annotations

import dataclasses
import enum
import json
import os
from typing import Any

from cook_tpu.models.entities import (
    Checkpoint,
    ConstraintOperator,
    Container,
    DruMode,
    Group,
    GroupPlacementType,
    HostPlacement,
    Instance,
    InstanceStatus,
    Job,
    JobConstraint,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
    StragglerHandling,
)
from cook_tpu.models.store import Event, JobStore


def _encode(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _encode(v)
                for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [_encode(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, float) and obj == float("inf"):
        return "Infinity"
    return obj


def _dec_float(x):
    return float("inf") if x == "Infinity" else x


def _dec_resources(d: dict) -> Resources:
    return Resources(
        mem=_dec_float(d["mem"]), cpus=_dec_float(d["cpus"]),
        gpus=_dec_float(d["gpus"]), disk=_dec_float(d.get("disk", 0.0)),
        ports=int(d.get("ports", 0)),
    )


def _dec_job(d: dict) -> Job:
    return Job(
        uuid=d["uuid"],
        user=d["user"],
        command=d["command"],
        name=d["name"],
        priority=d["priority"],
        max_retries=d["max_retries"],
        max_runtime_ms=d["max_runtime_ms"],
        expected_runtime_ms=d["expected_runtime_ms"],
        resources=_dec_resources(d["resources"]),
        pool=d["pool"],
        state=JobState(d["state"]),
        submit_time_ms=d["submit_time_ms"],
        user_provided_env=tuple(map(tuple, d["user_provided_env"])),
        labels=tuple(map(tuple, d["labels"])),
        constraints=tuple(
            JobConstraint(attribute=c["attribute"],
                          operator=ConstraintOperator(c["operator"]),
                          pattern=c["pattern"])
            for c in d["constraints"]
        ),
        group_uuid=d["group_uuid"],
        container=(Container(**{**d["container"],
                                "volumes": tuple(d["container"]["volumes"]),
                                "ports": tuple(d["container"]["ports"]),
                                "env": tuple(map(tuple, d["container"]["env"]))})
                   if d["container"] else None),
        application=None,
        checkpoint=(Checkpoint(
            mode=d["checkpoint"]["mode"],
            periodic_sec=d["checkpoint"]["periodic_sec"],
            preserve_paths=tuple(d["checkpoint"]["preserve_paths"]),
            location=d["checkpoint"]["location"],
        ) if d["checkpoint"] else None),
        disable_mea_culpa_retries=d["disable_mea_culpa_retries"],
        instance_ids=tuple(d["instance_ids"]),
        custom_executor=d["custom_executor"],
        last_waiting_start_time_ms=d["last_waiting_start_time_ms"],
        last_fenzo_placement_failure=d["last_fenzo_placement_failure"],
    )


def _dec_instance(d: dict) -> Instance:
    d = dict(d)
    d["status"] = InstanceStatus(d["status"])
    return Instance(**d)


def _dec_group(d: dict) -> Group:
    return Group(
        uuid=d["uuid"],
        name=d["name"],
        host_placement=HostPlacement(
            type=GroupPlacementType(d["host_placement"]["type"]),
            attribute=d["host_placement"]["attribute"],
            minimum=d["host_placement"]["minimum"],
        ),
        straggler_handling=StragglerHandling(**d["straggler_handling"]),
        job_uuids=tuple(d["job_uuids"]),
    )


def snapshot(store: JobStore, path: str) -> None:
    """Write full store state atomically."""
    with store._lock:
        state = {
            "seq": store._events[-1].seq if store._events else 0,
            "jobs": {k: _encode(v) for k, v in store.jobs.items()},
            "instances": {k: _encode(v) for k, v in store.instances.items()},
            "groups": {k: _encode(v) for k, v in store.groups.items()},
            "pools": {k: _encode(v) for k, v in store.pools.items()},
            "shares": [
                _encode(v) for v in store.shares.values()
            ],
            "quotas": [
                _encode(v) for v in store.quotas.values()
            ],
            "dynamic_config": store.dynamic_config,
        }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str, *, clock=None) -> JobStore:
    with open(path) as f:
        state = json.load(f)
    store = JobStore(clock=clock)
    for k, v in state["pools"].items():
        store.pools[k] = Pool(name=v["name"], purpose=v["purpose"],
                              state=v["state"],
                              dru_mode=DruMode(v["dru_mode"]))
    for k, v in state["jobs"].items():
        job = _dec_job(v)
        store.jobs[k] = job
        store.job_seq[k] = len(store.job_seq)  # snapshot preserves order
        store._index_job(job, None)
    for k, v in state["instances"].items():
        store.instances[k] = _dec_instance(v)
    for k, v in state["groups"].items():
        store.groups[k] = _dec_group(v)
    for v in state["shares"]:
        store.shares[(v["user"], v["pool"])] = Share(
            user=v["user"], pool=v["pool"],
            resources=_dec_resources(v["resources"]), reason=v["reason"])
    for v in state["quotas"]:
        store.quotas[(v["user"], v["pool"])] = Quota(
            user=v["user"], pool=v["pool"],
            resources=_dec_resources(v["resources"]),
            count=v["count"], reason=v["reason"])
    store.dynamic_config = state.get("dynamic_config", {})
    # resume event sequence numbering after the snapshot point
    import itertools

    store._seq = itertools.count(state["seq"] + 1)
    return store


class JournalWriter:
    """Append-only event journal (one JSON line per committed event)."""

    def __init__(self, path: str, *, fsync_every: int = 0):
        self.path = path
        self.fsync_every = fsync_every
        self._count = 0
        import threading

        self._lock = threading.Lock()
        self._f = open(path, "a")

    def __call__(self, event: Event) -> None:
        with self._lock:
            self._f.write(event.to_json() + "\n")
            self._f.flush()
            self._count += 1
            if self.fsync_every and self._count % self.fsync_every == 0:
                os.fsync(self._f.fileno())

    def rotate(self) -> None:
        """After a snapshot, the journal prefix is redundant: move it aside
        and start fresh (the snapshot + new journal reconstruct state)."""
        with self._lock:
            self._f.close()
            if os.path.exists(self.path):
                os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")

    def close(self) -> None:
        with self._lock:
            self._f.close()


def attach_journal(store: JobStore, path: str, **kw) -> JournalWriter:
    writer = JournalWriter(path, **kw)
    store.add_watcher(writer)
    return writer


def read_journal(path: str) -> list[dict]:
    events = []
    if not os.path.exists(path):
        return events
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events
