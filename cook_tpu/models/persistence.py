"""Store durability: snapshots + append-only event journal.

The reference delegates durability to Datomic (state survives leader
failover; the new leader reads the DB and reconstructs backend expectations
— kubernetes/compute_cluster.clj:269).  Here the JobStore persists itself:

  * `JournalWriter` appends every committed event as a JSON line (the
    transaction log).  Events carry the full post-transaction entity
    payloads (`Event.entities`), so the journal alone reconstructs every
    acknowledged write — the role Datomic's transaction log plays.
  * `snapshot` / `load_snapshot` serialize full store state; a snapshot +
    the journal suffix after it reconstructs the store exactly.
  * `attach_journal` wires a live store to a journal file; `recover`
    rebuilds a store from snapshot+journal at startup.

Entity (de)serialization lives in `cook_tpu.models.codec`.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Optional

from cook_tpu.models import codec
from cook_tpu.models.store import Event, JobStore
from cook_tpu.obs.contention import JournalTelemetry

log = logging.getLogger(__name__)

_encode = codec.encode  # back-compat aliases
_dec_resources = codec.dec_resources
_dec_job = codec.dec_job
_dec_instance = codec.dec_instance
_dec_group = codec.dec_group


def snapshot_state(store: JobStore) -> dict:
    """Serialize full store state to a JSON-ready dict (also served over
    HTTP to replicating standbys, rest/api.py /replication/snapshot).

    Entities are immutable, so only the dict copies happen under the
    store lock — the JSON encoding (the expensive part at 100k-job scale)
    runs outside it and never stalls writers."""
    with store._lock:
        seq = store.last_seq()
        jobs = dict(store.jobs)
        instances = dict(store.instances)
        groups = dict(store.groups)
        pools = dict(store.pools)
        shares = list(store.shares.values())
        quotas = list(store.quotas.values())
        dynamic_config = dict(store.dynamic_config)
        txns = dict(store.txn_results)
        capacity_ledger = [
            {"from": lender, "to": borrower, **amounts}
            for (lender, borrower), amounts
            in sorted(store.capacity_ledger.items())
        ]
    return {
        "txns": txns,
        "seq": seq,
        "capacity_ledger": capacity_ledger,
        "jobs": {k: codec.encode(v) for k, v in jobs.items()},
        "instances": {k: codec.encode(v) for k, v in instances.items()},
        "groups": {k: codec.encode(v) for k, v in groups.items()},
        "pools": {k: codec.encode(v) for k, v in pools.items()},
        "shares": [codec.encode(v) for v in shares],
        "quotas": [codec.encode(v) for v in quotas],
        "dynamic_config": dynamic_config,
    }


def snapshot(store: JobStore, path: str) -> None:
    """Write full store state atomically."""
    state = snapshot_state(store)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str, *, clock=None, store_factory=None) -> JobStore:
    with open(path) as f:
        state = json.load(f)
    store = store_factory() if store_factory is not None \
        else JobStore(clock=clock)
    _populate(store, state)
    return store


def restore_into(store: JobStore, state: dict) -> None:
    """Replace a LIVE store's contents with a snapshot state dict (the
    replicating standby's full-resync path — the store object is shared
    with the REST layer, so it must be rebuilt in place, atomically under
    the store lock).  The retained event window is cleared too: its
    entries predate the resync point, and a promoted standby serving
    `/replication/journal` must never mix pre-resync events with
    post-resync sequence numbering.  Watcher-derived state (columnar
    index, scheduler caches) is rebuilt via the store's resync listeners."""
    with store._lock:
        store.jobs.clear()
        store.job_seq.clear()
        store.instances.clear()
        store.groups.clear()
        store.pools.clear()
        store.shares.clear()
        store.quotas.clear()
        store.dynamic_config = {}
        store.txn_results.clear()
        store.capacity_ledger.clear()
        store._user_jobs.clear()
        store._pool_pending.clear()
        store._pool_running.clear()
        store._events.clear()
        _populate(store, state)
        store._notify_resync()


def _populate(store: JobStore, state: dict) -> None:
    for k, v in state["pools"].items():
        store.pools[k] = codec.dec_pool(v)
    for k, v in state["jobs"].items():
        job = codec.dec_job(v)
        store.jobs[k] = job
        store.job_seq[k] = len(store.job_seq)  # snapshot preserves order
        store._index_job(job, None)
    for k, v in state["instances"].items():
        store.instances[k] = codec.dec_instance(v)
    for k, v in state["groups"].items():
        store.groups[k] = codec.dec_group(v)
    for v in state["shares"]:
        share = codec.dec_share(v)
        store.shares[(share.user, share.pool)] = share
    for v in state["quotas"]:
        quota = codec.dec_quota(v)
        store.quotas[(quota.user, quota.pool)] = quota
    store.dynamic_config = state.get("dynamic_config", {})
    store.txn_results.update(state.get("txns", {}))
    store.set_capacity_ledger(state.get("capacity_ledger", []))
    store.reset_seq(state["seq"])


def _truncate_torn_tail(path: str) -> None:
    """Drop any unparsable tail left by a crash mid-write.  Appending onto a
    torn fragment would merge the next event into one corrupt line, silently
    discarding it (and everything after) on the NEXT recovery — so the
    fragment must go before a writer reopens the file."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        data = f.read()
    end = len(data)
    while end > 0:
        if data[end - 1:end] != b"\n":
            # partial tail with no line terminator: drop it
            end = data.rfind(b"\n", 0, end) + 1  # no newline at all -> 0
            continue
        # prefix ends in a terminator; validate its final line
        nl = data.rfind(b"\n", 0, end - 1)
        line = data[nl + 1:end - 1].strip()
        if line:
            try:
                json.loads(line)
                break  # clean, parsable tail: keep through end
            except json.JSONDecodeError:
                pass
        end = nl + 1  # drop the blank/corrupt line (each step shrinks end)
    if end < len(data):
        with open(path, "r+b") as f:
            f.truncate(end)


class JournalWriter:
    """Append-only event journal (one JSON line per committed event).

    Durability is batched by default: every write is flushed to the OS,
    but fsync happens every `fsync_every` events OR whenever `sync()` is
    called.  The transaction pipeline (cook_tpu.txn) calls `sync()` once
    per commit before the write is acknowledged — group commit: one
    fsync covers every event flushed so far, so concurrent commits share
    the disk barrier.  fsync_every is the backstop bound for writes that
    bypass the txn pipeline (scheduler-internal status updates): at most
    that many non-txn events are exposed to an OS crash (process crashes
    lose nothing — the data is in the page cache)."""

    DEFAULT_FSYNC_EVERY = 64

    # what an fsync failure means (docs/resilience.md): "fail-stop"
    # re-raises — the commit pipeline reports the write undurable (REST
    # 500) and, when wired (components.start_leader_duties), the leader
    # demotes so a standby with a working disk takes over; "degrade-async"
    # keeps committing WITHOUT the disk barrier (writes ride the page
    # cache), surfaces the `journal-fsync-degraded` health reason, and
    # probes the disk again every `degraded_retry_s`.
    FSYNC_POLICIES = ("fail-stop", "degrade-async")

    def __init__(self, path: str, *, fsync_every: int = DEFAULT_FSYNC_EVERY,
                 fsync_policy: str = "fail-stop",
                 degraded_retry_s: float = 5.0,
                 on_fsync_error=None):
        if fsync_policy not in self.FSYNC_POLICIES:
            raise ValueError(f"unknown fsync policy {fsync_policy!r}")
        self.path = path
        self.fsync_every = fsync_every
        self.fsync_policy = fsync_policy
        self.degraded_retry_s = degraded_retry_s
        # observer hook, called (under the writer lock) with the OSError;
        # the fail-stop leader-demotion wiring lives here
        self.on_fsync_error = on_fsync_error
        self._count = 0
        self._dirty = False
        self._degraded = False
        self._last_fsync_attempt = 0.0
        # events flushed to the OS but not yet covered by an fsync: the
        # append "queue" the contention observatory reports, and the
        # group-commit batch size the next fsync covers
        self._pending = 0
        # per-writer so the observatory reads ITS journal's stalls, not
        # some other process-resident writer's (obs/contention.py)
        self.telemetry = JournalTelemetry()
        import threading

        self._lock = threading.Lock()
        _truncate_torn_tail(path)
        self._f = open(path, "a")

    def _fsync_locked(self) -> None:
        import time as _time

        from cook_tpu import faults

        batch = self._pending
        self._last_fsync_attempt = _time.monotonic()
        t0 = _time.perf_counter()
        try:
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(faults.JOURNAL_FSYNC, path=self.path)
            os.fsync(self._f.fileno())
        except OSError as e:
            self._handle_fsync_error(e)
            return
        self.telemetry.note_fsync(batch, _time.perf_counter() - t0)
        if self._degraded:
            log.warning("journal %s fsync recovered; leaving degraded "
                        "async mode", self.path)
            self._degraded = False
            self.telemetry.set_degraded(False)
        self._pending = 0
        self._dirty = False

    def _handle_fsync_error(self, exc: OSError) -> None:
        """Caller holds self._lock.  The pending/dirty counters are NOT
        reset: the exposure the gauge reports is real until an fsync
        succeeds."""
        self.telemetry.note_fsync_error()
        if self.on_fsync_error is not None:
            try:
                self.on_fsync_error(exc)
            except Exception:  # noqa: BLE001 — observer only
                log.exception("on_fsync_error callback failed")
        if self.fsync_policy == "degrade-async":
            if not self._degraded:
                log.error("journal %s fsync failed (%s); degrading to "
                          "async (no disk barrier) — commits remain "
                          "applied+replicated but an OS crash may lose "
                          "the unfsynced tail; retrying the disk every "
                          "%.0fs", self.path, exc, self.degraded_retry_s)
                self._degraded = True
                self.telemetry.set_degraded(True)
            return
        log.error("journal %s fsync failed (%s); fail-stop policy "
                  "re-raises — the commit is reported undurable",
                  self.path, exc)
        raise exc

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def __call__(self, event: Event) -> None:
        self.write_line(event.to_json())

    def write_line(self, line: str) -> None:
        """Append a pre-serialized journal line (the replication follower
        persists events it fetched from the leader — they arrive already
        encoded, and routing them through this writer keeps one lock and
        one file handle on the journal)."""
        with self._lock:
            payload = line.rstrip("\n") + "\n"
            self._f.write(payload)
            self._f.flush()
            self._count += 1
            self._pending += 1
            self._dirty = True
            self.telemetry.note_append(len(payload), self._pending)
            if self.fsync_every and self._count % self.fsync_every == 0:
                import time as _time

                # degraded-async cool-off applies to the backstop too, or
                # a broken disk gets probed every 64 events
                if not (self._degraded and _time.monotonic()
                        - self._last_fsync_attempt < self.degraded_retry_s):
                    self._fsync_locked()

    def sync(self) -> None:
        """Group-commit barrier: fsync anything flushed since the last
        sync.  A no-op when nothing is dirty — so of N concurrent
        commits, whichever syncs first pays the fsync for all of them.
        In degraded-async mode (an earlier fsync failed under the
        degrade policy) the disk is only re-probed every
        `degraded_retry_s`; between probes commits proceed without the
        barrier — that IS the degradation the health reason names."""
        import time as _time

        with self._lock:
            if not self._dirty or self._f.closed:
                return
            if self._degraded and _time.monotonic() \
                    - self._last_fsync_attempt < self.degraded_retry_s:
                return
            self._fsync_locked()

    def rotate(self) -> None:
        """After a snapshot, the journal prefix is redundant: move it aside
        and start fresh (the snapshot + new journal reconstruct state)."""
        with self._lock:
            self._f.close()
            if os.path.exists(self.path):
                os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")
            self._dirty = False
            # the unfsynced tail went aside with the prefix (the
            # snapshot supersedes it); carrying _pending forward would
            # report a phantom fsync queue and inflate the next batch
            self._pending = 0
            self.telemetry.note_rotate()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed and self._dirty:
                self._fsync_locked()
            self._f.close()


def attach_journal(store: JobStore, path: str, **kw) -> JournalWriter:
    writer = JournalWriter(path, **kw)
    store.add_watcher(writer)
    return writer


def read_journal(path: str) -> list[dict]:
    events = []
    if not os.path.exists(path):
        return events
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail write from a crash: the suffix is unusable
    return events


def _upsert_job(store: JobStore, payload: dict):
    job = codec.dec_job(payload)
    old = store.jobs.get(job.uuid)
    if old is not None and old.pool != job.pool:
        store._pool_pending.get(old.pool, set()).discard(job.uuid)
        store._pool_running.get(old.pool, set()).discard(job.uuid)
    if old is None:
        store.job_seq[job.uuid] = len(store.job_seq)
    store.jobs[job.uuid] = job
    store._index_job(job, old)
    return job


def apply_journal(store: JobStore, events: list[dict],
                  *, after_seq: int = 0, live: bool = False) -> int:
    """Replay journal entries onto a store.  Entries carry post-transaction
    entity payloads, so replay is a pure upsert — no state-machine
    re-checks.  Returns the number of entries applied.

    Two modes:
      * cold replay (default) — startup recovery, before watchers attach:
        no event retention, no fan-out.
      * ``live=True`` — a replicating standby applying the leader's feed
        (control/replication.py): each applied entry becomes an ordinary
        committed Event on THIS store — appended to the retained window
        (so a promoted standby can serve `/replication/journal` itself)
        and fanned out to watchers, exactly like a local transaction.
        This is the Datomic-replication semantic: the tx-report mult
        delivers to ALL listeners on every peer (reference
        datomic.clj:49), so a standby's columnar rank index, journal
        writer, and passport stream track the leader continuously and
        promotion needs no rebuild.  Effect-executing consumers (the
        scheduler's kill fan-out) gate on leadership instead — the
        LEADER already performed those effects and their results arrive
        as further replicated events.
    """
    applied = 0
    max_seq = store.last_seq()
    fan: list[Event] = []
    for entry in events:
        seq = entry.get("seq", 0)
        if seq <= after_seq or seq <= max_seq:
            continue
        kind = entry.get("kind", "")
        data = entry.get("data", {})
        entities = entry.get("entities") or {}
        decoded: dict = {}
        if "job" in entities:
            decoded["job"] = _upsert_job(store, entities["job"])
        if "instance" in entities:
            inst = codec.dec_instance(entities["instance"])
            store.instances[inst.task_id] = inst
            decoded["instance"] = inst
        if "group" in entities:
            group = codec.dec_group(entities["group"])
            store.groups[group.uuid] = group
            decoded["group"] = group
        if "pool" in entities:
            pool = codec.dec_pool(entities["pool"])
            store.pools[pool.name] = pool
            decoded["pool"] = pool
        if "share" in entities:
            share = codec.dec_share(entities["share"])
            store.shares[(share.user, share.pool)] = share
            decoded["share"] = share
        if "quota" in entities:
            quota = codec.dec_quota(entities["quota"])
            store.quotas[(quota.user, quota.pool)] = quota
            decoded["quota"] = quota
        if kind == "job/shard-out":
            # cross-shard pool move (cook_tpu/shard/): this shard stops
            # owning the job; the destination shard's own journal carries
            # the matching upsert
            gone = store.jobs.pop(data.get("uuid", ""), None)
            if gone is not None:
                store.job_seq.pop(gone.uuid, None)
                store._user_jobs.get(gone.user, set()).discard(gone.uuid)
                store._pool_pending.get(gone.pool, set()).discard(gone.uuid)
                store._pool_running.get(gone.pool, set()).discard(gone.uuid)
            for tid in data.get("instances", ()):
                store.instances.pop(tid, None)
        elif kind == "share/retracted":
            store.shares.pop((data["user"], data["pool"]), None)
        elif kind == "quota/retracted":
            store.quotas.pop((data["user"], data["pool"]), None)
        elif kind == "config/updated":
            store.dynamic_config.update(data.get("updates", {}))
        elif kind == "pool/capacity":
            # the event carries the full post-transaction ledger, so
            # replay is a pure upsert (no move re-application, no
            # double-count on overlapping snapshot+journal replay)
            store.set_capacity_ledger(data.get("ledger", []))
        elif kind == "txn/committed":
            # rebuild the idempotency table: a promoted standby (or a
            # recovered leader) must answer retried commits of acked
            # transactions without re-applying them (cook_tpu.txn)
            store.record_txn(data.get("txn_id", ""), data.get("op", ""),
                             seq, data.get("result"))
        if live:
            event = Event(seq=seq, kind=kind, data=data,
                          entities=decoded or None)
            store._events.append(event)
            fan.append(event)
        max_seq = max(max_seq, seq)
        applied += 1
    if live and len(store._events) > 2 * store.EVENT_WINDOW:
        del store._events[:-store.EVENT_WINDOW]
    store.reset_seq(max_seq)
    if fan:
        store._fan_out(fan)
    return applied


def recover(data_dir: str, *, clock=None,
            snapshot_name: str = "snapshot.json",
            journal_name: str = "journal.jsonl",
            store_factory=None) -> Optional[JobStore]:
    """Rebuild a store from the last snapshot plus the journal suffix after
    it (the documented failover path).  Returns None when the data dir holds
    neither a snapshot nor a journal (fresh start).  `store_factory`
    overrides the bare-JobStore construction — the sharded layout
    (cook_tpu/shard/journal.py) recovers each segment into a
    shard-labeled store.

    The rotated journal (`journal.jsonl.1`) is replayed too: rotation only
    happens after a successful snapshot, so its entries are normally all
    ≤ the snapshot seq and skip out — but if a crash lands between rotate
    and the next snapshot write, the suffix is still there to replay.
    """
    snap_path = os.path.join(data_dir, snapshot_name)
    journal_path = os.path.join(data_dir, journal_name)
    store = None
    snap_seq = 0
    if os.path.exists(snap_path):
        store = load_snapshot(snap_path, clock=clock,
                              store_factory=store_factory)
        snap_seq = store.last_seq()
    replayed = 0
    for path in (journal_path + ".1", journal_path):
        entries = read_journal(path)
        if not entries:
            continue
        if store is None:
            store = store_factory() if store_factory is not None \
                else JobStore(clock=clock)
        replayed += apply_journal(store, entries, after_seq=snap_seq)
    if store is not None:
        store.recovered_stats = {"snapshot_seq": snap_seq,
                                 "journal_replayed": replayed}
    return store
