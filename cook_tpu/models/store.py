"""Event-sourced in-memory job store: the framework's source of truth.

Plays the role Datomic plays in the reference (`cook.datomic`,
`/root/reference/scheduler/src/cook/datomic.clj`): serialized transactions,
a transaction-report feed that downstream consumers subscribe to (the kill
fan-out in `scheduler.clj:378` tails it), and preconditions that can veto a
transaction (`:job/allowed-to-start?`).  Instead of a remote transactor we
use a process-local lock + an append-only event log; leader failover replays
the log (or a snapshot) to rebuild state.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence

from cook_tpu.models import state as state_mod
from cook_tpu.obs.contention import profiled_store_lock
from cook_tpu.models.entities import (
    DEFAULT_USER,
    Group,
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.reasons import Reason, get_reason


@dataclass(frozen=True)
class Event:
    """One entry in the transaction log.

    `entities` holds references to the post-transaction entity objects the
    event touched (all immutable — mutation always replaces), keyed by
    entity kind ("job", "instance", "group", "pool", "share", "quota").
    The journal serializes them so a snapshot + journal suffix replays to
    the exact store state (persistence.apply_journal); keeping references
    here instead of eagerly encoding keeps the hot path free of
    serialization cost when no journal is attached.
    """

    seq: int
    kind: str
    data: dict[str, Any]
    entities: Optional[dict[str, Any]] = None

    def to_json(self) -> str:
        from cook_tpu.models import codec

        d = {"seq": self.seq, "kind": self.kind, "data": self.data}
        if self.entities:
            d["entities"] = {k: codec.encode(v)
                             for k, v in self.entities.items()}
        return json.dumps(d)


Watcher = Callable[[Event], None]


class TransactionVetoed(Exception):
    pass


class JobStore:
    """Thread-safe state store.  All mutation goes through `_transact`, which
    serializes writers, applies pure transitions, appends events, and fans
    them out to watchers (the tx-report-queue analog)."""

    def __init__(self, *, mea_culpa_limit: int = 5, clock: Callable[[], int] = None,
                 lock_name: str = "store", shard_id: Optional[int] = None):
        # every `with store._lock:` in the tree reports its wait/hold to
        # the contention observatory, labeled by calling function.  A
        # sharded control plane (cook_tpu/shard/) constructs one JobStore
        # per shard with lock_name "store-s{i}", so the per-shard locks
        # stay individually attributable at /debug/contention.
        self._lock = profiled_store_lock(lock_name)
        # which shard of a ShardedStore this store is (None = unsharded)
        self.shard_id = shard_id
        self._seq = itertools.count(1)
        self._last_seq = 0
        self.recovered_stats: dict[str, int] = {}
        self._events: list[Event] = []
        self._watchers: list[Watcher] = []
        self._resync_listeners: list[Callable[[], None]] = []
        self.mea_culpa_limit = mea_culpa_limit
        # clock returns milliseconds; injectable for the frozen-time simulator
        self.clock = clock or (lambda: 0)

        self.jobs: dict[str, Job] = {}
        # submission order per job — the deterministic tie-breaker the
        # reference gets from :db/id entity ids (tools.clj:614-641)
        self.job_seq: dict[str, int] = {}
        self.instances: dict[str, Instance] = {}
        self.groups: dict[str, Group] = {}
        self.pools: dict[str, Pool] = {}
        self.shares: dict[tuple[str, str], Share] = {}  # (user, pool)
        self.quotas: dict[tuple[str, str], Quota] = {}
        # runtime-mutable config (reference: Datomic-resident rebalancer params
        # + incremental configs)
        self.dynamic_config: dict[str, Any] = {}
        # committed-transaction table: txn_id -> {op, seq, result}
        # (cook_tpu.txn) — the idempotency record.  Replicated via
        # txn/committed events and included in snapshots, so a promoted
        # standby answers retried commits of acked transactions without
        # re-applying them.  Insertion-ordered; bounded by
        # TXN_RESULTS_WINDOW.
        self.txn_results: dict[str, dict[str, Any]] = {}
        # elastic capacity ledger (cook_tpu/elastic/): (lender, borrower)
        # -> {mem, cpus, gpus} currently on loan.  Mutated only through
        # the pool/capacity-delta txn op; every mutation's event carries
        # the full post-transaction ledger so journal replay and standby
        # replication are pure upserts — a promoted leader reconciles
        # cluster capacity from THIS table.
        self.capacity_ledger: dict[tuple[str, str], dict[str, float]] = {}

        # secondary indexes
        self._user_jobs: dict[str, set[str]] = {}
        self._pool_pending: dict[str, set[str]] = {}
        self._pool_running: dict[str, set[str]] = {}

    # ------------------------------------------------------------------ infra

    def add_watcher(self, watcher: Watcher) -> None:
        with self._lock:
            self._watchers.append(watcher)

    def add_resync_listener(self, listener: Callable[[], None]) -> None:
        """Register a callback for wholesale state replacement
        (persistence.restore_into — a standby's snapshot bootstrap).
        Event watchers see each incremental commit; a resync invalidates
        everything at once, so derived state (columnar index, caches)
        rebuilds from the store instead."""
        with self._lock:
            self._resync_listeners.append(listener)

    def _notify_resync(self) -> None:
        for listener in list(self._resync_listeners):
            listener()

    def events_since(self, seq: int) -> list[Event]:
        with self._lock:
            return [e for e in self._events if e.seq > seq]

    def last_seq(self) -> int:
        """Sequence number of the last committed event (survives recovery —
        unlike `_events`, which only holds this process's events)."""
        with self._lock:
            return self._last_seq

    def reset_seq(self, seq: int) -> None:
        """Resume event numbering after `seq` (recovery from snapshot or
        journal replay)."""
        with self._lock:
            self._seq = itertools.count(seq + 1)
            self._last_seq = seq

    # retained recent-event window for events_since debugging/polling; the
    # durable record is the journal, so this may be bounded
    EVENT_WINDOW = 10_000

    def _emit(self, kind: str, data: dict[str, Any], **entities: Any) -> Event:
        event = Event(seq=next(self._seq), kind=kind, data=data,
                      entities=entities or None)
        self._last_seq = event.seq
        self._events.append(event)
        if len(self._events) > 2 * self.EVENT_WINDOW:
            del self._events[:-self.EVENT_WINDOW]
        return event

    def _fan_out(self, events: list[Event]) -> None:
        for event in events:
            for watcher in list(self._watchers):
                watcher(event)

    # ----------------------------------------------------------- transactions

    # committed-transaction records retained for idempotency answers; old
    # enough duplicates (>10k commits ago) re-apply, which is safe for
    # every registered op (all are state-idempotent upserts/kills)
    TXN_RESULTS_WINDOW = 10_000

    def record_txn(self, txn_id: str, op: str, seq: int, result: Any) -> None:
        """Remember a committed transaction's outcome (also called from
        journal/replication replay, persistence.apply_journal)."""
        with self._lock:
            self.txn_results[txn_id] = {"op": op, "seq": seq,
                                        "result": result}
            while len(self.txn_results) > self.TXN_RESULTS_WINDOW:
                self.txn_results.pop(next(iter(self.txn_results)))

    def note_txn(self, txn_id: str, op: str, result: Any) -> int:
        """Seal a transaction: emit the txn/committed record event (it
        replicates and journals like any entity event) and record the
        outcome for idempotency.  Called by cook_tpu.txn with the store
        lock held, right after the op handler applied."""
        with self._lock:
            event = self._emit("txn/committed",
                               {"txn_id": txn_id, "op": op, "result": result})
            self.record_txn(txn_id, op, event.seq, result)
            self._fan_out([event])
            return event.seq

    # ---------------------------------------------------------------- indexes

    def _index_job(self, job: Job, old: Optional[Job]) -> None:
        self._user_jobs.setdefault(job.user, set()).add(job.uuid)
        pool = job.pool
        pending = self._pool_pending.setdefault(pool, set())
        running = self._pool_running.setdefault(pool, set())
        pending.discard(job.uuid)
        running.discard(job.uuid)
        if job.state == JobState.WAITING:
            pending.add(job.uuid)
        elif job.state == JobState.RUNNING:
            running.add(job.uuid)

    # ----------------------------------------------------------------- writes

    def submit_jobs(
        self,
        jobs: Sequence[Job],
        groups: Sequence[Group] = (),
    ) -> list[str]:
        """Atomically create a batch of jobs (+ groups).  The reference makes
        this atomic with a metatransaction commit-latch
        (metatransaction/core.clj:47-140); here batch atomicity falls out of
        the store lock."""
        with self._lock:
            now = self.clock()
            for job in jobs:
                if job.uuid in self.jobs:
                    raise TransactionVetoed(f"job {job.uuid} already exists")
            self._validate_gangs(jobs)
            for group in groups:
                self.groups[group.uuid] = group
            created_jobs = []
            touched_groups: dict[str, bool] = {}
            for job in jobs:
                if job.submit_time_ms == 0:
                    job = job.with_(submit_time_ms=now)
                job = job.with_(last_waiting_start_time_ms=now)
                self.jobs[job.uuid] = job
                self.job_seq[job.uuid] = len(self.job_seq)
                self._index_job(job, None)
                if job.group_uuid and job.group_uuid in self.groups:
                    g = self.groups[job.group_uuid]
                    self.groups[job.group_uuid] = dataclasses.replace(
                        g, job_uuids=g.job_uuids + (job.uuid,)
                    )
                    touched_groups[job.group_uuid] = True
                created_jobs.append(job)
            # events carry the final post-transaction payloads (membership
            # updates included), so journal replay is a pure upsert
            events = []
            for group in groups:
                touched_groups.pop(group.uuid, None)
                events.append(self._emit("group/created",
                                         {"uuid": group.uuid},
                                         group=self.groups[group.uuid]))
            for guuid in touched_groups:
                events.append(self._emit("group/updated", {"uuid": guuid},
                                         group=self.groups[guuid]))
            for job in created_jobs:
                events.append(
                    self._emit(
                        "job/created",
                        {"uuid": job.uuid, "user": job.user, "pool": job.pool},
                        job=job,
                    )
                )
            self._fan_out(events)
            return [j.uuid for j in jobs]

    def _validate_gangs(self, jobs: Sequence[Job]) -> None:
        """Txn-level gang invariants (caller holds the store lock).

        A gang (gang_size=k, scheduler/gang.py) only ever places
        all-or-nothing, so a half-submitted gang would wait forever: the
        k members must arrive in ONE submit batch, share one group, agree
        on k and pool, and the group must not already hold members from
        an earlier transaction.  Violations veto the whole batch — the
        same contract a 2PC prepare phase re-checks (mp/worker.py)."""
        by_group: dict[str, list[Job]] = {}
        for job in jobs:
            if job.gang_size <= 0:
                continue
            if job.gang_size == 1:
                raise TransactionVetoed(
                    f"job {job.uuid}: gang_size 1 is not a gang (omit it)")
            if not job.group_uuid:
                raise TransactionVetoed(
                    f"job {job.uuid}: gang_size requires a group")
            by_group.setdefault(job.group_uuid, []).append(job)
        for guuid, members in by_group.items():
            k = members[0].gang_size
            if any(j.gang_size != k for j in members):
                raise TransactionVetoed(
                    f"group {guuid}: members disagree on gang_size")
            if any(j.pool != members[0].pool for j in members):
                raise TransactionVetoed(
                    f"group {guuid}: gang members span pools")
            existing = self.groups.get(guuid)
            if existing is not None and existing.job_uuids:
                raise TransactionVetoed(
                    f"group {guuid}: gang groups cannot be extended after "
                    "submit")
            if len(members) != k:
                raise TransactionVetoed(
                    f"group {guuid}: gang_size {k} but {len(members)} "
                    "member(s) in the batch (gangs submit atomically)")

    def create_instance(
        self,
        job_uuid: str,
        task_id: str,
        *,
        hostname: str,
        node_id: str = "",
        compute_cluster: str = "",
    ) -> Instance:
        """Launch transaction: enforces `:job/allowed-to-start?` then creates
        an UNKNOWN instance and moves the job to RUNNING (the reference's
        `matches->task-txns`, scheduler.clj:790-846)."""
        with self._lock:
            job = self.jobs.get(job_uuid)
            if job is None:
                raise TransactionVetoed(f"no such job {job_uuid}")
            insts = self.job_instances(job_uuid)
            try:
                state_mod.check_allowed_to_start(job, insts)
            except state_mod.JobNotAllowedToStart as e:
                raise TransactionVetoed(str(e)) from e
            inst = Instance(
                task_id=task_id,
                job_uuid=job_uuid,
                status=InstanceStatus.UNKNOWN,
                hostname=hostname,
                node_id=node_id,
                compute_cluster=compute_cluster,
                start_time_ms=self.clock(),
            )
            self.instances[task_id] = inst
            job = job.with_(
                state=JobState.RUNNING,
                instance_ids=job.instance_ids + (task_id,),
            )
            self.jobs[job_uuid] = job
            self._index_job(job, None)
            events = [
                self._emit(
                    "instance/created",
                    {"task_id": task_id, "job": job_uuid, "hostname": hostname},
                    instance=inst,
                ),
                self._emit("job/state", {"uuid": job_uuid, "state": "running"},
                           job=job),
            ]
            self._fan_out(events)
            return inst

    def update_instance_state(
        self,
        task_id: str,
        new_status: InstanceStatus,
        reason: Optional[Reason | int | str] = None,
    ) -> state_mod.StateUpdate:
        """The completion path (SURVEY §3.5): validate + apply the instance
        transition, re-derive job state, fan out events."""
        with self._lock:
            inst = self.instances.get(task_id)
            if inst is None:
                return state_mod.StateUpdate(applied=False)
            job = self.jobs[inst.job_uuid]
            siblings = self.job_instances(inst.job_uuid)
            reason_code = get_reason(reason).code if reason is not None else None
            update = state_mod.update_instance_state(
                job,
                siblings,
                task_id,
                new_status,
                reason_code,
                mea_culpa_limit=self.mea_culpa_limit,
            )
            if not update.applied:
                return update
            now = self.clock()
            new_inst = inst.with_(status=new_status, reason_code=reason_code)
            if new_status.terminal:
                new_inst = new_inst.with_(end_time_ms=now)
            self.instances[task_id] = new_inst
            events = [
                self._emit(
                    "instance/status",
                    {
                        "task_id": task_id,
                        "job": job.uuid,
                        "status": new_status.value,
                        "reason": reason_code,
                    },
                    instance=new_inst,
                )
            ]
            if update.new_job_state != job.state:
                job = job.with_(state=update.new_job_state)
                if update.job_newly_waiting:
                    job = job.with_(last_waiting_start_time_ms=now)
                events.append(
                    self._emit(
                        "job/state",
                        {"uuid": job.uuid, "state": update.new_job_state.value},
                        job=job,
                    )
                )
            self.jobs[job.uuid] = job
            self._index_job(job, None)
            self._fan_out(events)
            return update

    def kill_jobs(self, job_uuids: Iterable[str]) -> list[str]:
        """Job kill is 'mark completed in the store; the event feed does the
        rest' (reference: mesos.clj:331-364): live instances are killed by
        the tx-feed consumer in the scheduler, not here."""
        killed = []
        with self._lock:
            events = []
            for uuid in job_uuids:
                job = self.jobs.get(uuid)
                if job is None or job.state == JobState.COMPLETED:
                    continue
                job = job.with_(state=JobState.COMPLETED)
                self.jobs[uuid] = job
                self._index_job(job, None)
                events.append(
                    self._emit(
                        "job/state",
                        {"uuid": uuid, "state": "completed", "killed": True},
                        job=job,
                    )
                )
                killed.append(uuid)
            self._fan_out(events)
        return killed

    def mark_instance_cancelled(self, task_id: str) -> bool:
        with self._lock:
            inst = self.instances.get(task_id)
            if inst is None:
                return False
            new_inst = inst.with_(cancelled=True)
            self.instances[task_id] = new_inst
            self._fan_out([self._emit("instance/cancelled",
                                      {"task_id": task_id},
                                      instance=new_inst)])
            return True

    def retry_job(self, job_uuid: str, retries: int, *, increment: bool = False) -> Job:
        """`POST /retry` semantics (`:job/update-retry-count` +
        `:job/update-state-on-retry`)."""
        with self._lock:
            job = self.jobs.get(job_uuid)
            if job is None:
                raise TransactionVetoed(f"no such job {job_uuid}")
            insts = self.job_instances(job_uuid)
            if increment:
                retries = job.max_retries + retries
            new_state = state_mod.retry_job_state(
                job, insts, retries, mea_culpa_limit=self.mea_culpa_limit
            )
            old_state = job.state
            job = job.with_(max_retries=retries, state=new_state)
            if new_state == JobState.WAITING:
                job = job.with_(last_waiting_start_time_ms=self.clock())
            self.jobs[job_uuid] = job
            self._index_job(job, None)
            events = [
                self._emit(
                    "job/retried",
                    {"uuid": job_uuid, "retries": retries,
                     "state": job.state.value},
                    job=job,
                )
            ]
            if new_state != old_state:
                # state-change consumers (columnar index, kill fan-out...)
                # key off job/state events; a revived job must emit one
                events.append(
                    self._emit("job/state",
                               {"uuid": job_uuid, "state": new_state.value},
                               job=job)
                )
            self._fan_out(events)
            return job

    def move_job_pool(self, job_uuid: str, new_pool: str) -> bool:
        """Move a WAITING job to another pool (reference:
        plugins/pool_mover.clj — only pending jobs may move)."""
        with self._lock:
            job = self.jobs.get(job_uuid)
            if job is None or job.state != JobState.WAITING:
                return False
            if new_pool not in self.pools:
                return False
            old_pool = job.pool
            self._pool_pending.get(old_pool, set()).discard(job_uuid)
            job = job.with_(pool=new_pool)
            self.jobs[job_uuid] = job
            self._index_job(job, None)
            self._fan_out([
                self._emit("job/pool-moved",
                           {"uuid": job_uuid, "from": old_pool,
                            "to": new_pool},
                           job=job)
            ])
            return True

    # ---------------------------------------------------- shard handoff
    # Cross-shard pool move (cook_tpu/shard/): the source shard forgets
    # the job (and its instance history), the destination shard adopts
    # it.  Each half emits into ITS OWN journal segment, so per-shard
    # replay reconstructs per-shard state exactly; the transaction layer
    # orders the two applies and acknowledges once.

    def shard_out_job(self, job_uuid: str):
        """Remove a job (and its instance records) from THIS shard.
        Returns (job, instances) as they stood, or (None, []) when the
        job is not here.  The emitted `job/shard-out` event carries the
        instance ids so journal replay removes the same set."""
        with self._lock:
            job = self.jobs.pop(job_uuid, None)
            if job is None:
                return None, []
            self.job_seq.pop(job_uuid, None)
            self._user_jobs.get(job.user, set()).discard(job_uuid)
            self._pool_pending.get(job.pool, set()).discard(job_uuid)
            self._pool_running.get(job.pool, set()).discard(job_uuid)
            instances = [self.instances.pop(tid)
                         for tid in job.instance_ids
                         if tid in self.instances]
            self._fan_out([self._emit(
                "job/shard-out",
                {"uuid": job_uuid, "pool": job.pool,
                 "instances": [i.task_id for i in instances]})])
            return job, instances

    def shard_in_job(self, job: Job, instances: Sequence[Instance] = (),
                     *, from_pool: str = "") -> None:
        """Adopt a job (post-move entity, pool already rewritten) and its
        instance history onto THIS shard.  Emits upsert events — an
        `instance/shard-in` per instance, then a `job/pool-moved`
        carrying the job — so replay and replication are pure upserts
        and downstream consumers (columnar index) see the same
        `job/pool-moved` a same-shard move produces."""
        with self._lock:
            self.jobs[job.uuid] = job
            self.job_seq.setdefault(job.uuid, len(self.job_seq))
            self._index_job(job, None)
            events = []
            for inst in instances:
                self.instances[inst.task_id] = inst
                events.append(self._emit(
                    "instance/shard-in",
                    {"task_id": inst.task_id, "job": job.uuid},
                    instance=inst))
            events.append(self._emit(
                "job/pool-moved",
                {"uuid": job.uuid, "from": from_pool, "to": job.pool,
                 "cross_shard": True},
                job=job))
            self._fan_out(events)

    def update_instance_progress(
        self, task_id: str, progress: int, message: str = ""
    ) -> bool:
        with self._lock:
            inst = self.instances.get(task_id)
            if inst is None:
                return False
            # progress must be monotone; stale updates are dropped
            # (reference: progress.clj progress-aggregator)
            if progress < inst.progress:
                return False
            new_inst = inst.with_(
                progress=progress, progress_message=message or inst.progress_message
            )
            self.instances[task_id] = new_inst
            self._fan_out([self._emit("instance/progress",
                                      {"task_id": task_id,
                                       "progress": progress},
                                      instance=new_inst)])
            return True

    def set_instance_output(
        self, task_id: str, *, exit_code: Optional[int] = None,
        sandbox_directory: Optional[str] = None,
    ) -> None:
        """Batched exit-code/sandbox publisher target (reference:
        mesos/sandbox.clj)."""
        with self._lock:
            inst = self.instances.get(task_id)
            if inst is None:
                return
            kw = {}
            if exit_code is not None:
                kw["exit_code"] = exit_code
            if sandbox_directory is not None:
                kw["sandbox_directory"] = sandbox_directory
            if kw:
                new_inst = inst.with_(**kw)
                self.instances[task_id] = new_inst
                self._fan_out([self._emit("instance/output",
                                          {"task_id": task_id},
                                          instance=new_inst)])

    # ------------------------------------------------------- share/quota/pool

    def set_pool(self, pool: Pool) -> None:
        with self._lock:
            self.pools[pool.name] = pool
            self._fan_out([self._emit("pool/set", {"name": pool.name},
                                      pool=pool)])

    def set_share(self, share: Share) -> None:
        with self._lock:
            self.shares[(share.user, share.pool)] = share
            self._fan_out([self._emit("share/set",
                                      {"user": share.user,
                                       "pool": share.pool},
                                      share=share)])

    def retract_share(self, user: str, pool: str) -> None:
        with self._lock:
            self.shares.pop((user, pool), None)
            self._fan_out([self._emit("share/retracted",
                                      {"user": user, "pool": pool})])

    def get_share(self, user: str, pool: str) -> Resources:
        """Share lookup with default-user fallback (share.clj:123).  A share
        is the DRU divisor; missing resources fall back to the default user's
        share, then to +inf (never constrains)."""
        with self._lock:
            own = self.shares.get((user, pool))
            default = self.shares.get((DEFAULT_USER, pool))
        inf = float("inf")
        base = default.resources if default else Resources(mem=inf, cpus=inf, gpus=inf)
        if own is None:
            return base
        r = own.resources
        return Resources(
            mem=r.mem if r.mem > 0 else base.mem,
            cpus=r.cpus if r.cpus > 0 else base.cpus,
            gpus=r.gpus if r.gpus > 0 else base.gpus,
        )

    def set_quota(self, quota: Quota) -> None:
        with self._lock:
            self.quotas[(quota.user, quota.pool)] = quota
            self._fan_out([self._emit("quota/set",
                                      {"user": quota.user,
                                       "pool": quota.pool},
                                      quota=quota)])

    def retract_quota(self, user: str, pool: str) -> None:
        with self._lock:
            self.quotas.pop((user, pool), None)
            self._fan_out([self._emit("quota/retracted",
                                      {"user": user, "pool": pool})])

    def update_dynamic_config(self, updates: dict[str, Any]) -> None:
        """Runtime-mutable config writes (rebalancer params, incremental
        configs) go through the event feed so they survive failover."""
        with self._lock:
            self.dynamic_config.update(updates)
            self._fan_out([self._emit("config/updated",
                                      {"updates": updates})])

    # ------------------------------------------------------ capacity ledger

    CAPACITY_DIMS = ("mem", "cpus", "gpus")
    # loan amounts below this are float dust, not capacity: entries whose
    # every dimension sits under it are dropped from the ledger
    CAPACITY_EPSILON = 1e-6

    def apply_capacity_moves(self, moves: Sequence[dict]) -> dict:
        """Apply a capacity plan's loan/reclaim moves to the ledger (the
        pool/capacity-delta txn op's handler target).  Each move is
        {"kind": "loan"|"reclaim", "from": lender, "to": borrower,
        "mem"/"cpus"/"gpus": amounts}; reclaims are clamped to what is
        actually outstanding so a replayed or racing plan can never
        drive the ledger negative.  Emits one pool/capacity event
        carrying the full post-transaction ledger (replay = upsert)."""
        with self._lock:
            for move in moves:
                kind = move.get("kind", "loan")
                key = (move["from"], move["to"])
                entry = self.capacity_ledger.get(
                    key, {d: 0.0 for d in self.CAPACITY_DIMS})
                for dim in self.CAPACITY_DIMS:
                    amount = float(move.get(dim, 0.0))
                    if kind == "reclaim":
                        entry[dim] = max(entry[dim] - amount, 0.0)
                    else:
                        entry[dim] = entry[dim] + amount
                if any(v > self.CAPACITY_EPSILON for v in entry.values()):
                    self.capacity_ledger[key] = entry
                else:
                    self.capacity_ledger.pop(key, None)
            ledger = self.encoded_capacity_ledger()
            event = self._emit("pool/capacity",
                               {"moves": [dict(m) for m in moves],
                                "ledger": ledger})
            self._fan_out([event])
            return {"applied": len(moves), "ledger": ledger}

    def encoded_capacity_ledger(self) -> list[dict]:
        """JSON-able ledger rows (snapshot / event / REST payloads)."""
        with self._lock:
            return [
                {"from": lender, "to": borrower, **amounts}
                for (lender, borrower), amounts
                in sorted(self.capacity_ledger.items())
            ]

    def set_capacity_ledger(self, entries: Sequence[dict]) -> None:
        """Replace the ledger wholesale (journal replay / snapshot
        restore — entries are the encoded post-transaction rows)."""
        with self._lock:
            self.capacity_ledger = {
                (e["from"], e["to"]): {d: float(e.get(d, 0.0))
                                       for d in self.CAPACITY_DIMS}
                for e in entries
            }

    def net_capacity_adjustment(self, pool: str) -> dict[str, float]:
        """Ledger-derived net elastic capacity for a pool: inbound loans
        minus outbound (negative = the pool is a net lender).  This is
        the declarative target clusters converge their elastic capacity
        to (ComputeCluster.scale)."""
        net = {d: 0.0 for d in self.CAPACITY_DIMS}
        with self._lock:
            for (lender, borrower), amounts in self.capacity_ledger.items():
                if borrower == pool:
                    for dim in self.CAPACITY_DIMS:
                        net[dim] += amounts[dim]
                if lender == pool:
                    for dim in self.CAPACITY_DIMS:
                        net[dim] -= amounts[dim]
        return net

    def outstanding_loans_from(self, pool: str) -> dict[str, dict[str, float]]:
        """borrower -> amounts currently on loan FROM `pool` (the
        reclaim-on-demand input)."""
        with self._lock:
            return {borrower: dict(amounts)
                    for (lender, borrower), amounts
                    in self.capacity_ledger.items() if lender == pool}

    def get_quota(self, user: str, pool: str) -> Quota:
        with self._lock:
            own = self.quotas.get((user, pool))
            if own is not None:
                return own
            default = self.quotas.get((DEFAULT_USER, pool))
            if default is not None:
                return Quota(user=user, pool=pool, resources=default.resources,
                             count=default.count)
        inf = float("inf")
        return Quota(user=user, pool=pool,
                     resources=Resources(mem=inf, cpus=inf, gpus=inf, disk=inf),
                     count=2**31)

    # ---------------------------------------------------------------- queries

    def job_instances(self, job_uuid: str) -> list[Instance]:
        job = self.jobs.get(job_uuid)
        if job is None:
            return []
        return [self.instances[tid] for tid in job.instance_ids
                if tid in self.instances]

    def pending_jobs(self, pool: str) -> list[Job]:
        with self._lock:
            return [self.jobs[u] for u in self._pool_pending.get(pool, ())]

    def running_jobs(self, pool: str) -> list[Job]:
        with self._lock:
            return [self.jobs[u] for u in self._pool_running.get(pool, ())]

    def running_instances(self, pool: str) -> list[Instance]:
        """Live (UNKNOWN or RUNNING) instances of running jobs in a pool."""
        out = []
        with self._lock:
            for job in self.running_jobs(pool):
                for inst in self.job_instances(job.uuid):
                    if not inst.status.terminal:
                        out.append(inst)
        return out

    def live_instances_of_job(self, job_uuid: str) -> list[Instance]:
        return [i for i in self.job_instances(job_uuid) if not i.status.terminal]

    def user_jobs(self, user: str) -> list[Job]:
        with self._lock:
            return [self.jobs[u] for u in self._user_jobs.get(user, ())]

    def user_usage(self, pool: str) -> dict[str, Resources]:
        """Per-user resources of currently-running jobs in a pool (the
        `user->usage` input of the match cycle, scheduler.clj:711)."""
        usage: dict[str, Resources] = {}
        with self._lock:
            for job in self.running_jobs(pool):
                usage[job.user] = usage.get(job.user, Resources()) + job.resources
        return usage

    def pending_count(self, pool: Optional[str] = None,
                      user: Optional[str] = None) -> int:
        """Queue lengths for queue limits (queue_limit.clj:92)."""
        with self._lock:
            if pool is not None:
                ids = self._pool_pending.get(pool, set())
                if user is None:
                    return len(ids)
                return sum(1 for u in ids if self.jobs[u].user == user)
            total = 0
            for ids in self._pool_pending.values():
                if user is None:
                    total += len(ids)
                else:
                    total += sum(1 for u in ids if self.jobs[u].user == user)
            return total

    # ------------------------------------------------------------- snapshots

    def snapshot_events(self) -> list[Event]:
        with self._lock:
            return list(self._events)
