"""Domain model: entities, state machine, failure reasons, job store."""
from cook_tpu.models.entities import (  # noqa: F401
    Application,
    Checkpoint,
    Container,
    DruMode,
    Group,
    GroupPlacementType,
    HostPlacement,
    Instance,
    InstanceStatus,
    Job,
    JobConstraint,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
    StragglerHandling,
    new_uuid,
)
from cook_tpu.models.store import Event, JobStore, TransactionVetoed  # noqa: F401
