"""Entity JSON codec shared by the snapshot and the journal.

Encoding is generic (dataclasses + enums); decoding is explicit per entity
type so schema drift fails loudly.  The reference gets this for free from
Datomic's serialization; here it is the durability boundary, so both the
snapshot (`persistence.snapshot`) and every journal entry's entity payload
go through these functions.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any

from cook_tpu.models.entities import (
    Application,
    Checkpoint,
    ConstraintOperator,
    Container,
    DruMode,
    Group,
    GroupPlacementType,
    HostPlacement,
    Instance,
    InstanceStatus,
    Job,
    JobConstraint,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
    StragglerHandling,
)


def encode(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: encode(v)
                for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    if isinstance(obj, float) and obj == float("inf"):
        return "Infinity"
    return obj


def dec_float(x):
    return float("inf") if x == "Infinity" else x


def dec_resources(d: dict) -> Resources:
    return Resources(
        mem=dec_float(d["mem"]), cpus=dec_float(d["cpus"]),
        gpus=dec_float(d["gpus"]), disk=dec_float(d.get("disk", 0.0)),
        ports=int(d.get("ports", 0)),
        disk_type=d.get("disk_type", ""),
    )


def dec_job(d: dict) -> Job:
    return Job(
        uuid=d["uuid"],
        user=d["user"],
        command=d["command"],
        name=d["name"],
        priority=d["priority"],
        max_retries=d["max_retries"],
        max_runtime_ms=d["max_runtime_ms"],
        expected_runtime_ms=d["expected_runtime_ms"],
        resources=dec_resources(d["resources"]),
        pool=d["pool"],
        state=JobState(d["state"]),
        submit_time_ms=d["submit_time_ms"],
        user_provided_env=tuple(map(tuple, d["user_provided_env"])),
        labels=tuple(map(tuple, d["labels"])),
        constraints=tuple(
            JobConstraint(attribute=c["attribute"],
                          operator=ConstraintOperator(c["operator"]),
                          pattern=c["pattern"])
            for c in d["constraints"]
        ),
        group_uuid=d["group_uuid"],
        container=(Container(**{**d["container"],
                                "volumes": tuple(d["container"]["volumes"]),
                                "ports": tuple(d["container"]["ports"]),
                                "env": tuple(map(tuple, d["container"]["env"]))})
                   if d["container"] else None),
        application=(Application(**d["application"])
                     if d.get("application") else None),
        checkpoint=(Checkpoint(
            mode=d["checkpoint"]["mode"],
            periodic_sec=d["checkpoint"]["periodic_sec"],
            preserve_paths=tuple(d["checkpoint"]["preserve_paths"]),
            location=d["checkpoint"]["location"],
        ) if d["checkpoint"] else None),
        disable_mea_culpa_retries=d["disable_mea_culpa_retries"],
        instance_ids=tuple(d["instance_ids"]),
        custom_executor=d["custom_executor"],
        last_waiting_start_time_ms=d["last_waiting_start_time_ms"],
        last_fenzo_placement_failure=d["last_fenzo_placement_failure"],
    )


def dec_instance(d: dict) -> Instance:
    d = dict(d)
    d["status"] = InstanceStatus(d["status"])
    return Instance(**d)


def dec_group(d: dict) -> Group:
    return Group(
        uuid=d["uuid"],
        name=d["name"],
        host_placement=HostPlacement(
            type=GroupPlacementType(d["host_placement"]["type"]),
            attribute=d["host_placement"]["attribute"],
            minimum=d["host_placement"]["minimum"],
        ),
        straggler_handling=StragglerHandling(**d["straggler_handling"]),
        job_uuids=tuple(d["job_uuids"]),
    )


def dec_pool(d: dict) -> Pool:
    return Pool(name=d["name"], purpose=d["purpose"], state=d["state"],
                dru_mode=DruMode(d["dru_mode"]))


def dec_share(d: dict) -> Share:
    return Share(user=d["user"], pool=d["pool"],
                 resources=dec_resources(d["resources"]),
                 reason=d["reason"])


def dec_quota(d: dict) -> Quota:
    return Quota(user=d["user"], pool=d["pool"],
                 resources=dec_resources(d["resources"]),
                 count=d["count"], reason=d.get("reason", ""),
                 launch_rate_saved=d.get("launch_rate_saved", 0.0),
                 launch_rate_per_minute=d.get("launch_rate_per_minute", 0.0))
