"""Real Kubernetes apiserver client behind the `KubeApi` boundary.

This is the production implementation the reference keeps in
cook.kubernetes.api (/root/reference/scheduler/src/cook/kubernetes/api.clj):

  * pod LIST + WATCH loop with resourceVersion tracking and re-list on
    gap — a watch that dies, or that the apiserver answers with 410 Gone
    (history compacted past our resourceVersion), falls back to a full
    re-list whose diff against the local view is replayed as synthetic
    events, then the watch resumes from the fresh resourceVersion
    (initialize-pod-watch, api.clj:449-570);
  * node listing (api.clj:572 keeps a node watch; offers here re-list
    nodes each cycle, which matches the synthesized-offer cadence);
  * pod manifest construction from the launch details — main container
    with resource requests/limits, env, sidecar file-server container,
    labels, priority class for synthetic pods (launch-pod, api.clj:2152);
  * bearer-token refresh: tokens on disk rotate (projected service
    account tokens), so the Authorization header re-reads the file when
    it changes or a TTL lapses
    (scheduler/java/.../TokenRefreshingAuthenticator.java).

Everything is stdlib (http.client / json / threading): the scheduler's
backend boundary is synchronous, and the watch is one long-lived streaming
GET per client, not a connection pool workload.
"""
from __future__ import annotations

import http.client
import json
import logging
import os
import ssl
import threading
import time
from typing import Callable, Optional
from urllib.parse import urlencode, urlsplit

from cook_tpu import faults
from cook_tpu.cluster.k8s import KubeApi, KubeNode, KubePod, PodPhase
from cook_tpu.utils.retry import RetryPolicy, call_with_retry

log = logging.getLogger(__name__)


class ApiError(OSError):
    """A non-2xx apiserver answer; `status` distinguishes client errors
    (4xx — never retried) from server errors (5xx — retryable on
    idempotent requests)."""

    def __init__(self, message: str, status: int):
        super().__init__(message)
        self.status = status


def _retryable_get_error(exc: BaseException) -> bool:
    """GET/LIST retry classification: transport failures and 5xx are
    transient; 4xx means the request itself is wrong (and a 410 WatchGap
    has its own re-list recovery)."""
    if isinstance(exc, WatchGap):
        return False
    if isinstance(exc, ApiError):
        return exc.status >= 500
    return isinstance(exc, OSError)

COOK_MANAGED_LABEL = "cook.scheduler/managed"
COOK_POOL_LABEL = "cook.scheduler/pool"
COOK_SYNTHETIC_LABEL = "cook.scheduler/synthetic"
SYNTHETIC_PRIORITY_CLASS = "cook-synthetic-pod"


class WatchGap(Exception):
    """The apiserver compacted history past our resourceVersion (HTTP 410
    or an ERROR event): the only recovery is a fresh LIST."""


_MIB = 1024.0 * 1024.0
_MEM_SUFFIXES = {
    # binary suffixes -> MiB
    "Ki": 1 / 1024, "Mi": 1.0, "Gi": 1024.0, "Ti": 1024.0**2,
    "Pi": 1024.0**3, "Ei": 1024.0**4,
    # decimal suffixes -> MiB
    "k": 1000 / _MIB, "K": 1000 / _MIB, "M": 1e6 / _MIB, "G": 1e9 / _MIB,
    "T": 1e12 / _MIB, "P": 1e15 / _MIB, "E": 1e18 / _MIB,
}


def parse_mem(q) -> float:
    """K8s memory quantity -> MiB.  An UNSUFFIXED quantity is BYTES (the
    apiserver's normalized form), not MiB."""
    if isinstance(q, (int, float)):
        return float(q) / _MIB
    s = str(q)
    for suffix, mult in _MEM_SUFFIXES.items():
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    if s.endswith("m"):  # millibytes: legal, absurd, normalize anyway
        return float(s[:-1]) / 1000 / _MIB
    return float(s) / _MIB


def parse_cpu(q) -> float:
    """K8s cpu quantity -> cores ("500m" -> 0.5, "4" -> 4.0)."""
    s = str(q)
    if s.endswith("m"):
        return float(s[:-1]) / 1000
    return float(s)


def format_mem(mem_mb: float) -> str:
    return f"{int(round(mem_mb))}Mi"


class TokenSource:
    """Re-reads a bearer-token file when its mtime changes or a TTL
    lapses (TokenRefreshingAuthenticator.java: periodic refresh so
    rotated projected tokens are picked up without restart)."""

    def __init__(self, path: Optional[str], ttl_s: float = 300.0):
        self.path = path
        self.ttl_s = ttl_s
        self._token: Optional[str] = None
        self._read_at = 0.0
        self._mtime = 0.0
        self._lock = threading.Lock()

    def token(self) -> Optional[str]:
        if self.path is None:
            return None
        with self._lock:
            now = time.time()
            try:
                mtime = os.path.getmtime(self.path)
            except OSError:
                return self._token
            if (self._token is None or mtime != self._mtime
                    or now - self._read_at > self.ttl_s):
                try:
                    with open(self.path) as f:
                        self._token = f.read().strip()
                    self._mtime = mtime
                    self._read_at = now
                except OSError:
                    pass
            return self._token


class HttpKubeApi(KubeApi):
    """KubeApi over a real apiserver.  `KubeCluster` runs unmodified
    against this class (same construction as with FakeKubeApi)."""

    def __init__(
        self,
        base_url: str,
        *,
        namespace: str = "default",
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure_skip_verify: bool = False,
        request_timeout_s: float = 30.0,
        watch_timeout_s: float = 300.0,
        relist_backoff_s: float = 1.0,
        default_image: str = "busybox:stable",
        file_server_port: int = 0,
        file_server_image: str = "",
        checkpoint_tools_image: str = "",
    ):
        self.base_url = base_url.rstrip("/")
        # apiservers behind path-prefixed proxies (kubeconfig allows
        # "https://host/k8s/clusters/x"): keep the prefix on every request
        self._path_prefix = urlsplit(self.base_url).path.rstrip("/")
        self.namespace = namespace
        self.tokens = TokenSource(token_file)
        self.ca_file = ca_file
        self.insecure_skip_verify = insecure_skip_verify
        self.request_timeout_s = request_timeout_s
        self.watch_timeout_s = watch_timeout_s
        self.relist_backoff_s = relist_backoff_s
        self.default_image = default_image
        self.file_server_port = file_server_port
        self.file_server_image = file_server_image
        self.checkpoint_tools_image = checkpoint_tools_image
        self._watch_cb: Optional[Callable[[str, Optional[KubePod]], None]] = None
        self._known: dict[str, KubePod] = {}  # watch-maintained local view
        self._synced = threading.Event()  # set after the first LIST
        # second, selector-free watch: the cluster-wide consumption view
        # feeding list_all_pods (the reference computes consumption from
        # watch state, api.clj:886 — re-LISTing every cluster pod per
        # offer cycle is the apiserver-hammering alternative)
        self._known_all: dict[str, KubePod] = {}
        self._all_synced = threading.Event()
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._all_watch_thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        # bounded retry for idempotent GET/LIST only (see _request);
        # deadline keeps attempts + backoff inside ~2 request budgets
        self._get_retry_policy = RetryPolicy(
            max_attempts=2, base_s=0.2, cap_s=1.0,
            deadline_s=request_timeout_s * 2)

    # ----------------------------------------------------------- plumbing

    def _connection(self, timeout: float) -> http.client.HTTPConnection:
        parts = urlsplit(self.base_url)
        if parts.scheme == "https":
            if self.insecure_skip_verify:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context(cafile=self.ca_file)
            return http.client.HTTPSConnection(
                parts.hostname, parts.port or 443, timeout=timeout,
                context=ctx)
        return http.client.HTTPConnection(
            parts.hostname, parts.port or 80, timeout=timeout)

    def _headers(self) -> dict:
        headers = {"Accept": "application/json",
                   "Content-Type": "application/json"}
        token = self.tokens.token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: Optional[dict] = None) -> dict:
        """One apiserver call.  Idempotent GET/LIST requests get a
        bounded, deadline-aware retry (2 attempts, utils/retry.py shared
        policy) on transport errors and 5xx; MUTATING requests stay
        single-shot — a retried POST whose first attempt actually landed
        would double-create, and the watch/expected-state machinery
        already reconciles uncertainty."""
        path = self._path_prefix + path
        if query:
            path = f"{path}?{urlencode(query)}"

        def once() -> dict:
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(faults.K8S_REQUEST, method=method,
                                   path=path)
            conn = self._connection(self.request_timeout_s)
            try:
                conn.request(
                    method, path,
                    body=json.dumps(body) if body is not None else None,
                    headers=self._headers())
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 410:
                    raise WatchGap(path)
                if resp.status >= 400:
                    raise ApiError(
                        f"{method} {path} -> {resp.status}: "
                        f"{data[:200]!r}", resp.status)
                return json.loads(data) if data else {}
            finally:
                conn.close()

        if method != "GET":
            return once()
        return call_with_retry(once, self._get_retry_policy,
                               op="k8s.get",
                               retry_on=_retryable_get_error)

    # ------------------------------------------------------------ parsing

    @staticmethod
    def _pod_from_manifest(manifest: dict) -> KubePod:
        meta = manifest.get("metadata", {})
        spec = manifest.get("spec", {})
        status = manifest.get("status", {})
        labels = meta.get("labels", {}) or {}
        mem = cpus = gpus = 0.0
        host_ports = []
        for container in spec.get("containers", []):
            requests = container.get("resources", {}).get("requests", {})
            mem += parse_mem(requests.get("memory", 0))
            cpus += parse_cpu(requests.get("cpu", 0))
            gpus += parse_cpu(requests.get("nvidia.com/gpu", 0)
                              or requests.get("google.com/tpu", 0))
            for port in container.get("ports", []) or []:
                if port.get("hostPort"):
                    host_ports.append(int(port["hostPort"]))
        try:
            phase = PodPhase(status.get("phase", "Pending"))
        except ValueError:
            # e.g. a phase this client predates: treat as Unknown (alive)
            phase = PodPhase.UNKNOWN
        reason = ""
        if phase == PodPhase.FAILED:
            reason = status.get("reason", "")
            for cs in status.get("containerStatuses", []):
                term = cs.get("state", {}).get("terminated")
                if term and term.get("reason"):
                    reason = reason or term["reason"]
            # normalize the common kubelet reasons to cook failure reasons
            reason = {
                "OOMKilled": "max-mem-exceeded",
                "Evicted": "preempted-by-cluster",
                "DeadlineExceeded": "max-runtime-exceeded",
            }.get(reason, reason or "command-executor-failed")
        # a deletionTimestamp means the pod is going away; the watch will
        # deliver DELETED next, the phase meanwhile stays as reported
        return KubePod(
            name=meta.get("name", ""),
            node_name=spec.get("nodeName", ""),
            mem=mem,
            cpus=cpus,
            gpus=gpus,
            phase=phase,
            synthetic=labels.get(COOK_SYNTHETIC_LABEL) == "true",
            failure_reason=reason,
            pool=labels.get(COOK_POOL_LABEL, ""),
            ports=tuple(host_ports),
        )

    @staticmethod
    def _node_from_manifest(manifest: dict) -> KubeNode:
        meta = manifest.get("metadata", {})
        status = manifest.get("status", {})
        spec = manifest.get("spec", {})
        alloc = status.get("allocatable", {}) or status.get("capacity", {})
        labels = dict(meta.get("labels", {}) or {})
        ready = any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in status.get("conditions", [])
        )
        # a NoSchedule taint makes the node unusable for new cook pods
        # (node-schedulable?, api.clj:782)
        tainted = any(
            t.get("effect") in ("NoSchedule", "NoExecute")
            for t in spec.get("taints", []) or []
            if not t.get("key", "").startswith("cook.scheduler/")
        )
        return KubeNode(
            name=meta.get("name", ""),
            mem=parse_mem(alloc.get("memory", 0)),
            cpus=parse_cpu(alloc.get("cpu", 0)),
            gpus=parse_cpu(alloc.get("nvidia.com/gpu", 0)
                           or alloc.get("google.com/tpu", 0)),
            pool=labels.get(COOK_POOL_LABEL, "default"),
            labels=tuple(sorted(labels.items())),
            schedulable=ready and not spec.get("unschedulable", False)
            and not tainted,
        )

    def pod_manifest(self, pod: KubePod) -> dict:
        """launch-pod parity (api.clj:2152): main container + optional
        sidecar file server, resource requests == limits, labels, node
        binding, synthetic priority class, checkpointing volume/init
        container/memory overhead (api.clj:934,1152-1198)."""
        # checkpoint env vars (mode/period/preserve-paths) arrive already
        # folded into pod.env by the matcher, and the memory overhead is
        # already in pod.mem — match-time padding keeps placement and the
        # launched pod in agreement (a backend-only pad would direct-bind
        # pods the kubelet rejects OutOfmemory on tight-fit nodes)
        checkpointing = bool(pod.checkpoint_mode)
        volume_mounts = []
        if checkpointing:
            volume_mounts = [{"name": "cook-checkpoint-tools",
                              "mountPath": "/opt/cook-checkpoint"}]
        containers = [{
            "name": "cook-job",
            "image": pod.image or self.default_image,
            "command": ["/bin/sh", "-c", pod.command] if pod.command else [],
            "env": [{"name": k, "value": str(v)} for k, v in pod.env],
            **({"ports": [{"containerPort": p, "hostPort": p}
                          for p in pod.ports]} if pod.ports else {}),
            **({"volumeMounts": volume_mounts} if volume_mounts else {}),
            "resources": {
                "requests": {
                    "memory": format_mem(pod.mem),
                    "cpu": str(pod.cpus),
                    **({"nvidia.com/gpu": str(int(pod.gpus))}
                       if pod.gpus else {}),
                },
                "limits": {
                    "memory": format_mem(pod.mem),
                    **({"nvidia.com/gpu": str(int(pod.gpus))}
                       if pod.gpus else {}),
                },
            },
        }]
        if self.file_server_port and not pod.synthetic:
            containers.append({
                "name": "cook-sidecar",
                "image": self.file_server_image or self.default_image,
                "command": ["cook-sidecar-fileserver", "--port",
                            str(self.file_server_port)],
                "ports": [{"containerPort": self.file_server_port}],
                "resources": {"requests": {"memory": "64Mi", "cpu": "0.1"}},
            })
        init_containers = []
        volumes = []
        if checkpointing:
            # the tools volume is populated by an init container from the
            # checkpoint image, so app images stay checkpoint-agnostic
            # (aux-cook-init-container-for-checkpoint, api.clj:934)
            volumes.append({"name": "cook-checkpoint-tools",
                            "emptyDir": {}})
            init_containers.append({
                "name": "aux-cook-init-container-for-checkpoint",
                "image": (self.checkpoint_tools_image
                          or self.default_image),
                "command": ["/bin/sh", "-c",
                            "cp -r /opt/checkpoint-tools/. "
                            "/opt/cook-checkpoint/ 2>/dev/null || true"],
                "volumeMounts": [{"name": "cook-checkpoint-tools",
                                  "mountPath": "/opt/cook-checkpoint"}],
            })
        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod.name,
                "namespace": self.namespace,
                "labels": {
                    COOK_MANAGED_LABEL: "true",
                    COOK_POOL_LABEL: pod.pool or "default",
                    **({COOK_SYNTHETIC_LABEL: "true"}
                       if pod.synthetic else {}),
                },
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": containers,
                **({"initContainers": init_containers}
                   if init_containers else {}),
                **({"volumes": volumes} if volumes else {}),
                # synthetic pods must be preemptible by real workloads
                **({"priorityClassName": SYNTHETIC_PRIORITY_CLASS}
                   if pod.synthetic else {}),
                # the scheduler already picked the node: bind directly
                **({"nodeName": pod.node_name} if pod.node_name else {}),
                "tolerations": [{
                    "key": "cook.scheduler/pool",
                    "operator": "Equal",
                    "value": pod.pool or "default",
                    "effect": "NoSchedule",
                }],
            },
        }
        return manifest

    # ------------------------------------------------------------ KubeApi

    def list_nodes(self) -> list[KubeNode]:
        body = self._request("GET", "/api/v1/nodes")
        return [self._node_from_manifest(item)
                for item in body.get("items", [])]

    def list_pods(self) -> list[KubePod]:
        # the watch maintains a coherent local view; re-LISTing on every
        # caller (reconcile, scan, autoscale, offer cycles) would hammer
        # the apiserver for data the stream already delivers
        if self._watch_thread is not None and self._synced.is_set():
            with self._lock:
                return list(self._known.values())
        pods, _ = self._list_raw(
            f"/api/v1/namespaces/{self.namespace}/pods",
            f"{COOK_MANAGED_LABEL}=true")
        return pods

    def list_all_pods(self) -> list[KubePod]:
        """Cluster-wide, label-unfiltered: offers must account for
        daemonset/system pods or a direct-bound pod gets rejected
        OutOfcpu by the kubelet (get-consumption, api.clj:886).  Served
        from the selector-free watch view once synced."""
        if self._all_watch_thread is not None and self._all_synced.is_set():
            with self._lock:
                return list(self._known_all.values())
        body = self._request("GET", "/api/v1/pods")
        return [self._pod_from_manifest(item)
                for item in body.get("items", [])]

    def _list_pods_raw(self) -> tuple[list[KubePod], str]:
        body = self._request(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods",
            query={"labelSelector": f"{COOK_MANAGED_LABEL}=true"})
        pods = [self._pod_from_manifest(item)
                for item in body.get("items", [])]
        rv = body.get("metadata", {}).get("resourceVersion", "")
        return pods, rv

    def create_pod(self, pod: KubePod) -> None:
        self._request("POST", f"/api/v1/namespaces/{self.namespace}/pods",
                      body=self.pod_manifest(pod))

    def delete_pod(self, name: str) -> None:
        try:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{self.namespace}/pods/{name}",
                body={"gracePeriodSeconds": 30})
        except ApiError as e:
            if e.status != 404:
                raise

    def set_pod_watch(self, callback) -> None:
        self._watch_cb = callback

    # -------------------------------------------------------------- watch

    def start(self, *, watch_all_pods: bool = True) -> None:
        """Start the watch loop threads: the cook-managed pod watch
        (initialize-pod-watch) and, by default, the selector-free
        cluster-wide watch that feeds `list_all_pods` consumption."""
        if self._watch_thread is None:
            self._stop.clear()
            self._watch_thread = threading.Thread(
                target=self._watch_loop,
                kwargs=dict(path=f"/api/v1/namespaces/{self.namespace}/pods",
                            selector=f"{COOK_MANAGED_LABEL}=true",
                            store=self._known, synced=self._synced,
                            emit=self._emit, what="pod"),
                name="kube-pod-watch", daemon=True)
            self._watch_thread.start()
        # not folded into the branch above: a second start(watch_all_pods=
        # True) after start(watch_all_pods=False) must still launch the
        # cluster-wide watch, or list_all_pods silently degrades to a full
        # cluster LIST per offer cycle
        if watch_all_pods and self._all_watch_thread is None:
            self._all_watch_thread = threading.Thread(
                target=self._watch_loop,
                kwargs=dict(path="/api/v1/pods", selector=None,
                            store=self._known_all, synced=self._all_synced,
                            emit=None, what="all-pods"),
                name="kube-all-pod-watch", daemon=True)
            self._all_watch_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._synced.clear()
        self._all_synced.clear()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5)
            self._watch_thread = None
        if self._all_watch_thread is not None:
            self._all_watch_thread.join(timeout=5)
            self._all_watch_thread = None

    def _emit(self, name: str, pod: Optional[KubePod]) -> None:
        if self._watch_cb is not None:
            try:
                self._watch_cb(name, pod)
            except Exception:
                log.exception("pod watch callback failed for %s", name)

    def _list_raw(self, path: str, selector: Optional[str]
                  ) -> tuple[list[KubePod], str]:
        query = {"labelSelector": selector} if selector else None
        body = self._request("GET", path, query=query)
        pods = [self._pod_from_manifest(item)
                for item in body.get("items", [])]
        rv = body.get("metadata", {}).get("resourceVersion", "")
        return pods, rv

    def _relist_and_diff(self, path, selector, store, synced, emit) -> str:
        """Fresh LIST; replay the diff against the local view as events —
        this is what closes a watch gap (missed events are reconstructed
        as state deltas, api.clj:449 re-list branch)."""
        pods, rv = self._list_raw(path, selector)
        fresh = {p.name: p for p in pods}
        with self._lock:
            gone = [name for name in store if name not in fresh]
            changed = [p for p in pods if store.get(p.name) != p]
            store.clear()
            store.update(fresh)
        synced.set()
        if emit is not None:
            for name in gone:
                emit(name, None)
            for pod in changed:
                emit(pod.name, pod)
        return rv

    def _watch_loop(self, *, path, selector, store, synced, emit,
                    what) -> None:
        while not self._stop.is_set():
            try:
                rv = self._relist_and_diff(path, selector, store, synced,
                                           emit)
                # a clean watch timeout resumes from the last event's (or
                # bookmark's) resourceVersion — only a gap or error pays
                # for a full re-list
                while not self._stop.is_set():
                    rv = self._stream_watch(rv, path, selector, store, emit)
            except WatchGap:
                log.info("%s watch gap (410): re-listing", what)
                continue
            except Exception as e:
                if self._stop.is_set():
                    return
                log.warning("%s watch error, re-listing: %s", what, e)
                self._stop.wait(self.relist_backoff_s)

    def _stream_watch(self, resource_version: str, path, selector, store,
                      emit) -> str:
        """One streaming watch connection; returns the last seen
        resourceVersion on clean timeout, raises WatchGap on 410."""
        params = {
            "watch": "1",
            "resourceVersion": resource_version,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(int(self.watch_timeout_s)),
        }
        if selector:
            params["labelSelector"] = selector
        query = urlencode(params)
        conn = self._connection(self.watch_timeout_s + 10)
        last_rv = resource_version
        try:
            conn.request("GET", f"{self._path_prefix}{path}?{query}",
                         headers=self._headers())
            resp = conn.getresponse()
            if resp.status == 410:
                raise WatchGap(resource_version)
            if resp.status >= 400:
                raise OSError(f"watch -> {resp.status}")
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return last_rv  # clean close (timeout): caller resumes
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                etype = event.get("type")
                obj = event.get("object", {})
                if etype == "ERROR":
                    # apiserver reports expiry as an in-stream Status
                    if obj.get("code") == 410:
                        raise WatchGap(resource_version)
                    raise OSError(f"watch ERROR: {obj}")
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    last_rv = rv
                if etype == "BOOKMARK":
                    continue
                pod = self._pod_from_manifest(obj)
                if etype == "DELETED":
                    with self._lock:
                        store.pop(pod.name, None)
                    if emit is not None:
                        emit(pod.name, None)
                else:  # ADDED / MODIFIED
                    with self._lock:
                        store[pod.name] = pod
                    if emit is not None:
                        emit(pod.name, pod)
            return last_rv
        finally:
            conn.close()
