"""Kubernetes-style compute cluster: synthesized offers + two-map controller.

Reference: cook.kubernetes.{compute-cluster,controller,api}
(/root/reference/scheduler/src/cook/kubernetes/):

  * K8s has no offer protocol, so offers are SYNTHESIZED from node capacity
    minus pod consumption (compute_cluster.clj:68-190, api.clj:874-905).
  * Task lifecycle is driven by a two-map reconciliation controller:
    `expected_state` (what Cook wants) vs `actual_state` (what the pod
    watch last reported); every event runs `process(task_id)`, a state
    machine whose (expected x actual) table decides launch/kill/delete/
    status-report actions (controller.clj:482-828).
  * Autoscaling submits SYNTHETIC placeholder pods so the cluster
    autoscaler provisions nodes (compute_cluster.clj:606), bounded by
    outstanding/total caps.
  * A periodic anti-entropy scan re-processes every known task
    (compute_cluster.clj:199-230).

The `KubeApi` boundary below is the piece a production deployment swaps
for a real apiserver client (watches + pod CRUD); `FakeKubeApi` is the
in-memory stand-in used by tests and the simulator.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, replace
from typing import Callable, Optional, Sequence

from cook_tpu.cluster.base import ComputeCluster, Offer, TaskSpec, subtract_ports
from cook_tpu.models.entities import InstanceStatus


class PodPhase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    # kubelet stopped reporting (node unreachable); the pod may still be
    # running — treated as alive until reconciliation or a real phase
    UNKNOWN = "Unknown"


@dataclass
class KubeNode:
    name: str
    mem: float
    cpus: float
    gpus: float = 0.0
    pool: str = "default"
    labels: tuple = ()
    schedulable: bool = True


@dataclass
class KubePod:
    name: str
    node_name: str
    mem: float
    cpus: float
    gpus: float = 0.0
    phase: PodPhase = PodPhase.PENDING
    synthetic: bool = False
    failure_reason: str = ""
    # launch details a real apiserver client needs to build the pod
    # manifest (launch-pod, api.clj:2152); FakeKubeApi ignores them
    command: str = ""
    image: str = ""
    env: tuple = ()
    pool: str = ""
    # host ports assigned to this pod (surfaced as hostPort entries)
    ports: tuple = ()
    # checkpointing (api.clj:934 init container + :1173 volume wiring)
    checkpoint_mode: str = ""
    checkpoint_periodic_sec: int = 0


class KubeApi:
    """The apiserver boundary (api.clj): node/pod listings, pod CRUD, and a
    pod-event callback (the watch)."""

    def list_nodes(self) -> Sequence[KubeNode]:
        raise NotImplementedError

    def list_pods(self) -> Sequence[KubePod]:
        """Cook-managed pods (the controller's domain)."""
        raise NotImplementedError

    def list_all_pods(self) -> Sequence[KubePod]:
        """EVERY pod consuming node resources — daemonsets/system pods
        included — for offer synthesis (get-consumption, api.clj:886).
        The controller must NOT see these (it kills unknown pods)."""
        return self.list_pods()

    def create_pod(self, pod: KubePod) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def set_pod_watch(self, callback: Callable[[str, Optional[KubePod]], None]
                      ) -> None:
        raise NotImplementedError


class FakeKubeApi(KubeApi):
    """Deterministic in-memory apiserver.  Pods scheduled onto the emptiest
    feasible node; `tick()` moves Pending->Running; tests complete/fail pods
    explicitly."""

    def __init__(self, nodes: Sequence[KubeNode] = ()):
        self.nodes: dict[str, KubeNode] = {n.name: n for n in nodes}
        self.pods: dict[str, KubePod] = {}
        self._watch: Optional[Callable] = None
        self._lock = threading.RLock()

    def list_nodes(self) -> list[KubeNode]:
        with self._lock:
            return list(self.nodes.values())

    def list_pods(self) -> list[KubePod]:
        with self._lock:
            return list(self.pods.values())

    def create_pod(self, pod: KubePod) -> None:
        with self._lock:
            if pod.name in self.pods:
                raise ValueError(f"pod {pod.name} exists")
            self.pods[pod.name] = pod
        self._notify(pod.name)

    def delete_pod(self, name: str) -> None:
        with self._lock:
            self.pods.pop(name, None)
        self._notify(name)

    def set_pod_watch(self, callback) -> None:
        self._watch = callback

    def _notify(self, name: str) -> None:
        if self._watch is not None:
            self._watch(name, self.pods.get(name))

    def add_node(self, node: KubeNode) -> None:
        """Node-pool grow (the piece a real deployment's node-pool
        controller does in response to a resize request)."""
        with self._lock:
            self.nodes[node.name] = node

    def set_schedulable(self, name: str, schedulable: bool) -> None:
        """Cordon/uncordon a node (loaned-out capacity is withheld by
        cordoning, never by killing pods)."""
        with self._lock:
            node = self.nodes.get(name)
            if node is not None:
                node.schedulable = schedulable

    # ----- test/simulation controls -----

    def tick(self) -> None:
        """Start all pending pods (the kubelet's work)."""
        with self._lock:
            starting = [p for p in self.pods.values()
                        if p.phase == PodPhase.PENDING]
            for pod in starting:
                self.pods[pod.name] = replace(pod, phase=PodPhase.RUNNING)
        for pod in starting:
            self._notify(pod.name)

    def finish_pod(self, name: str, *, failed: bool = False,
                   reason: str = "") -> None:
        with self._lock:
            pod = self.pods.get(name)
            if pod is None:
                return
            self.pods[name] = replace(
                pod,
                phase=PodPhase.FAILED if failed else PodPhase.SUCCEEDED,
                failure_reason=reason,
            )
        self._notify(name)

    def remove_node(self, name: str) -> list[str]:
        with self._lock:
            self.nodes.pop(name, None)
            lost = [p.name for p in self.pods.values() if p.node_name == name]
            for pname in lost:
                self.pods[pname] = replace(
                    self.pods[pname], phase=PodPhase.FAILED,
                    failure_reason="node-removed",
                )
        for pname in lost:
            self._notify(pname)
        return lost


class ExpectedState(enum.Enum):
    """What Cook wants for a task (controller.clj cook-expected-state)."""

    STARTING = "starting"
    RUNNING = "running"
    COMPLETED = "completed"
    KILLED = "killed"
    MISSING = "missing"


class KubeCluster(ComputeCluster):
    def __init__(self, name: str, api: KubeApi, clock: Callable[[], int],
                 *, synthetic_pod_limits: Optional[dict] = None,
                 file_server_port: int = 8000,
                 host_port_range: tuple = (31000, 32767)):
        super().__init__(name)
        self.file_server_port = file_server_port
        # offerable hostPort range per node (K8s has no port offers; jobs
        # requesting ports get hostPorts from this window, mirroring the
        # NodePort service range)
        self.host_port_range = host_port_range
        self.api = api
        self.clock = clock
        self.expected: dict[str, ExpectedState] = {}
        self.task_pods: dict[str, KubePod] = {}  # task id -> last actual
        # kill tombstones consulted by launch_tasks: process() pops the
        # KILLED expected entry as soon as the kill is reported, so a
        # batch still queued on the async launch executor needs this
        # longer-lived marker or it would create a pod for a task the
        # store already drove terminal (a leaked pod — nothing would
        # ever delete it).  FIFO-bounded; consumed on launch skip.
        from collections import OrderedDict

        self._killed_tombstones: "OrderedDict[str, None]" = OrderedDict()
        self.status_callback = None
        self.synthetic_limits = {
            "max-pods-outstanding": 128,
            "max-total-pods": 32_000,
            **(synthetic_pod_limits or {}),
        }
        self._synthetic_seq = 0
        self._lock = threading.RLock()
        # elastic capacity (cook_tpu/elastic/): node-pool resize requests
        # issued by scale(), newest last (bounded); nodes cordoned to
        # withhold loaned-out capacity, per pool
        self.resize_requests: list[dict] = []
        self._last_requested: dict[str, dict] = {}
        self._cordoned_for_loan: dict[str, set[str]] = {}
        api.set_pod_watch(self._pod_event)

    # ------------------------------------------------------------- offers

    def pending_offers(self, pool: str) -> list[Offer]:
        """Synthesize offers: capacity minus consumption per schedulable
        node (generate-offers)."""
        consumption: dict[str, list[float]] = {}
        ports_taken: dict[str, set] = {}
        for pod in self.api.list_all_pods():
            if pod.phase in (PodPhase.PENDING, PodPhase.RUNNING,
                             PodPhase.UNKNOWN):
                c = consumption.setdefault(pod.node_name, [0.0, 0.0, 0.0])
                c[0] += pod.mem
                c[1] += pod.cpus
                c[2] += pod.gpus
                if pod.ports:
                    ports_taken.setdefault(pod.node_name,
                                           set()).update(pod.ports)
        offers = []
        for node in self.api.list_nodes():
            if not node.schedulable or node.pool != pool:
                continue
            used = consumption.get(node.name, [0.0, 0.0, 0.0])
            offers.append(Offer(
                node_id=node.name,
                hostname=node.name,
                mem=node.mem - used[0],
                cpus=node.cpus - used[1],
                gpus=node.gpus - used[2],
                attributes=node.labels,
                total_mem=node.mem,
                total_cpus=node.cpus,
                ports=subtract_ports((self.host_port_range,),
                                     ports_taken.get(node.name, ())),
            ))
        return offers

    # ----------------------------------------------------- task lifecycle

    def launch_tasks(self, pool: str, specs: Sequence[TaskSpec]) -> None:
        """Create one pod per spec.  Safe under the async launch contract
        (ComputeCluster.launch_tasks_async): `expected` mutations are
        lock-guarded, per-spec API errors are reported as
        pod-submission-api-error without aborting the batch, and the
        status callback chain never runs while this cluster's internal
        lock is held."""
        for spec in specs:
            with self._lock:
                if (spec.task_id in self._killed_tombstones
                        or self.expected.get(spec.task_id)
                        is ExpectedState.KILLED):
                    # a kill raced this batch while it sat in the async
                    # launch queue (the kill-lock only excludes kills
                    # during the backend call itself): the store
                    # instance is already terminal, so creating the pod
                    # now would leak it — nothing would ever delete it
                    self._killed_tombstones.pop(spec.task_id, None)
                    continue
                self.expected[spec.task_id] = ExpectedState.STARTING
            try:
                self.api.create_pod(KubePod(
                    name=spec.task_id,
                    node_name=spec.node_id,
                    mem=spec.mem,
                    cpus=spec.cpus,
                    gpus=spec.gpus,
                    command=spec.command,
                    image=spec.container_image,
                    env=tuple(spec.env),
                    pool=pool,
                    ports=tuple(spec.ports),
                    checkpoint_mode=spec.checkpoint_mode,
                    checkpoint_periodic_sec=spec.checkpoint_periodic_sec,
                ))
            except Exception:
                self._report(spec.task_id, InstanceStatus.FAILED,
                             "pod-submission-api-error")
                with self._lock:
                    self.expected.pop(spec.task_id, None)

    def kill_task(self, task_id: str) -> None:
        with self._lock:
            self.expected[task_id] = ExpectedState.KILLED
            if len(self._killed_tombstones) >= 10_000:
                self._killed_tombstones.popitem(last=False)
            self._killed_tombstones[task_id] = None
        self.process(task_id)

    # -------------------------------------------------------- controller

    def _pod_event(self, name: str, pod: Optional[KubePod]) -> None:
        """pod-update / pod-deleted (controller.clj:752-765)."""
        if name.startswith("synthetic-"):
            return
        with self._lock:
            if pod is not None:
                self.task_pods[name] = pod
            else:
                self.task_pods.pop(name, None)
        self.process(name)

    def process(self, task_id: str) -> None:
        """The (expected x actual) state machine (controller.clj:482)."""
        with self._lock:
            expected = self.expected.get(task_id, ExpectedState.MISSING)
            pod = self.task_pods.get(task_id)
        phase = pod.phase if pod is not None else None

        if expected == ExpectedState.KILLED:
            if pod is not None and phase in (PodPhase.PENDING,
                                             PodPhase.RUNNING,
                                             PodPhase.UNKNOWN):
                self.api.delete_pod(task_id)
            self._report(task_id, InstanceStatus.FAILED, "killed-by-user")
            with self._lock:
                self.expected.pop(task_id, None)
            return

        if expected in (ExpectedState.STARTING, ExpectedState.RUNNING):
            if pod is None:
                # pod vanished: mea-culpa failure, scheduler may retry
                self._report(task_id, InstanceStatus.FAILED,
                             "could-not-reconstruct-state")
                with self._lock:
                    self.expected.pop(task_id, None)
            elif phase == PodPhase.RUNNING:
                if expected == ExpectedState.STARTING:
                    with self._lock:
                        self.expected[task_id] = ExpectedState.RUNNING
                    self._report(task_id, InstanceStatus.RUNNING, None)
            elif phase == PodPhase.SUCCEEDED:
                self._report(task_id, InstanceStatus.SUCCESS, "normal-exit")
                with self._lock:
                    self.expected[task_id] = ExpectedState.COMPLETED
                self.api.delete_pod(task_id)
            elif phase == PodPhase.FAILED:
                reason = pod.failure_reason or "command-executor-failed"
                self._report(task_id, InstanceStatus.FAILED, reason)
                with self._lock:
                    self.expected[task_id] = ExpectedState.COMPLETED
                self.api.delete_pod(task_id)
            return

        if expected == ExpectedState.MISSING and pod is not None \
                and not pod.synthetic:
            # unknown pod owned by us: kill it (controller's orphan branch)
            if phase in (PodPhase.PENDING, PodPhase.RUNNING,
                         PodPhase.UNKNOWN):
                self.api.delete_pod(task_id)

    def scan_all(self) -> None:
        """Anti-entropy scan (scan-process, compute_cluster.clj:199-230)."""
        with self._lock:
            known = set(self.expected) | set(self.task_pods)
        for pod in self.api.list_pods():
            known.add(pod.name)
            with self._lock:
                if not pod.synthetic:
                    self.task_pods[pod.name] = pod
        for task_id in sorted(known):
            if not task_id.startswith("synthetic-"):
                self.process(task_id)

    def determine_expected_state_on_startup(self, live_task_ids: set[str]
                                            ) -> None:
        """Failover recovery (compute_cluster.clj:269): rebuild the expected
        map from the store's live instances."""
        with self._lock:
            for task_id in live_task_ids:
                self.expected.setdefault(task_id, ExpectedState.RUNNING)
        self.scan_all()

    # -------------------------------------------------------- autoscaling

    def autoscaling(self, pool: str) -> bool:
        return True

    def autoscale(self, pool: str, pending_demand: Sequence[TaskSpec]) -> None:
        """Submit synthetic placeholder pods for unmatched demand so the
        cluster autoscaler provisions capacity (autoscale!,
        compute_cluster.clj:606)."""
        outstanding = sum(
            1
            for p in self.api.list_pods()
            if p.synthetic and p.phase == PodPhase.PENDING
        )
        budget = self.synthetic_limits["max-pods-outstanding"] - outstanding
        for spec in list(pending_demand)[: max(budget, 0)]:
            self._synthetic_seq += 1
            self.api.create_pod(KubePod(
                name=f"synthetic-{self._synthetic_seq}",
                node_name="",  # unschedulable until the autoscaler adds nodes
                mem=spec.mem,
                cpus=spec.cpus,
                gpus=spec.gpus,
                synthetic=True,
            ))

    def synthetic_pods(self) -> list[KubePod]:
        return [p for p in self.api.list_pods() if p.synthetic]

    # --------------------------------------------------- elastic capacity

    # fallback per-node shape when a pool has no template node to copy
    ELASTIC_NODE_SHAPE = {"mem": 65536.0, "cpus": 32.0, "gpus": 0.0}
    MAX_RESIZE_REQUESTS = 256

    def supports_scale(self) -> bool:
        return True

    def _node_busy(self, name: str) -> bool:
        return any(
            p.node_name == name
            and p.phase in (PodPhase.PENDING, PodPhase.RUNNING,
                            PodPhase.UNKNOWN)
            for p in self.api.list_all_pods()
        )

    def scale(self, pool: str, adjustment: dict) -> dict:
        """Elastic capacity as a NODE-POOL RESIZE REQUEST (the k8s
        analog of Aryl's loaned nodes): positive targets grow the pool
        with `elastic-{pool}-{i}` nodes sized like the pool's template
        node; negative targets cordon empty nodes so the loaned-out
        capacity stops being offered — pods are never killed (reclaim
        is non-disruptive; a cordoned node drains as work finishes).
        The request itself is always recorded (`resize_requests`) so a
        deployment whose node-pool controller lives outside this
        process can act on it; against an api exposing node CRUD
        (FakeKubeApi) it is applied immediately."""
        adj = {d: float(adjustment.get(d, 0.0))
               for d in ("mem", "cpus", "gpus")}
        # the request ring is for an EXTERNAL node-pool controller: only
        # target changes are worth recording — the planner reconciles
        # every interval, and a stream of unchanged/all-zero requests
        # would rotate real ones out of the bounded ring.  Convergence
        # work below still runs every call (a prior shrink may have
        # skipped then-busy nodes that have since drained).
        if self._last_requested.get(pool) != adj and (
                any(adj.values()) or pool in self._last_requested):
            self.resize_requests.append(
                {"pool": pool, "adjustment": dict(adj),
                 "t_ms": self.clock()})
            del self.resize_requests[:-self.MAX_RESIZE_REQUESTS]
            self._last_requested[pool] = dict(adj)

        prefix = f"elastic-{pool}-"
        nodes = self.api.list_nodes()
        regular = sorted((n for n in nodes
                          if n.pool == pool and not n.name.startswith(prefix)),
                         key=lambda n: n.name)
        # ownership = prefix AND pool: with pools "gpu" and "gpu-west",
        # "elastic-gpu-west-0" startswith "elastic-gpu-" — the prefix
        # alone would let pool "gpu" shrink away gpu-west's loaned nodes
        elastic = sorted((n for n in nodes
                          if n.pool == pool and n.name.startswith(prefix)),
                         key=lambda n: n.name)
        template = (regular[0] if regular else None)
        shape = ({"mem": template.mem, "cpus": template.cpus,
                  "gpus": template.gpus} if template is not None
                 else dict(self.ELASTIC_NODE_SHAPE))

        # grow: enough elastic nodes to cover every positive dimension
        want = 0
        for dim in adj:
            if adj[dim] > 0 and shape.get(dim, 0.0) > 0:
                want = max(want, -(-adj[dim] // shape[dim]))
        want = int(want)
        add_node = getattr(self.api, "add_node", None)
        if add_node is not None:
            seq = len(elastic)
            while len(elastic) < want:
                node = KubeNode(name=f"{prefix}{seq}", mem=shape["mem"],
                                cpus=shape["cpus"], gpus=shape["gpus"],
                                pool=pool)
                add_node(node)
                elastic.append(node)
                seq += 1
            # shrink: drop only EMPTY elastic nodes (drain, don't kill)
            remove_node = getattr(self.api, "remove_node", None)
            for node in elastic[want:]:
                if remove_node is not None and not self._node_busy(node.name):
                    remove_node(node.name)

        # negative dims: cordon empty regular nodes until the withheld
        # capacity covers the loaned-out amount; uncordon on reclaim
        set_schedulable = getattr(self.api, "set_schedulable", None)
        if set_schedulable is not None:
            need = {d: max(-v, 0.0) for d, v in adj.items()}
            cordoned = self._cordoned_for_loan.setdefault(pool, set())
            for name in sorted(cordoned):
                set_schedulable(name, True)
            cordoned.clear()
            if any(v > 0 for v in need.values()):
                for node in regular:
                    if all(v <= 0 for v in need.values()):
                        break
                    if self._node_busy(node.name):
                        continue
                    set_schedulable(node.name, False)
                    cordoned.add(node.name)
                    need["mem"] -= node.mem
                    need["cpus"] -= node.cpus
                    need["gpus"] -= node.gpus
        return adj

    # ------------------------------------------------------------- misc

    def num_tasks_on_host(self, hostname: str) -> int:
        return sum(
            1 for p in self.api.list_pods()
            if p.node_name == hostname
            and p.phase in (PodPhase.PENDING, PodPhase.RUNNING,
                            PodPhase.UNKNOWN)
            and not p.synthetic
        )

    def retrieve_sandbox_url_path(self, task_id: str) -> str:
        """The pod sidecar file-server URL (reference: the sidecar serves
        the Mesos files/ API on a well-known port inside each pod)."""
        pod = self.task_pods.get(task_id)
        if pod is None or not pod.node_name:
            return ""
        return f"http://{pod.node_name}:{self.file_server_port}"

    @property
    def running(self):
        """Task view for reconciliation (Scheduler.reconcile)."""
        return {
            p.name: p for p in self.api.list_pods()
            if not p.synthetic and p.phase in (PodPhase.PENDING,
                                               PodPhase.RUNNING,
                                               PodPhase.UNKNOWN)
        }

    def _report(self, task_id, status, reason) -> None:
        if self.status_callback is not None:
            self.status_callback(task_id, status, reason)
