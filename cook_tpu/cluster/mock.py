"""In-memory mock compute cluster: the simulator backbone.

Plays the role of the reference's in-memory Mesos master mock
(/root/reference/scheduler/src/cook/mesos/mesos_mock.clj): hosts with fixed
capacity hand out offers of their spare resources; launched tasks consume
resources and complete (success) after their simulated runtime when virtual
time advances; kills release resources immediately.  Status transitions are
reported to a callback, exactly like a real backend's watch/callback feed.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from cook_tpu.cluster.base import ComputeCluster, Offer, TaskSpec, subtract_ports
from cook_tpu.models.entities import InstanceStatus


@dataclass
class MockHost:
    node_id: str
    hostname: str
    mem: float
    cpus: float
    gpus: float = 0.0
    disk: float = 0.0
    attributes: tuple = ()
    pool: str = "default"
    # offerable port ranges ((begin, end), ...) inclusive — Mesos-style
    # port resources (mesos_mock.clj:162)
    ports: tuple = ()


@dataclass
class _RunningTask:
    spec: TaskSpec
    started_ms: int
    ends_ms: int  # virtual completion time


StatusCallback = Callable[[str, InstanceStatus, Optional[str]], None]
# (task_id, new_status, reason_name)


class MockCluster(ComputeCluster):
    """Deterministic fake backend driven by a virtual clock."""

    def __init__(self, name: str, hosts: Sequence[MockHost],
                 clock: Callable[[], int], *,
                 default_runtime_ms: int = 60_000,
                 sandbox_url_fn: Optional[Callable[[str], str]] = None):
        super().__init__(name)
        self.hosts = {h.node_id: h for h in hosts}
        self.clock = clock
        self.default_runtime_ms = default_runtime_ms
        self.running: dict[str, _RunningTask] = {}
        # async launch workers (ComputeCluster.launch_tasks_async) mutate
        # `running` off the scheduler thread; this lock keeps offer scans
        # from iterating a dict mid-mutation.  Status callbacks are always
        # emitted OUTSIDE it — the callback chain re-enters the store (and
        # from there possibly this cluster's kill path), and holding the
        # lock across it would invert lock order against kill_lock/store
        self._mutate_lock = threading.RLock()
        # kills that raced a launch batch still queued (or about to be
        # queued — the kill can land between the match transaction and
        # launch_tasks_async) on the async executor: the launch must not
        # resurrect them.  Recorded unconditionally; FIFO-ordered so the
        # capacity bound evicts the OLDEST (stalest) entry
        self._killed_before_launch: "OrderedDict[str, None]" = OrderedDict()
        self.status_callback: Optional[StatusCallback] = None
        self.launched_count = 0
        self.killed_count = 0
        self.sandbox_url_fn = sandbox_url_fn
        # elastic capacity adjustments per pool (scale()): positive nets
        # materialize as a synthetic borrowed-capacity host, negative
        # nets are withheld from the pool's offers
        self.pool_adjust: dict[str, dict] = {}

    def retrieve_sandbox_url_path(self, task_id: str) -> str:
        if self.sandbox_url_fn is not None:
            return self.sandbox_url_fn(task_id)
        return ""

    # ------------------------------------------------------------- offers

    def _running_snapshot(self) -> list[_RunningTask]:
        with self._mutate_lock:
            return list(self.running.values())

    def pending_offers(self, pool: str) -> list[Offer]:
        offers = []
        # a net-lender pool's loaned-out capacity is withheld from its
        # offers (scale() with negative dims): walk the deficit down
        # across hosts in stable order so the matcher simply sees less
        # spare — running tasks are untouched (loans move FREE capacity)
        adj = self.pool_adjust.get(pool, {})
        deficit = {d: max(-float(adj.get(d, 0.0)), 0.0)
                   for d in ("mem", "cpus", "gpus")}
        with self._mutate_lock:
            hosts = list(self.hosts.values())
            running = list(self.running.values())
        # ONE pass over the running tasks builds per-node usage and taken
        # ports — per-host _host_used/_free_port_ranges calls would make
        # the offer scan O(hosts x tasks) in snapshot copies alone
        used: dict[str, list[float]] = {}
        ports_taken: dict[str, set] = {}
        for rt in running:
            u = used.setdefault(rt.spec.node_id, [0.0, 0.0, 0.0, 0.0])
            u[0] += rt.spec.mem
            u[1] += rt.spec.cpus
            u[2] += rt.spec.gpus
            u[3] += rt.spec.disk
            if rt.spec.ports:
                ports_taken.setdefault(rt.spec.node_id,
                                       set()).update(rt.spec.ports)
        for h in hosts:
            if h.pool != pool:
                continue
            um, uc, ug, ud = used.get(h.node_id, (0.0, 0.0, 0.0, 0.0))
            free = {"mem": max(h.mem - um, 0.0),
                    "cpus": max(h.cpus - uc, 0.0),
                    "gpus": max(h.gpus - ug, 0.0)}
            for dim in free:
                take = min(deficit[dim], free[dim])
                free[dim] -= take
                deficit[dim] -= take
            offers.append(
                Offer(
                    node_id=h.node_id,
                    hostname=h.hostname,
                    mem=free["mem"],
                    cpus=free["cpus"],
                    gpus=free["gpus"],
                    disk=max(h.disk - ud, 0.0),
                    attributes=h.attributes,
                    total_mem=h.mem,
                    total_cpus=h.cpus,
                    ports=(subtract_ports(
                        h.ports, ports_taken.get(h.node_id, ()))
                        if h.ports else ()),
                )
            )
        return offers

    # ------------------------------------------------------ elastic scale

    ELASTIC_NODE_PREFIX = "elastic@"

    def supports_scale(self) -> bool:
        return True

    def scale(self, pool: str, adjustment: dict) -> dict:
        """Converge the pool's elastic capacity to the declarative
        target: positive dims materialize as one synthetic
        `elastic@{pool}` host holding the borrowed capacity (launchable
        like any host); negative dims are withheld from the pool's
        offers in pending_offers.  A reclaimed-away elastic host still
        running tasks is drained (capacity zeroed, tasks finish) rather
        than yanked — reclaim is non-disruptive by design."""
        adj = {d: float(adjustment.get(d, 0.0))
               for d in ("mem", "cpus", "gpus")}
        self.pool_adjust[pool] = adj
        node_id = self.ELASTIC_NODE_PREFIX + pool
        positive = {d: max(v, 0.0) for d, v in adj.items()}
        host = self.hosts.get(node_id)
        if any(v > 0 for v in positive.values()):
            if host is None:
                self.hosts[node_id] = MockHost(
                    node_id=node_id, hostname=node_id,
                    mem=positive["mem"], cpus=positive["cpus"],
                    gpus=positive["gpus"], pool=pool,
                )
            else:
                host.mem = positive["mem"]
                host.cpus = positive["cpus"]
                host.gpus = positive["gpus"]
        elif host is not None:
            if any(rt.spec.node_id == node_id
                   for rt in self._running_snapshot()):
                host.mem = host.cpus = host.gpus = 0.0  # drain
            else:
                self.hosts.pop(node_id, None)
        return adj

    # ------------------------------------------------------ task lifecycle

    def launch_tasks(self, pool: str, specs: Sequence[TaskSpec]) -> None:
        now = self.clock()
        for spec in specs:
            with self._mutate_lock:
                if spec.task_id in self._killed_before_launch:
                    # a kill raced this batch in the async launch queue;
                    # the killer already drove the store transition —
                    # launching now would resurrect a terminal task
                    self._killed_before_launch.pop(spec.task_id, None)
                    continue
                known = spec.node_id in self.hosts
                if known:
                    runtime = (spec.expected_runtime_ms
                               or self.default_runtime_ms)
                    self.running[spec.task_id] = _RunningTask(
                        spec=spec, started_ms=now, ends_ms=now + runtime
                    )
                    self.launched_count += 1
            if known:
                self._report(spec.task_id, InstanceStatus.RUNNING, None)
            else:
                self._report(spec.task_id, InstanceStatus.FAILED,
                             "scheduling-failed-on-host")

    def kill_task(self, task_id: str) -> None:
        with self._mutate_lock:
            rt = self.running.pop(task_id, None)
            self.killed_count += 1
            if rt is None:
                if len(self._killed_before_launch) >= 10_000:
                    self._killed_before_launch.popitem(last=False)
                self._killed_before_launch[task_id] = None
        if rt is not None:
            self._report(task_id, InstanceStatus.FAILED, "killed-by-user")

    def num_tasks_on_host(self, hostname: str) -> int:
        return sum(1 for rt in self._running_snapshot()
                   if rt.spec.hostname == hostname)

    # --------------------------------------------------------- virtual time

    def advance_to(self, now_ms: int) -> list[str]:
        """Complete every task whose simulated runtime has elapsed; returns
        the completed task ids (mesos_mock.clj `complete-task!`)."""
        with self._mutate_lock:
            done = [tid for tid, rt in self.running.items()
                    if rt.ends_ms <= now_ms]
            for tid in done:
                self.running.pop(tid)
        for tid in sorted(done):  # deterministic order
            self._report(tid, InstanceStatus.SUCCESS, "normal-exit")
        return done

    def fail_task(self, task_id: str, reason: str = "unknown") -> None:
        """Test/fault-injection hook."""
        with self._mutate_lock:
            removed = self.running.pop(task_id, None)
        if removed is not None:
            self._report(task_id, InstanceStatus.FAILED, reason)

    def remove_host(self, node_id: str) -> list[str]:
        """Simulate node loss: fail all its tasks mea-culpa."""
        with self._mutate_lock:
            lost = [tid for tid, rt in self.running.items()
                    if rt.spec.node_id == node_id]
            for tid in lost:
                self.running.pop(tid)
            self.hosts.pop(node_id, None)
        for tid in sorted(lost):
            self._report(tid, InstanceStatus.FAILED, "node-removed")
        return lost

    def _report(self, task_id: str, status: InstanceStatus,
                reason: Optional[str]) -> None:
        if self.status_callback is not None:
            self.status_callback(task_id, status, reason)
