"""The ComputeCluster boundary: the pluggable backend interface.

Mirrors the reference's `ComputeCluster` protocol
(/root/reference/scheduler/src/cook/compute_cluster.clj:27-112): offers in,
launches/kills out, autoscaling, draining, and the launch/kill read-write
lock that closes the kill-before-launch race the reference documents at
compute_cluster.clj:86-112 (a kill observed while a launch is mid-flight
must not be lost: kills take the write side, launches the read side).
"""
from __future__ import annotations

import abc
import enum
import logging
import threading
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from cook_tpu import faults
from cook_tpu.faults.breaker import BreakerParams, CircuitBreaker

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Offer:
    """Available resources on one node.  K8s-style backends synthesize these
    from capacity minus consumption (kubernetes/compute_cluster.clj:68-190);
    mock/Mesos-style backends hand them out directly."""

    node_id: str
    hostname: str
    mem: float
    cpus: float
    gpus: float = 0.0
    disk: float = 0.0
    attributes: tuple = ()       # ((key, value), ...) host attributes
    total_mem: float = 0.0       # capacity, for binpacking fitness
    total_cpus: float = 0.0
    # free port ranges ((begin, end), ...) inclusive — Mesos-style offers
    # carry port resources (mesos_mock.clj:162 range arithmetic)
    ports: tuple = ()

    def port_count(self) -> int:
        return sum(e - b + 1 for b, e in self.ports)


def subtract_ports(ranges: tuple, taken) -> tuple:
    """Free (begin, end) ranges minus taken ports — interval arithmetic,
    O(ranges + taken log taken), never iterating individual ports
    (the range subtraction of mesos_mock.clj:184)."""
    if not taken:
        return tuple(ranges)
    import bisect

    taken_sorted = sorted(set(taken))
    out = []
    for begin, end in ranges:
        cur = begin
        i = bisect.bisect_left(taken_sorted, begin)
        while i < len(taken_sorted) and taken_sorted[i] <= end:
            p = taken_sorted[i]
            if p > cur:
                out.append((cur, p - 1))
            cur = p + 1
            i += 1
        if cur <= end:
            out.append((cur, end))
    return tuple(out)

    def attr_dict(self) -> dict:
        return dict(self.attributes)


@dataclass(frozen=True)
class TaskSpec:
    """What a backend needs to launch one task."""

    task_id: str
    job_uuid: str
    user: str
    command: str
    mem: float
    cpus: float
    gpus: float
    node_id: str
    hostname: str
    disk: float = 0.0
    env: tuple = ()
    container_image: str = ""
    expected_runtime_ms: int = 0
    # concrete ports assigned from the offer's ranges (mesos/task.clj
    # port assignment; surfaced to the task as PORT0..PORTn env vars)
    ports: tuple = ()
    # job checkpointing (schema.clj:84 :job/checkpoint): backends wire
    # mode/period into the task sandbox (k8s: tools volume + init
    # container + env, api.clj:934,1173-1198)
    checkpoint_mode: str = ""            # "" = checkpointing off
    checkpoint_periodic_sec: int = 0
    checkpoint_preserve_paths: tuple = ()


class ClusterState(enum.Enum):
    """Dynamic cluster config state machine
    (compute_cluster.clj:340-359,450-530): running accepts new work,
    draining only finishes existing work, deleted is gone."""

    RUNNING = "running"
    DRAINING = "draining"
    DELETED = "deleted"

    def valid_next(self) -> set["ClusterState"]:
        return {
            ClusterState.RUNNING: {ClusterState.RUNNING, ClusterState.DRAINING},
            ClusterState.DRAINING: {ClusterState.DRAINING, ClusterState.RUNNING,
                                    ClusterState.DELETED},
            ClusterState.DELETED: {ClusterState.DELETED},
        }[self]


class KillLock:
    """Read-write lock guarding launch (read side, many concurrent) against
    kill (write side, exclusive) — `kill-lock-object`
    (compute_cluster.clj:86-112)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    class _Read:
        def __init__(self, lock):
            self.lock = lock

        def __enter__(self):
            with self.lock._cond:
                while self.lock._writer:
                    self.lock._cond.wait()
                self.lock._readers += 1

        def __exit__(self, *exc):
            with self.lock._cond:
                self.lock._readers -= 1
                self.lock._cond.notify_all()

    class _Write:
        def __init__(self, lock):
            self.lock = lock

        def __enter__(self):
            with self.lock._cond:
                while self.lock._writer or self.lock._readers:
                    self.lock._cond.wait()
                self.lock._writer = True

        def __exit__(self, *exc):
            with self.lock._cond:
                self.lock._writer = False
                self.lock._cond.notify_all()

    def read(self):
        return self._Read(self)

    def write(self):
        return self._Write(self)


def wait_all_launches(clusters, timeout: Optional[float] = None) -> list:
    """Block until every cluster's in-flight async launch batches have
    completed; returns the clusters still busy at the timeout.  THE one
    drain idiom — Scheduler.drain_launches and the pipelined pass's
    end-of-cycle drain both go through here."""
    stuck = []
    for cluster in clusters:
        wait = getattr(cluster, "wait_launches", None)
        if wait is not None and not wait(timeout=timeout):
            stuck.append(cluster)
    return stuck


def safe_pool_offers(cluster, pool: str) -> Optional[list]:
    """One cluster's offers for one pool, fault-injectable: an offer RPC
    raising returns None (the cluster is skipped this scan) instead of
    taking the whole rank/match cycle down — one flapping backend must
    not starve every pool.  Offer outcomes deliberately do NOT feed the
    circuit breaker: its window watches launch/kill RPC outcomes only
    (BreakerParams), and scans report no successes, so rare scan blips
    would accumulate one-sidedly until they opened the breaker on a
    healthy cluster."""
    try:
        fault_schedule = faults.ACTIVE
        if fault_schedule is not None:
            fault_schedule.hit(faults.CLUSTER_OFFERS, cluster=cluster.name,
                               pool=pool)
        return cluster.pending_offers(pool)
    except Exception:  # noqa: BLE001 — backend RPC boundary
        log.exception("pending_offers failed (cluster %s, pool %s); "
                      "skipping this scan", cluster.name, pool)
        return None


def scan_pool_offers(clusters, pool: str):
    """Yield every offer the pool's work-accepting clusters currently
    make.  THE one spare/capacity offer scan — the scheduler's spare
    cache, the cycle-start capacity snapshot, and the elastic planner's
    supply tensors all consume this, so offer-semantics changes (clamps,
    synthesized fields) happen in exactly one traversal.  Note each call
    re-queries the backends; per-cycle callers should scan once and
    share the result."""
    for cluster in clusters:
        if not cluster.accepts_work:
            continue
        offers = safe_pool_offers(cluster, pool)
        if offers is None:
            continue
        for offer in offers:
            yield cluster, offer


class ComputeCluster(abc.ABC):
    """Backend interface.  Implementations: `cluster.mock.MockCluster` (the
    simulator backbone, reference mesos_mock.clj) and `cluster.k8s`
    (synthesized offers + expected-vs-actual controller)."""

    name: str
    state: ClusterState

    def __init__(self, name: str, location: str = ""):
        self.name = name
        # physical location (e.g. region/zone); checkpoint-locality steers
        # restarted jobs to clusters co-located with their checkpoint
        # (reference: constraints.clj:218, job->acceptable-compute-clusters)
        self.location = location
        self.state = ClusterState.RUNNING
        self.kill_lock = KillLock()
        # per-cluster launch token bucket (launch-rate-limiter,
        # rate_limit.clj:44 + compute_cluster.clj); None = unlimited.
        # The matcher caps each cycle's launches on this cluster at the
        # bucket's balance and spends through it.
        self.launch_rate_limiter = None
        # async launch fan-out (scheduler/pipeline.py): one worker thread
        # per cluster serializes this backend's launch RPCs off the match
        # cycle's critical path; the semaphore bounds queued batches so a
        # stalled backend applies backpressure instead of growing an
        # unbounded queue.  Lazily created on first launch_tasks_async.
        self.launch_queue_bound = 8
        self._launch_executor = None
        self._launch_pending: set = set()
        self._launch_sema: Optional[threading.BoundedSemaphore] = None
        self._launch_lock = threading.Lock()
        # circuit breaker over this backend's launch/kill RPC outcomes
        # (cook_tpu/faults/breaker.py): open = accepts_work False, so a
        # failing backend stops receiving offers/launches until a
        # half-open probe succeeds.  Replaceable (tests/chaos tune
        # params); kills are never gated, only counted.
        self.breaker = CircuitBreaker(name)

    def configure_breaker(self, params: BreakerParams,
                          clock=None) -> CircuitBreaker:
        """Swap in a breaker with custom thresholds (chaos/test knob)."""
        import time as _time

        self.breaker = CircuitBreaker(self.name, params,
                                      clock=clock or _time.monotonic)
        return self.breaker

    def run_launch(self, pool: str, specs: Sequence[TaskSpec]) -> None:
        """THE backend launch entry: the `cluster.launch` fault point and
        breaker accounting around `launch_tasks`.  Callers hold whatever
        kill-lock side they need (the serial matcher path and the async
        worker both hold the read side around this call)."""
        try:
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(faults.CLUSTER_LAUNCH, cluster=self.name,
                                   pool=pool)
            self.launch_tasks(pool, specs)
        except Exception:
            self.breaker.note_failure(probe=True)
            raise
        self.breaker.note_success(probe=True)

    # --- offers ---
    @abc.abstractmethod
    def pending_offers(self, pool: str) -> list[Offer]:
        ...

    def restore_offers(self, pool: str, offers: Sequence[Offer]) -> None:
        """Return unmatched offers (Mesos semantics; no-op for synthesized)."""

    # --- task lifecycle ---
    @abc.abstractmethod
    def launch_tasks(self, pool: str, specs: Sequence[TaskSpec]) -> None:
        ...

    @abc.abstractmethod
    def kill_task(self, task_id: str) -> None:
        ...

    def safe_kill_task(self, task_id: str) -> None:
        """Kill that tolerates backend errors (reference safe-kill-task).
        Never gated by the circuit breaker — a sick cluster must still
        honor kills — but outcomes feed its error window (the
        `cluster.kill` fault point sits in front of the RPC)."""
        try:
            with self.kill_lock.write():
                fault_schedule = faults.ACTIVE
                if fault_schedule is not None:
                    fault_schedule.hit(faults.CLUSTER_KILL,
                                       cluster=self.name, task_id=task_id)
                self.kill_task(task_id)
        except Exception:  # noqa: BLE001 — kill must never propagate
            self.breaker.note_failure()
            return
        self.breaker.note_success()

    # --- async launch fan-out (scheduler/pipeline.py) ---

    def launch_tasks_async(self, pool: str, specs: Sequence[TaskSpec], *,
                           done_cb: Optional[Callable] = None):
        """Launch `specs` on this cluster's single worker thread and
        return a Future.

        The worker holds the kill-lock's READ side around the backend
        call, so a concurrent kill (write side) still excludes mid-launch
        exactly as the synchronous path does.  `done_cb(specs, exc)` runs
        on the worker AFTER the kill-lock is released (exc is None on
        success) — callers use it to flow launch failures back into the
        store's state machine; an RPC error must never be swallowed by
        the async boundary.  Backpressure: at most `launch_queue_bound`
        batches may be queued; beyond that this call blocks."""
        import concurrent.futures

        with self._launch_lock:
            if self._launch_executor is None:
                self._launch_executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"launch-{self.name}")
                self._launch_sema = threading.BoundedSemaphore(
                    self.launch_queue_bound)
        self._launch_sema.acquire()
        specs = list(specs)

        def work():
            exc = None
            try:
                with self.kill_lock.read():
                    self.run_launch(pool, specs)
            except Exception as e:  # noqa: BLE001 — flows to done_cb
                exc = e
            finally:
                self._launch_sema.release()
            if done_cb is not None:
                try:
                    done_cb(specs, exc)
                except Exception:  # noqa: BLE001 — observability only
                    log.exception("launch done_cb failed (cluster %s)",
                                  self.name)
            elif exc is not None:
                log.exception("async launch_tasks failed (cluster %s, "
                              "%d specs)", self.name, len(specs),
                              exc_info=exc)

        future = self._launch_executor.submit(work)
        with self._launch_lock:
            self._launch_pending.add(future)
        future.add_done_callback(self._launch_done)
        return future

    def _launch_done(self, future) -> None:
        with self._launch_lock:
            self._launch_pending.discard(future)

    def pending_launches(self) -> int:
        """Launch batches dispatched but not yet completed."""
        with self._launch_lock:
            return len(self._launch_pending)

    def wait_launches(self, timeout: Optional[float] = None) -> bool:
        """Block until every in-flight async launch batch has completed
        (tests, clean shutdown, and the pipelined cycle's default drain).
        Returns False on timeout."""
        import concurrent.futures

        with self._launch_lock:
            pending = list(self._launch_pending)
        if not pending:
            return True
        done, not_done = concurrent.futures.wait(pending, timeout=timeout)
        return not not_done

    # --- autoscaling ---
    def autoscaling(self, pool: str) -> bool:
        return False

    def autoscale(self, pool: str, pending_demand: Sequence[TaskSpec]) -> None:
        """Request capacity for unmatched demand (reference: synthetic pods,
        kubernetes/compute_cluster.clj:606)."""

    # --- elastic capacity (cook_tpu/elastic/) ---
    def supports_scale(self) -> bool:
        """True when this backend can apply elastic pool-capacity
        adjustments (scale())."""
        return False

    def scale(self, pool: str, adjustment: dict) -> dict:
        """Converge the pool's ELASTIC capacity to `adjustment` — a
        declarative target ({"mem": MB, "cpus": n, "gpus": n}; positive
        grows the pool with loaned-in capacity, negative withholds
        loaned-out capacity from its offers).  Declarative (a target,
        not a delta) so the call is idempotent: a promoted leader
        replays the ledger-derived net per pool and converges, no
        matter where the old leader died between commit and resize.
        Returns the adjustment actually in force.  Default: inelastic
        backend, nothing applied."""
        return {}

    # --- capacity limits ---
    def max_launchable(self) -> int:
        return 2**31

    def max_tasks_per_host(self) -> int:
        return 2**31

    def num_tasks_on_host(self, hostname: str) -> int:
        return 0

    # --- state/queries ---
    def set_state(self, new_state: ClusterState) -> None:
        if new_state not in self.state.valid_next():
            raise ValueError(f"invalid cluster transition {self.state} -> {new_state}")
        self.state = new_state

    @property
    def accepts_work(self) -> bool:
        """RUNNING and circuit-closed (or half-open — offers flowing
        again IS the probe).  An open breaker withholds this cluster
        from every offer scan and launch path until its cooldown."""
        return self.state == ClusterState.RUNNING \
            and self.breaker.allows_work()

    def retrieve_sandbox_url_path(self, task_id: str) -> str:
        return ""
