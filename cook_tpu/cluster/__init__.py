"""Compute-cluster backends behind the ComputeCluster boundary."""
from cook_tpu.cluster.base import (  # noqa: F401
    ClusterState,
    ComputeCluster,
    KillLock,
    Offer,
    TaskSpec,
)
from cook_tpu.cluster.mock import MockCluster, MockHost  # noqa: F401
