"""Hierarchical matcher: one giant pool via block (and superblock)
decomposition.

The flat matchers (`ops/match.py`) hold the whole [J, N] problem on one
chip, and `parallel/mesh.py` only shards *across* pools — so a single
100k-job x 10k-node pool cannot use more than one device.  This module
decomposes one giant pool into B topology blocks and solves it in three
passes:

  1. **coarse** — nodes are grouped into B contiguous capacity blocks
     (offer order reflects cluster/rack adjacency, so contiguous slices
     are the topology grouping; block size comes from tuned buckets).
     Jobs are assigned to blocks by the SAME chunked greedy kernel run on
     the aggregated problem: block availability is the summed capacity,
     feasibility is gated by the block's per-resource max single node
     (a job no node in the block can hold never routes there).  J x B is
     tiny next to J x N.

  2. **fine** — jobs scatter to their assigned blocks and every block's
     [jobs_per_block, nodes_per_block] problem solves as ONE batched
     `MatchProblem` with blocks as the leading batch axis — exactly the
     axis `parallel/mesh.py` already shards for pools.  The block axis
     pads to a mesh multiple with `invalid_match_problem` lanes, so ANY
     block count engages the mesh with a single XLA program per
     (block-bucket, job-slot, node-slot) shape.

  3. **refine** — jobs the coarse pass overflowed (no block, slot-cap
     spill, or fine-solve miss) are re-offered to under-filled blocks: a
     bounded number of extra coarse+fine rounds against the UPDATED block
     availabilities, reusing the exact same padded shapes (no new XLA
     programs).

**Superblocks** (`HierParams.superblock_nodes`) add a second
decomposition level above the blocks for mega-scale pools (ROADMAP item
2's 1M x 100k target).  Blocks group into S contiguous *superblocks* —
the DCN-domain analog of the blocks' ICI adjacency — and the coarse
level itself splits in two:

  1a. **super-coarse** — jobs x superblocks on the superblock
      aggregates (the same `block_aggregates` reduction at superblock
      width), via the same chunked kernel.  J x S is tiny even at 1M
      jobs.

  1b. **batched coarse** — jobs scatter to their superblocks and every
      superblock's [jobs_per_superblock, blocks_per_superblock] routing
      problem solves as ONE batched MatchProblem with superblocks as the
      leading batch axis — the SAME mesh axis (and the same
      `invalid_match_problem` dead-lane padding) the fine batch uses, so
      any superblock count keeps one XLA program per
      (superblock-bucket, slot, block) shape
      (`parallel/mesh.pool_sharded_coarse`).

The fine and refine machinery below is untouched: the two coarse levels
merge into the same global per-job block assignment, and gang placement
keeps the FINE block as its co-location domain (`gang_filter` strips at
`nodes_per_block` granularity — a gang landing in one superblock but two
blocks is stripped, never admitted).

The coarse pass has an optional fused Pallas backend
(`ops/pallas_match.best_block`: aggregate-fit + max-node gate + fitness +
argmax in one VMEM-resident sweep); it skips the host-built [J, B]
constraint mask, so it is guarded by the QualityMonitor shadow solves
like every other approximate backend (tuned_match.json promotes it only
with measured packing parity).

Packing parity vs the flat `cpu_reference.np_greedy_match` is pinned by
tests/test_hierarchical.py within a fixed tolerance; the scheduler's
quality monitor guards the live trend.
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from cook_tpu.obs import data_plane
from cook_tpu.ops.common import BIG, bucket_size, fetch_result
from cook_tpu.ops.gang import gang_filter, release_assignments
from cook_tpu.ops.match import (
    MatchProblem,
    MatchResult,
    backend_flags,
    chunked_match,
    conflict_round,
    vmap_safe_backend,
)
from cook_tpu.utils.metrics import global_registry

# tuned buckets for nodes-per-block: power-of-two block widths so the
# (block-bucket, job-slot, node-slot) shape lattice stays bounded like
# every other padded solve (ops/common.bucket_size rationale)
NODE_BLOCK_BUCKETS = (64, 128, 256, 512, 1024)
# aim for at least this many blocks so the mesh has lanes to shard
MIN_BLOCKS = 8


@dataclass
class HierParams:
    """Knobs of the two-level solve (MatchConfig.hierarchical_* mirrors
    the subset the scheduler exposes)."""

    nodes_per_block: int = 0      # 0 = auto from NODE_BLOCK_BUCKETS
    jobs_per_block: int = 0       # 0 = auto (block_slack x J/B, bucketed)
    block_slack: float = 2.0      # per-block job-slot headroom factor
    refine_rounds: int = 2        # bounded re-offer rounds (0 disables)
    # superblock (DCN-domain) layer: nodes per superblock, rounded up to
    # a power-of-two number of blocks so the (superblock-bucket, slot,
    # block) shape lattice stays bounded.  0 disables; the layer also
    # stands down when the rounding yields < 2 superblocks (a single
    # DCN domain is exactly the classic two-level problem).
    superblock_nodes: int = 0
    # fine-solve chunked-matcher knobs (MatchConfig equivalents)
    chunk: int = 1024
    rounds: int = 3
    passes: int = 2
    kc: int = 128
    backend: str = "xla"          # fine candidate backend (vmap-safe)
    # fine-solve schedule: "xla" (vmapped chunked kernel — the mesh-
    # shardable default) or "pallas" (ops/pallas_match.best_node_batched:
    # the fused fit+fitness+argmax scorer owning the block axis in ITS
    # grid, so the inner loop stops depending on XLA fusion luck;
    # single-candidate picks + the shared conflict rounds, like the
    # pallas coarse pass).  The fused path ignores `mesh` (pallas_call
    # is not shard_map'd); quality-guarded like every approximate
    # backend.
    fine_backend: str = "xla"
    # fused-fine pass count: each pass re-picks every unplaced job's ONE
    # best node against updated availability, so a pass places roughly
    # one node-capacity segment per contended node — the fused sweep is
    # cheap, so the default buys full parity at the tested shapes
    # (16 passes -> eff 1.0 vs the flat CPU greedy at 512x128)
    fine_passes: int = 16
    # coarse block-scoring backend: "xla" (masked chunked_match) or
    # "pallas" (fused best_block kernel; quality-guarded)
    coarse_backend: str = "xla"
    coarse_chunk: int = 4096
    # the coarse pass runs SINGLE-candidate conflict rounds (each job
    # picks its one best block; the prefix-accept then admits as many
    # contenders as the block's aggregate capacity holds — multi-
    # candidate spreading would cap admissions at kc per block per
    # round, starving a J >> B problem); passes re-pick fresh blocks for
    # jobs whose first choice filled — the binpack fitness jams one block
    # per pass, so passes should be O(blocks it takes to hold the queue)
    coarse_rounds: int = 2
    coarse_passes: int = 8

    def __post_init__(self):
        if self.coarse_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown hierarchical coarse backend "
                f"{self.coarse_backend!r} (expected xla | pallas)")
        if self.fine_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown hierarchical fine backend "
                f"{self.fine_backend!r} (expected xla | pallas)")
        backend_flags(self.backend)  # canonical validation + error


def choose_nodes_per_block(n_nodes: int, override: int = 0) -> int:
    """Pick the block width from the tuned buckets: the largest bucket
    that still yields >= MIN_BLOCKS blocks (so the mesh has lanes), else
    the largest yielding >= 2, else the smallest bucket."""
    if override:
        return override
    for npb in reversed(NODE_BLOCK_BUCKETS):
        if n_nodes // npb >= MIN_BLOCKS:
            return npb
    for npb in reversed(NODE_BLOCK_BUCKETS):
        if n_nodes // npb >= 2:
            return npb
    return NODE_BLOCK_BUCKETS[0]


@functools.partial(jax.jit, static_argnames=("npb",))
def block_aggregates(avail, totals, node_valid, npb: int):
    """Per-block coarse tensors from node-axis slices: summed capacity
    (the coarse availability), per-resource max single node (the coarse
    feasibility gate), summed totals (fitness denominators), any-valid."""
    n, r = avail.shape
    b = n // npb
    av = avail.reshape(b, npb, r)
    nv = node_valid.reshape(b, npb)
    tot = totals.reshape(b, npb, 2)
    masked = jnp.where(nv[..., None], av, 0.0)
    block_sum = masked.sum(axis=1)
    block_max = jnp.where(nv[..., None], av, -1.0).max(axis=1)
    block_tot = jnp.where(nv[..., None], tot, 0.0).sum(axis=1)
    block_valid = nv.any(axis=1)
    block_count = nv.sum(axis=1).astype(jnp.int32)
    return block_sum, block_max, block_tot, block_valid, block_count


def _coarse_xla(demands, active, block_sum, block_max, block_tot,
                block_valid, block_any, params: HierParams,
                gate_demands=None, need_row=None, block_count=None):
    """Coarse jobs x blocks assignment on the aggregated problem via the
    shared chunked kernel; `block_any` optionally gates each (job, block)
    on the original constraint mask having any feasible node there.

    Gang rows route with their gang's AGGREGATE demand (the leader row
    carries the sum; members are inactive here) but gate on what the
    block must hold member-wise: `gate_demands` is the per-row max member
    demand (block_max must fit it) and `need_row` the member count, gated
    against `block_count` (valid hosts per block) — a gang of k only
    routes to blocks with >= k candidate hosts."""
    gate = demands if gate_demands is None else gate_demands
    feas = jnp.all(block_max[None, :, :] >= gate[:, None, :], axis=-1)
    if need_row is not None and block_count is not None:
        feas = feas & (block_count[None, :] >= need_row[:, None])
    if block_any is not None:
        feas = feas & block_any
    problem = MatchProblem(
        demands=demands, job_valid=active, avail=block_sum,
        totals=block_tot, node_valid=block_valid, feasible=feas)
    chunk = _chunk_for(params.coarse_chunk, demands.shape[0])
    # kc=1: single-candidate conflict rounds (see HierParams.coarse_rounds
    # comment); exact top-1 — approx_max_k has nothing to save over B
    # blocks and its recall target would misroute jobs
    result = chunked_match(problem, chunk=chunk, rounds=params.coarse_rounds,
                           passes=params.coarse_passes,
                           kc=1, use_approx=False, **backend_flags("xla"))
    return result.assignment


@functools.partial(jax.jit,
                   static_argnames=("chunk", "rounds", "passes", "interpret"))
def _coarse_pallas(demands, active, block_sum, block_max, block_tot,
                   block_valid, *, chunk: int, rounds: int, passes: int,
                   interpret: bool):
    """Coarse pass on the fused Pallas block-scoring kernel: per chunk,
    `best_block` returns each job's best block (aggregate fit + max-node
    gate + fitness + argmax in one sweep), then the shared conflict
    rounds accept against the aggregate availability.  No [J, B] mask is
    ever materialized — that is the fusion the XLA path can't express."""
    from cook_tpu.ops.pallas_match import best_block

    j, r = demands.shape
    b = block_sum.shape[0]
    demands_c = demands.reshape(j // chunk, chunk, r)
    ok_c = active.reshape(j // chunk, chunk)

    def chunk_step(avail, inputs):
        d, ok = inputs

        def candidate_pass(avail, assignment):
            unplaced = assignment < 0
            d_eff = jnp.where((ok & unplaced)[:, None], d, 2 * BIG)
            val, idx = best_block(d_eff, avail, block_max, block_tot,
                                  block_valid, interpret=interpret)
            return val[:, None], jnp.maximum(idx, 0)[:, None]

        def round_step(carry, _):
            avail, assignment, cv, ci = carry
            avail, assignment = conflict_round(avail, assignment, cv, ci,
                                               d, b)
            return (avail, assignment, cv, ci), None

        assignment = (d[:, 0] * 0).astype(jnp.int32) - 1
        for _ in range(passes):
            cv, ci = candidate_pass(avail, assignment)
            (avail, assignment, _, _), _ = jax.lax.scan(
                round_step, (avail, assignment, cv, ci), None, length=rounds)
        return avail, assignment

    _, assignment = jax.lax.scan(chunk_step, block_sum, (demands_c, ok_c))
    return assignment.reshape(j)


def scatter_to_blocks(coarse: np.ndarray, job_valid: np.ndarray,
                      b: int, slots: int):
    """Host-side scatter: per-block job-slot index matrix [b, slots]
    (-1 padding), filling each block in schedule order so the ranked
    queue's fairness order survives the decomposition.  Jobs beyond a
    block's slot cap spill (True in the returned mask) to the refinement
    round instead of silently dropping."""
    j = coarse.shape[0]
    active = (coarse >= 0) & (coarse < b) & job_valid
    blocks = np.where(active, coarse, b)  # inactive jobs sort last
    order = np.argsort(blocks, kind="stable")
    sb = blocks[order]
    first = np.searchsorted(sb, np.arange(b + 1))
    job_idx = np.full((b, slots), -1, dtype=np.int32)
    spilled = np.zeros(j, dtype=bool)
    for bi in range(b):
        seg = order[first[bi]:first[bi + 1]]
        take = seg[:slots]
        job_idx[bi, :len(take)] = take
        if len(seg) > slots:
            spilled[seg[slots:]] = True
    return job_idx, spilled


@functools.partial(jax.jit, static_argnames=("npb",))
def gather_fine(demands, job_valid, feasible, avail, totals, node_valid,
                job_idx, npb: int) -> MatchProblem:
    """Build the batched per-block fine problems: demands gathered by the
    scatter's slot matrix, node tensors sliced by contiguous blocks.  The
    constraint mask is gathered per (block, slot) against the block's OWN
    node columns — no [B, S, N] blowup."""
    b, s = job_idx.shape
    r = demands.shape[-1]
    safe = jnp.maximum(job_idx, 0)
    demands_f = demands[safe]                                  # [B, S, R]
    valid_f = (job_idx >= 0) & job_valid[safe]
    avail_f = avail.reshape(b, npb, r)
    totals_f = totals.reshape(b, npb, 2)
    nv_f = node_valid.reshape(b, npb)
    if feasible is not None:
        j = demands.shape[0]
        f3 = feasible.reshape(j, b, npb)
        feas_f = f3[safe, jnp.arange(b)[:, None], :]           # [B, S, npb]
    else:
        feas_f = None
    return MatchProblem(demands=demands_f, job_valid=valid_f, avail=avail_f,
                        totals=totals_f, node_valid=nv_f, feasible=feas_f)


@functools.partial(jax.jit, static_argnames=("sb_blocks",))
def gather_super(demands, active, gate_demands, need_row, block_sum,
                 block_max, block_tot, block_valid, block_count, block_any,
                 job_idx, sb_blocks: int) -> MatchProblem:
    """Build the batched per-superblock coarse problems: job demands
    gathered by the super-coarse scatter's slot matrix, BLOCK aggregates
    sliced by contiguous superblocks (blocks play the node role).  The
    feasibility gate is the flat coarse pass's, gathered per
    (superblock, slot): the block's per-resource max single node must
    fit the row's gate demand (member-wise max for gang leaders), gangs
    additionally need >= k candidate hosts in the block, and the
    original constraint mask must have a feasible node there."""
    s, ss = job_idx.shape
    r = demands.shape[-1]
    safe = jnp.maximum(job_idx, 0)
    demands_f = demands[safe]                                 # [S, ss, R]
    valid_f = (job_idx >= 0) & active[safe]
    bs = block_sum.reshape(s, sb_blocks, r)
    bm = block_max.reshape(s, sb_blocks, r)
    bt = block_tot.reshape(s, sb_blocks, 2)
    bv = block_valid.reshape(s, sb_blocks)
    gate = (demands if gate_demands is None else gate_demands)[safe]
    feas = jnp.all(bm[:, None, :, :] >= gate[:, :, None, :], axis=-1)
    if need_row is not None:
        bc = block_count.reshape(s, sb_blocks)
        feas = feas & (bc[:, None, :] >= need_row[safe][:, :, None])
    if block_any is not None:
        f3 = block_any.reshape(-1, s, sb_blocks)
        feas = feas & f3[safe, jnp.arange(s)[:, None], :]
    return MatchProblem(demands=demands_f, job_valid=valid_f, avail=bs,
                        totals=bt, node_valid=bv, feasible=feas)


def _pad_block_axis(problems: MatchProblem, count: int,
                    n_res: int) -> MatchProblem:
    """Extend the fine batch with `count` all-invalid lanes
    (`parallel.mesh.invalid_match_problem`) so the block axis reaches the
    mesh/bucket multiple — the same dead-lane padding the pool-batched
    path uses, so any block count keeps ONE XLA program."""
    if count <= 0:
        return problems
    from cook_tpu.parallel.mesh import invalid_match_problem

    s, npb = problems.demands.shape[1], problems.avail.shape[1]
    pad = invalid_match_problem(
        s, npb, n_res=n_res, with_feasible=problems.feasible is not None,
        dtype=problems.demands.dtype)
    return jax.tree.map(
        lambda real, dead: jnp.concatenate(
            [real, jnp.broadcast_to(dead, (count,) + dead.shape)]),
        problems, pad)


def _chunk_for(width: int, axis: int) -> int:
    """Largest power-of-two chunk <= min(width, axis): the padded job
    axes here are powers of two, so a pow2 chunk always divides them
    (an odd configured chunk must not trip chunked_match's assert)."""
    chunk = max(1, min(width, axis))
    return 1 << (chunk.bit_length() - 1)


@functools.partial(jax.jit, static_argnames=("rounds", "passes",
                                             "interpret"))
def _fine_fused(problems: MatchProblem, *, rounds: int, passes: int,
                interpret: bool) -> MatchResult:
    """Fused fine batch solve: per pass, ONE `best_node_batched` sweep
    (ops/pallas_match.py — fit + fitness + argmax in VMEM, block axis
    owned by the kernel grid) picks each unplaced job's best node in
    its block; the shared conflict rounds then accept against the
    block's availability (single-candidate picks, so the prefix-accept
    admits contenders up to capacity — the same scheme as the pallas
    coarse pass)."""
    from cook_tpu.ops.pallas_match import best_node_batched

    b, s, n_res = problems.demands.shape
    npb = problems.avail.shape[1]
    demands = problems.demands.astype(jnp.float32)
    avail = problems.avail.astype(jnp.float32)
    totals = problems.totals.astype(jnp.float32)

    def one_conflict(av, asg, cv, ci, d):
        return conflict_round(av, asg, cv, ci, d, npb)

    vconflict = jax.vmap(one_conflict)

    assignment = jnp.full((b, s), -1, jnp.int32)
    for _ in range(passes):
        active = problems.job_valid & (assignment < 0)
        d_eff = jnp.where(active[..., None], demands, 2 * BIG)
        if problems.feasible is not None:
            feas_arg = problems.feasible & problems.node_valid[:, None, :]
            valid_arg = jnp.ones_like(problems.node_valid)
        else:
            feas_arg = None
            valid_arg = problems.node_valid
        val, idx = best_node_batched(d_eff, avail, totals, valid_arg,
                                     feas_arg, interpret=interpret)
        cand_val = val[..., None]
        cand_idx = jnp.maximum(idx, 0)[..., None]

        def round_step(carry, _):
            av, asg = carry
            av, asg = vconflict(av, asg, cand_val, cand_idx, demands)
            return (av, asg), None

        (avail, assignment), _ = jax.lax.scan(
            round_step, (avail, assignment), None, length=rounds)
    return MatchResult(assignment=assignment, new_avail=avail)


def _fine_solve(problems: MatchProblem, params: HierParams,
                mesh) -> MatchResult:
    if params.fine_backend == "pallas":
        # the fused scorer owns the batch axis in its own grid — mesh
        # sharding does not apply (Mosaic compiles on real TPUs; the
        # kernel runs in interpret mode everywhere else)
        return _fine_fused(problems, rounds=params.rounds,
                           passes=max(params.passes, params.fine_passes),
                           interpret=jax.default_backend() != "tpu")
    backend = vmap_safe_backend(params.backend)
    chunk = _chunk_for(params.chunk, problems.demands.shape[1])
    if mesh is not None:
        from cook_tpu.parallel.mesh import pool_sharded_match, shard_pools

        problems = shard_pools(mesh, problems)
        return pool_sharded_match(mesh, problems, chunk=chunk,
                                  rounds=params.rounds, passes=params.passes,
                                  kc=params.kc, backend=backend)
    fn = functools.partial(chunked_match, chunk=chunk, rounds=params.rounds,
                           passes=params.passes, kc=params.kc,
                           **backend_flags(backend))
    return jax.vmap(fn)(problems)


def _coarse_batched_solve(problems: MatchProblem, params: HierParams,
                          mesh) -> MatchResult:
    """Batched per-superblock coarse routing (jobs x blocks per lane)
    with the flat coarse pass's exact single-candidate semantics (kc=1,
    use_approx=False — see `_coarse_xla`); superblocks batch on the SAME
    mesh axis the fine solve shards."""
    chunk = _chunk_for(params.coarse_chunk, problems.demands.shape[1])
    if mesh is not None:
        from cook_tpu.parallel.mesh import pool_sharded_coarse, shard_pools

        problems = shard_pools(mesh, problems)
        return pool_sharded_coarse(mesh, problems, chunk=chunk,
                                   rounds=params.coarse_rounds,
                                   passes=params.coarse_passes)
    fn = functools.partial(chunked_match, chunk=chunk,
                           rounds=params.coarse_rounds,
                           passes=params.coarse_passes, kc=1,
                           use_approx=False, **backend_flags("xla"))
    return jax.vmap(fn)(problems)


_metrics = None


def _note_metrics(pool: str, backend: str, stats: dict) -> None:
    global _metrics
    if _metrics is None:
        _metrics = {
            "solves": global_registry.counter(
                "hierarchical.solves",
                "two-level hierarchical match solves per pool/backend"),
            "blocks": global_registry.gauge(
                "hierarchical.blocks",
                "topology blocks of the pool's last hierarchical solve"),
            "superblocks": global_registry.gauge(
                "hierarchical.superblocks",
                "DCN-domain superblocks of the pool's last hierarchical "
                "solve (0 = superblock layer off/degenerate)"),
            "spilled": global_registry.gauge(
                "hierarchical.spilled",
                "jobs the last coarse pass overflowed into refinement"),
            "refine_placed": global_registry.counter(
                "hierarchical.refine_placed",
                "jobs placed by hierarchical refinement rounds per pool"),
        }
    labels = {"pool": pool or "-"}
    _metrics["solves"].inc(labels={**labels, "backend": backend})
    _metrics["blocks"].set(stats["blocks"], labels)
    _metrics["superblocks"].set(stats.get("superblocks", 0), labels)
    _metrics["spilled"].set(stats["spilled"], labels)
    if stats.get("refine_placed"):
        _metrics["refine_placed"].inc(stats["refine_placed"], labels)


def hierarchical_match(
    problem: MatchProblem,
    *,
    params: Optional[HierParams] = None,
    mesh=None,
    observatory=None,
    pool: str = "",
    gang_id: Optional[np.ndarray] = None,
    gang_need: Optional[np.ndarray] = None,
) -> tuple[MatchResult, dict]:
    """Solve one giant pool's match problem coarse-then-fine.

    Returns (MatchResult, stats): the assignment is in the ORIGINAL node
    index space (block * nodes_per_block + local), and `stats` carries
    the phase walls (coarse_s/fine_s/refine_s), block geometry, per-block
    jobs/placed counts, and spill/refine accounting — the matcher copies
    it into the CycleRecord's hierarchical fields.

    `gang_id`/`gang_need` (host [J] int arrays; -1/0 on non-gang rows)
    turn on gang placement: each gang routes coarse as ONE row (the
    leader carries the summed demand, gated on per-member fit and >= k
    candidate hosts in the block), members inherit the leader's block,
    and after every fine pass the `ops/gang.gang_filter` kernel strips
    any gang that did not fully land inside one block — the stripped
    demand is released back into the live availability so refine rounds
    (and the next cycle) retry the gang whole.  A gang therefore never
    partially places on this path; `stats["gangs"]` carries the
    considered/placed/stripped accounting.

    `observatory` (obs.CompileObservatory) receives one
    `match_coarse`/`match_fine` solve report per pass — plus
    `match_super_coarse` when `params.superblock_nodes` engages the
    superblock layer — keyed by the padded shapes: the pin that any
    block/superblock count compiles ONE program per level.

    With superblocks on, the coarse level splits in two (super-coarse
    jobs x superblocks, then per-superblock jobs x blocks batched on the
    mesh axis) and `stats` gains superblock geometry + `super_coarse_s`;
    the fine/refine machinery, and gang co-location at the FINE block,
    are unchanged.
    """
    params = params or HierParams()
    t_start = time.perf_counter()
    orig_j = int(problem.demands.shape[0])
    n = int(problem.avail.shape[0])
    n_res = int(problem.demands.shape[-1])
    # power-of-two job axis so every chunk width divides it (the matcher
    # and bench already bucket-pad; direct callers get the same treatment)
    j = bucket_size(orig_j)
    if j != orig_j:
        problem = problem._replace(
            demands=jnp.pad(problem.demands, ((0, j - orig_j), (0, 0))),
            job_valid=jnp.pad(problem.job_valid, (0, j - orig_j)),
            feasible=(None if problem.feasible is None else
                      jnp.pad(problem.feasible,
                              ((0, j - orig_j), (0, 0)))),
        )
    npb = choose_nodes_per_block(n, params.nodes_per_block)
    npb = min(npb, bucket_size(n))
    b_real = -(-n // npb)
    n_pad = b_real * npb
    # ---- superblock (DCN-domain) geometry: blocks group into S
    # contiguous superblocks of `sb_blocks` blocks each (a power of two,
    # so the batched-coarse shape lattice stays bounded); the node axis
    # then pads to a whole number of superblocks so ONE reshape yields
    # both block and superblock aggregates.  < 2 superblocks means a
    # single DCN domain — the classic two-level path is exact there.
    sb_blocks = s_real = sbn = 0
    if params.superblock_nodes > 0:
        sb_blocks = bucket_size(max(2, -(-params.superblock_nodes // npb)),
                                minimum=2)
        sbn = sb_blocks * npb
        s_real = -(-n // sbn)
        if s_real < 2:
            sb_blocks = s_real = sbn = 0
        else:
            n_pad = s_real * sbn
            b_real = n_pad // npb
    use_superblocks = s_real >= 2
    mesh_size = int(mesh.devices.size) if mesh is not None else 1

    avail = problem.avail
    totals = problem.totals
    node_valid = problem.node_valid
    feasible = problem.feasible
    if n_pad != n:
        # pad the node axis to a whole number of blocks with dead nodes
        avail = jnp.pad(avail, ((0, n_pad - n), (0, 0)))
        totals = jnp.pad(totals, ((0, n_pad - n), (0, 0)),
                         constant_values=1.0)
        node_valid = jnp.pad(node_valid, (0, n_pad - n))
        if feasible is not None:
            feasible = jnp.pad(feasible, ((0, 0), (0, n_pad - n)))

    # block axis pads to a power-of-two bucket that is also a mesh
    # multiple: the fine batch shape — and therefore the XLA program —
    # is keyed by (b_pad, slots, npb), never by the raw block count
    b_pad = bucket_size(b_real, minimum=max(mesh_size, MIN_BLOCKS))
    b_pad += (-b_pad) % mesh_size
    if params.jobs_per_block:
        # round an override up to a power of two: the chunked fine solve
        # needs its chunk to divide the slot axis
        slots = 1 << (params.jobs_per_block - 1).bit_length()
    else:
        slots = bucket_size(int(np.ceil(params.block_slack * j / b_real)))
    slots = min(slots, bucket_size(j))
    s_pad = super_slots = 0
    if use_superblocks:
        # the superblock axis pads exactly like the block axis — a
        # power-of-two bucket that is also a mesh multiple — so the
        # batched-coarse program is keyed by (s_pad, super_slots,
        # sb_blocks), never the raw superblock count
        s_pad = bucket_size(s_real, minimum=max(mesh_size, MIN_BLOCKS))
        s_pad += (-s_pad) % mesh_size
        super_slots = bucket_size(
            int(np.ceil(params.block_slack * j / s_real)))
        super_slots = min(super_slots, bucket_size(j))

    job_valid_np = np.asarray(problem.job_valid)
    data_plane.note_d2h(int(job_valid_np.nbytes),
                        family=data_plane.FAM_HIER_COARSE)
    out = np.full(j, -1, dtype=np.int32)
    block_pad_axis = b_pad - b_real
    coarse_backend = params.coarse_backend
    if use_superblocks:
        # the two-level coarse path runs the masked xla kernels at both
        # levels (the fused pallas block scorer has no batched variant)
        coarse_backend = "xla"
    fine_backend_label = ("pallas-fine" if params.fine_backend == "pallas"
                          else vmap_safe_backend(params.backend))
    super_coarse_s = coarse_s = fine_s = refine_s = 0.0
    superblock_spilled = 0
    spilled_total = 0
    refine_placed = 0
    block_stats: list[dict] = []
    avail_now = avail

    # ---- gang precompute (one-time per solve): the leader row of each
    # gang carries the gang's aggregate coarse demand; members ride the
    # leader's block.  Device filter arrays are bucketed so the filter
    # compiles once per (rows, gang-slots) shape like everything else.
    gang_rows_np = is_leader_np = leader_row_np = None
    gang_id_dev = gang_need_dev = None
    demands_coarse = problem.demands
    gate_demands = need_row = None
    n_gangs = gang_slots = 0
    gangs_stripped_rows = 0
    has_gangs = False
    if gang_id is not None and gang_need is not None:
        gang_id_np = np.full(j, -1, dtype=np.int32)
        gang_id_np[:orig_j] = np.asarray(gang_id, dtype=np.int32)
        gang_need_np = np.zeros(j, dtype=np.int32)
        gang_need_np[:orig_j] = np.asarray(gang_need, dtype=np.int32)
        has_gangs = bool((gang_id_np >= 0).any())
    if has_gangs:
        gang_rows_np = gang_id_np >= 0
        leader_row_np = np.arange(j, dtype=np.int32)
        is_leader_np = np.zeros(j, dtype=bool)
        for g in np.unique(gang_id_np[gang_rows_np]):
            rows = np.flatnonzero(gang_id_np == g)
            leader_row_np[rows] = rows[0]
            is_leader_np[rows[0]] = True
        n_gangs = int(is_leader_np.sum())
        gang_slots = bucket_size(n_gangs)
        lr = data_plane.h2d(leader_row_np,
                            family=data_plane.FAM_HIER_COARSE)
        gmask = data_plane.h2d(gang_rows_np,
                               family=data_plane.FAM_HIER_COARSE)
        gang_id_dev = data_plane.h2d(gang_id_np,
                                     family=data_plane.FAM_HIER_FINE)
        gang_need_dev = data_plane.h2d(gang_need_np,
                                       family=data_plane.FAM_HIER_FINE)
        contrib = jnp.where(gmask[:, None], problem.demands, 0.0)
        agg = jnp.zeros_like(problem.demands).at[lr].add(contrib)
        # members route as one aggregate row; gates stay member-sized
        demands_coarse = jnp.where(gmask[:, None], agg, problem.demands)
        gmax = jnp.zeros_like(problem.demands).at[lr].max(contrib)
        gate_demands = jnp.where(gmask[:, None], gmax, problem.demands)
        need_row = data_plane.h2d(
            np.where(gang_rows_np, gang_need_np, 1).astype(np.int32),
            family=data_plane.FAM_HIER_COARSE)
        # gang gating needs the masked coarse path (the fused pallas
        # scorer has no per-row host-count gate); quality unaffected —
        # xla is the exact backend
        coarse_backend = "xla"

    def coarse_pass(active_mask: np.ndarray) -> np.ndarray:
        """One coarse jobs x blocks assignment against the CURRENT block
        availabilities (refine rounds re-enter here with only the
        leftover jobs active).  Transfers ride the `hier-coarse` family
        (the active mask up, the coarse assignment down); the padded
        jobs x blocks grid feeds the padding-waste account."""
        data_plane.note_padding(
            "match_coarse", (j, b_pad),
            valid_cells=int(active_mask.sum()) * b_real,
            padded_cells=j * b_pad)
        block_sum, block_max, block_tot, block_valid, block_count = \
            block_aggregates(avail_now, totals, node_valid, npb)
        if block_pad_axis:
            block_sum = jnp.pad(block_sum, ((0, block_pad_axis), (0, 0)))
            block_max = jnp.pad(block_max, ((0, block_pad_axis), (0, 0)),
                                constant_values=-1.0)
            block_tot = jnp.pad(block_tot, ((0, block_pad_axis), (0, 0)),
                                constant_values=1.0)
            block_valid = jnp.pad(block_valid, (0, block_pad_axis))
            block_count = jnp.pad(block_count, (0, block_pad_axis))
        if has_gangs:
            # gang members ride their leader's row through the coarse
            # solve — only the leader (aggregate demand) routes
            active_mask = active_mask & ~(gang_rows_np & ~is_leader_np)
        active = data_plane.h2d(active_mask,
                                family=data_plane.FAM_HIER_COARSE)
        if coarse_backend == "pallas":
            interpret = jax.default_backend() != "tpu"
            assignment = _coarse_pallas(
                demands_coarse, active, block_sum, block_max, block_tot,
                block_valid,
                chunk=_chunk_for(params.coarse_chunk, j),
                rounds=params.coarse_rounds, passes=params.coarse_passes,
                interpret=interpret)
        else:
            block_any = None
            if feasible is not None:
                block_any = feasible.reshape(j, b_real, npb).any(axis=-1)
                if block_pad_axis:
                    block_any = jnp.pad(block_any,
                                        ((0, 0), (0, block_pad_axis)))
            assignment = _coarse_xla(
                demands_coarse, active, block_sum, block_max, block_tot,
                block_valid, block_any, params,
                gate_demands=gate_demands if has_gangs else None,
                need_row=need_row if has_gangs else None,
                block_count=block_count if has_gangs else None)
        if observatory is not None:
            observatory.observe_solve("match_coarse", (j, b_pad),
                                      coarse_backend)
        with data_plane.family(data_plane.FAM_HIER_COARSE):
            res = np.asarray(fetch_result(assignment))
        if has_gangs:
            # members inherit the leader's block (or its miss): the
            # scatter then seats the whole gang in one block's slots
            members = gang_rows_np & ~is_leader_np
            res = res.copy()
            res[members] = res[leader_row_np[members]]
        return res

    def coarse_two_level(active_mask: np.ndarray):
        """Two-level coarse routing for superblock pools: a super-coarse
        jobs x superblocks pass on the superblock aggregates, a host
        scatter into superblock job slots, then every superblock's
        jobs x blocks routing problem solved as ONE batched MatchProblem
        on the mesh axis (the same `invalid_match_problem` dead-lane
        padding and single-candidate semantics as the flat coarse pass).
        Same contract as `coarse_pass` — a global per-job block
        assignment — plus the per-level walls."""
        nonlocal superblock_spilled
        eff = active_mask
        if has_gangs:
            # gang members ride their leader's row at BOTH coarse levels
            eff = eff & ~(gang_rows_np & ~is_leader_np)
        # -- level 1a: jobs x superblocks on the superblock aggregates
        t0 = time.perf_counter()
        data_plane.note_padding(
            "match_super_coarse", (j, s_pad),
            valid_cells=int(eff.sum()) * s_real,
            padded_cells=j * s_pad)
        sup_sum, sup_max, sup_tot, sup_valid, sup_count = \
            block_aggregates(avail_now, totals, node_valid, sbn)
        sup_pad_axis = s_pad - s_real
        if sup_pad_axis:
            sup_sum = jnp.pad(sup_sum, ((0, sup_pad_axis), (0, 0)))
            sup_max = jnp.pad(sup_max, ((0, sup_pad_axis), (0, 0)),
                              constant_values=-1.0)
            sup_tot = jnp.pad(sup_tot, ((0, sup_pad_axis), (0, 0)),
                              constant_values=1.0)
            sup_valid = jnp.pad(sup_valid, (0, sup_pad_axis))
            sup_count = jnp.pad(sup_count, (0, sup_pad_axis))
        active = data_plane.h2d(eff, family=data_plane.FAM_HIER_COARSE)
        sup_any = None
        if feasible is not None:
            sup_any = feasible.reshape(j, s_real, sbn).any(axis=-1)
            if sup_pad_axis:
                sup_any = jnp.pad(sup_any, ((0, 0), (0, sup_pad_axis)))
        sup_assignment = _coarse_xla(
            demands_coarse, active, sup_sum, sup_max, sup_tot,
            sup_valid, sup_any, params,
            gate_demands=gate_demands if has_gangs else None,
            need_row=need_row if has_gangs else None,
            block_count=sup_count if has_gangs else None)
        if observatory is not None:
            observatory.observe_solve("match_super_coarse", (j, s_pad),
                                      "xla")
        with data_plane.family(data_plane.FAM_HIER_COARSE):
            sup_np = np.asarray(fetch_result(sup_assignment))
        w_super = time.perf_counter() - t0
        # -- level 1b: per-superblock jobs x blocks, batched on the SAME
        # mesh axis (and dead-lane padding) the fine batch uses
        t0 = time.perf_counter()
        sup_idx, sup_spill = scatter_to_blocks(sup_np, eff, s_real,
                                               super_slots)
        superblock_spilled += int(sup_spill.sum())
        data_plane.note_padding(
            "match_coarse", (s_pad, super_slots, sb_blocks),
            valid_cells=int((sup_idx >= 0).sum()) * sb_blocks,
            padded_cells=s_pad * super_slots * sb_blocks)
        block_sum, block_max, block_tot, block_valid, block_count = \
            block_aggregates(avail_now, totals, node_valid, npb)
        block_any = None
        if feasible is not None:
            block_any = feasible.reshape(j, b_real, npb).any(axis=-1)
        problems = gather_super(
            demands_coarse, active, gate_demands,
            need_row if has_gangs else None, block_sum, block_max,
            block_tot, block_valid, block_count, block_any,
            data_plane.h2d(sup_idx, family=data_plane.FAM_HIER_COARSE),
            sb_blocks)
        problems = _pad_block_axis(problems, sup_pad_axis, n_res)
        result = _coarse_batched_solve(problems, params, mesh)
        if observatory is not None:
            observatory.observe_solve(
                "match_coarse", (s_pad, super_slots, sb_blocks), "xla")
        with data_plane.family(data_plane.FAM_HIER_COARSE):
            local = np.asarray(fetch_result(result.assignment))[:s_real]
        res = np.full(j, -1, dtype=np.int32)
        sel = (sup_idx >= 0) & (local >= 0)
        global_block = (np.arange(s_real, dtype=np.int32)[:, None]
                        * sb_blocks + np.maximum(local, 0))
        res[sup_idx[sel]] = global_block[sel]
        if has_gangs:
            members = gang_rows_np & ~is_leader_np
            res[members] = res[leader_row_np[members]]
        return res, {"super_coarse": w_super,
                     "coarse": time.perf_counter() - t0}

    def route_jobs(active_mask: np.ndarray):
        """Coarse routing dispatcher: the two-level superblock path when
        the layer is engaged, else the classic flat coarse pass."""
        if use_superblocks:
            return coarse_two_level(active_mask)
        t0 = time.perf_counter()
        res = coarse_pass(active_mask)
        return res, {"coarse": time.perf_counter() - t0}

    def fine_pass(job_idx: np.ndarray):
        """Scattered fine batch solve; returns (assignment [b_real, s]
        local node indices, updated flat availability).  Transfers ride
        the `hier-fine` family; the block-fill fraction of the padded
        [b_pad, slots] grid is the hierarchical padding-waste signal."""
        data_plane.note_padding(
            "match_fine", (b_pad, slots, npb),
            valid_cells=int((job_idx >= 0).sum()) * npb,
            padded_cells=b_pad * slots * npb)
        problems = gather_fine(problem.demands, problem.job_valid, feasible,
                               avail_now, totals, node_valid,
                               data_plane.h2d(
                                   job_idx,
                                   family=data_plane.FAM_HIER_FINE), npb)
        problems = _pad_block_axis(problems, block_pad_axis, n_res)
        result = _fine_solve(problems, params, mesh)
        if observatory is not None:
            observatory.observe_solve(
                "match_fine", (b_pad, slots, npb), fine_backend_label)
        with data_plane.family(data_plane.FAM_HIER_FINE):
            assignment = np.asarray(
                fetch_result(result.assignment))[:b_real]
        new_avail = result.new_avail[:b_real].reshape(n_pad, n_res)
        return assignment, new_avail

    def merge(job_idx: np.ndarray, fine_assign: np.ndarray) -> int:
        """Fold one fine pass's block-local picks into the global
        assignment; returns the number of jobs placed this pass."""
        sel = (job_idx >= 0) & (fine_assign >= 0)
        local = np.where(sel, fine_assign, 0)
        global_idx = (np.arange(b_real, dtype=np.int64)[:, None] * npb
                      + local)
        out[job_idx[sel]] = global_idx[sel].astype(np.int32)
        return int(sel.sum())

    def enforce_gangs() -> int:
        """Group-sum constraint: run the device `gang_filter` over the
        merged global assignment, stripping any gang that did not fully
        land inside one block, and release the stripped demand back into
        the live availability so refine rounds retry the gang whole.
        Returns the number of rows stripped (0 when gangs are absent —
        the gang-free path never touches the device)."""
        nonlocal avail_now, gangs_stripped_rows
        if not has_gangs:
            return 0
        asg_dev = data_plane.h2d(out, family=data_plane.FAM_HIER_FINE)
        new_asg, stripped = gang_filter(
            asg_dev, gang_id_dev, gang_need_dev,
            num_gangs=gang_slots, num_nodes=n_pad, nodes_per_block=npb)
        with data_plane.family(data_plane.FAM_HIER_FINE):
            stripped_np = np.asarray(fetch_result(stripped))
        count = int(stripped_np.sum())
        if count:
            avail_now = release_assignments(avail_now, problem.demands,
                                            asg_dev, stripped)
            with data_plane.family(data_plane.FAM_HIER_FINE):
                out[:] = np.asarray(fetch_result(new_asg))
            gangs_stripped_rows += count
        return count

    # ---- round 0: (super-)coarse -> scatter -> fine
    coarse, walls0 = route_jobs(job_valid_np)
    super_coarse_s += walls0.get("super_coarse", 0.0)
    coarse_s += walls0.get("coarse", 0.0)
    t0 = time.perf_counter()
    job_idx, spilled = scatter_to_blocks(coarse, job_valid_np, b_real, slots)
    spilled_total = int(spilled.sum())
    fine_assign, avail_now = fine_pass(job_idx)
    fine_s += time.perf_counter() - t0
    merge(job_idx, fine_assign)
    enforce_gangs()
    for bi in range(b_real):
        block_stats.append({
            "jobs": int((job_idx[bi] >= 0).sum()),
            "placed": int(((job_idx[bi] >= 0)
                           & (fine_assign[bi] >= 0)).sum()),
        })

    # ---- bounded refinement: re-offer every leftover (coarse-unrouted,
    # slot-spilled, fine-unplaced, or gang-stripped) to under-filled
    # blocks against the UPDATED availabilities — identical shapes, so
    # no new programs
    rounds_run = 0
    # the superblock path adds a second slot bottleneck (the super
    # scatter), halving worst-case per-round throughput — so its bounded
    # re-offer budget doubles; the early no-leftover / no-progress breaks
    # make unused budget free
    refine_budget = max(0, params.refine_rounds) * (2 if use_superblocks
                                                    else 1)
    for _ in range(refine_budget):
        leftover = job_valid_np & (out < 0)
        if not leftover.any():
            break
        rounds_run += 1
        t0 = time.perf_counter()
        coarse, _ = route_jobs(leftover)  # walls fold into refine_s
        job_idx, _ = scatter_to_blocks(coarse, leftover, b_real, slots)
        fine_assign, avail_now = fine_pass(job_idx)
        placed = merge(job_idx, fine_assign)
        stripped = enforce_gangs()
        refine_placed += max(0, placed - stripped)
        refine_s += time.perf_counter() - t0
        if placed - stripped <= 0:
            # net-zero progress: a strip returned exactly what the round
            # consumed, so the next round would replay the same solve
            break

    stats = {
        "blocks": b_real,
        "block_pad": b_pad,
        "nodes_per_block": npb,
        "jobs_per_block": slots,
        "superblocks": s_real,
        "superblock_pad": s_pad,
        "superblock_nodes": sbn,
        "superblock_blocks": sb_blocks,
        "jobs_per_superblock": super_slots,
        "superblock_spilled": superblock_spilled,
        "super_coarse_s": super_coarse_s,
        "coarse_s": coarse_s,
        "fine_s": fine_s,
        "refine_s": refine_s,
        "refine_rounds": rounds_run,
        "refine_placed": refine_placed,
        "spilled": spilled_total,
        "placed": int((out >= 0).sum()),
        "super_shape": (j, s_pad) if use_superblocks else None,
        "coarse_shape": ((s_pad, super_slots, sb_blocks)
                         if use_superblocks else (j, b_pad)),
        "fine_shape": (b_pad, slots, npb),
        "backend": fine_backend_label,
        "coarse_backend": coarse_backend,
        "block_stats": block_stats,
        "total_s": time.perf_counter() - t_start,
    }
    if has_gangs:
        stats["gangs"] = {
            "considered": n_gangs,
            "placed": int((is_leader_np & (out >= 0)).sum()),
            "stripped_rows": gangs_stripped_rows,
        }
    _note_metrics(pool, stats["backend"], stats)
    return MatchResult(assignment=jnp.asarray(out[:orig_j]),
                       new_avail=avail_now[:n]), stats
