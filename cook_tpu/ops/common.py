"""Shared kernel utilities: padding/bucketing (static shapes for XLA) and
multi-key sorting helpers.

XLA compiles one program per shape, so all kernels take fixed-size padded
arrays with validity masks; `bucket_size` rounds problem sizes up to a small
set of buckets to bound recompilation (SURVEY §7 "hard parts").
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

# A value larger than any real DRU/score; used instead of +inf so arithmetic
# on padded lanes stays finite.
BIG = 1e30


def fetch_result(tree):
    """Materialize a device result (array or pytree) as host numpy.

    This is THE definition of "the solve finished": over a remote-device
    tunnel `jax.block_until_ready` returns without waiting (measured
    ~0.05 ms for a ~950 ms solve), so only a device-to-host transfer
    observes completion — and fetching is also the honest cycle
    semantics, since the scheduler consumes assignments host-side.
    Every timed solve (bench, smoke bench, match cycle, quality monitor)
    must end in this call so timing means the same thing everywhere.

    Being THE completion observation also makes it THE D2H accounting
    site: the materialized result's logical bytes land in the data-plane
    ledger (obs/data_plane.py), attributed to the ambient tensor family
    and the active cycle scope.
    """
    import jax

    from cook_tpu.obs import data_plane

    out = jax.tree.map(np.asarray, tree)
    data_plane.note_d2h(data_plane.tree_nbytes(out))
    return out


class PendingResult:
    """Handle to an asynchronously dispatched device computation.

    JAX dispatches eagerly and asynchronously: calling a jitted kernel
    returns device buffers immediately while the accelerator executes in
    the background.  Holding those buffers in a PendingResult makes the
    dispatch/fetch split explicit — the pipelined match cycle
    (scheduler/pipeline.py) dispatches pool k's solve, does host work for
    pools k±1, and only then fetches — instead of the historical
    dispatch-then-immediately-`fetch_result` pattern that serialized host
    and device.  `fetch()` is the ONE completion observation (same
    semantics as `fetch_result`); it may be called exactly once per
    logical consume and re-raises any deferred device error there, so
    failures surface at the fetch site, not at dispatch.
    """

    __slots__ = ("_tree",)

    def __init__(self, tree):
        self._tree = tree

    def fetch(self):
        """Block until the device result is materialized host-side."""
        return fetch_result(self._tree)


def dispatch(fn, *args, **kwargs) -> PendingResult:
    """Run a kernel entry point and wrap its (still in-flight) device
    output without observing completion.  The counterpart of
    `fetch_result`: dispatch() starts the solve, PendingResult.fetch()
    ends it."""
    return PendingResult(fn(*args, **kwargs))


def binpack_fitness(used0, used1, d0, d1, denom0, denom1):
    """cpuMemBinPacker fitness (Fenzo's default, config.clj:108): mean
    post-placement utilization across mem and cpus.  Plain arithmetic so the
    ONE definition serves both the jnp kernels (ops/match.py) and the numpy
    host-side top-up (scheduler/constraints.py) — callers broadcast shapes.
    """
    return ((used0 + d0) / denom0 + (used1 + d1) / denom1) * 0.5


def bucket_size(n: int, minimum: int = 64) -> int:
    """Round n up to the next power-of-two bucket (>= minimum)."""
    if n <= minimum:
        return minimum
    return 1 << math.ceil(math.log2(n))


def pad_to(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    """Pad axis 0 of arr to `size` with `fill`."""
    n = arr.shape[0]
    if n == size:
        return arr
    if n > size:
        raise ValueError(f"cannot pad {n} down to {size}")
    pad_width = [(0, size - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def lexsort_perm(*keys):
    """Permutation sorting rows ascending by keys, last key least significant
    (numpy.lexsort convention reversed: keys[0] is MOST significant here).

    ONE fused multi-key `lax.sort` (keys compared lexicographically, an
    index payload carries the permutation out) instead of k sequential
    stable argsorts + gathers — measurably cheaper on TPU where each sort
    of a 131k vector is a multi-pass bitonic network.
    """
    import jax

    n = keys[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                       is_stable=True)
    return out[-1]


def segment_starts(sorted_ids):
    """Boolean mask of positions where a new segment begins in a sorted id
    vector."""
    prev = jnp.concatenate([sorted_ids[:1] - 1, sorted_ids[:-1]])
    return sorted_ids != prev


def segmented_cumsum(values, sorted_ids):
    """Cumulative sum of `values` restarting at each new id in `sorted_ids`
    (which must be sorted).  O(n log n)-free: plain cumsum minus the running
    total at each segment start, broadcast forward with a max-scan via
    cummax on masked prefix sums."""
    total = jnp.cumsum(values, axis=0)
    starts = segment_starts(sorted_ids)
    # index of each row's segment start, carried forward with a running max
    idx = jnp.arange(sorted_ids.shape[0])
    seg_first = jax_cummax(jnp.where(starts, idx, 0))
    base = jnp.take(total, jnp.maximum(seg_first - 1, 0), axis=0)
    nonzero = seg_first > 0
    if values.ndim > 1:
        nonzero = nonzero.reshape((-1,) + (1,) * (values.ndim - 1))
    base = jnp.where(nonzero, base, jnp.zeros_like(base))
    return total - base


def jax_cummax(x):
    import jax

    return jax.lax.cummax(x, axis=0)


def inverse_permutation(perm):
    """inv[perm[i]] = i."""
    n = perm.shape[0]
    return jnp.zeros(n, dtype=perm.dtype).at[perm].set(jnp.arange(n, dtype=perm.dtype))
