"""JAX kernels for the scheduling hot loops (DRU rank, match, rebalance)
plus reference-faithful CPU baselines for parity and benchmarking."""
from cook_tpu.ops.dru import DruResult, DruTasks, dru_rank  # noqa: F401
from cook_tpu.ops.hierarchical import (  # noqa: F401
    HierParams,
    hierarchical_match,
)
from cook_tpu.ops.match import (  # noqa: F401
    MatchProblem,
    MatchResult,
    chunked_match,
    greedy_match,
)
from cook_tpu.ops.rebalance import (  # noqa: F401
    PreemptionDecision,
    RebalanceState,
    find_preemption_decision,
)
