"""ctypes bindings for the C++ host-side solvers (native/cook_native.cc).

Auto-builds the shared library on first use when a toolchain is present;
callers fall back to the numpy implementations in `cpu_reference` when the
library is unavailable (`available()`).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcook_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    d = ctypes.POINTER(ctypes.c_double)
    i64 = ctypes.POINTER(ctypes.c_int64)
    i32 = ctypes.POINTER(ctypes.c_int32)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    lib.greedy_match.argtypes = [d, ctypes.c_int64, d, d, ctypes.c_int64,
                                 u8, i64]
    lib.greedy_match.restype = None
    lib.dru_rank.argtypes = [i32, d, d, d, d, ctypes.c_int64, d, d, d,
                             ctypes.c_int64, ctypes.c_int32, d, i64]
    lib.dru_rank.restype = None
    lib.find_preemption.argtypes = [i32, d, d, u8, ctypes.c_int64, d, u8,
                                    ctypes.c_int64, d, ctypes.c_double,
                                    ctypes.c_double, ctypes.c_double,
                                    i64, i64]
    lib.find_preemption.restype = ctypes.c_int64
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def greedy_match(demands: np.ndarray, avail: np.ndarray, totals: np.ndarray,
                 feasible: Optional[np.ndarray] = None) -> np.ndarray:
    lib = _load()
    assert lib is not None
    j, n = len(demands), len(avail)
    demands = np.ascontiguousarray(demands, dtype=np.float64)
    avail = np.ascontiguousarray(avail, dtype=np.float64)
    totals = np.ascontiguousarray(totals, dtype=np.float64)
    out = np.empty(j, dtype=np.int64)
    feas_ptr = None
    if feasible is not None:
        feasible = np.ascontiguousarray(feasible, dtype=np.uint8)
        feas_ptr = _ptr(feasible, ctypes.c_uint8)
    lib.greedy_match(
        _ptr(demands, ctypes.c_double), j,
        _ptr(avail, ctypes.c_double),
        _ptr(totals, ctypes.c_double), n,
        feas_ptr, _ptr(out, ctypes.c_int64),
    )
    return out


def dru_rank(user: np.ndarray, mem: np.ndarray, cpus: np.ndarray,
             gpus: np.ndarray, order_key: np.ndarray,
             mem_div: np.ndarray, cpu_div: np.ndarray, gpu_div: np.ndarray,
             gpu_mode: bool = False) -> tuple[np.ndarray, np.ndarray]:
    lib = _load()
    assert lib is not None
    t, u = len(user), len(mem_div)
    user = np.ascontiguousarray(user, dtype=np.int32)
    arrays = [np.ascontiguousarray(a, dtype=np.float64)
              for a in (mem, cpus, gpus, order_key, mem_div, cpu_div,
                        gpu_div)]
    out_dru = np.empty(t, dtype=np.float64)
    out_order = np.empty(t, dtype=np.int64)
    lib.dru_rank(
        _ptr(user, ctypes.c_int32),
        *[_ptr(a, ctypes.c_double) for a in arrays[:4]],
        t,
        *[_ptr(a, ctypes.c_double) for a in arrays[4:]],
        u, int(gpu_mode),
        _ptr(out_dru, ctypes.c_double), _ptr(out_order, ctypes.c_int64),
    )
    return out_dru, out_order


def find_preemption(task_host, task_dru, task_res, eligible, spare, host_ok,
                    demand, pending_dru, safe_dru_threshold, min_dru_diff):
    lib = _load()
    assert lib is not None
    t, h = len(task_host), len(spare)
    task_host = np.ascontiguousarray(task_host, dtype=np.int32)
    task_dru = np.ascontiguousarray(task_dru, dtype=np.float64)
    task_res = np.ascontiguousarray(task_res, dtype=np.float64)
    eligible = np.ascontiguousarray(eligible, dtype=np.uint8)
    spare = np.ascontiguousarray(spare, dtype=np.float64)
    host_ok = np.ascontiguousarray(host_ok, dtype=np.uint8)
    demand = np.ascontiguousarray(demand, dtype=np.float64)
    out_tasks = np.empty(t, dtype=np.int64)
    out_n = np.zeros(1, dtype=np.int64)
    host = lib.find_preemption(
        _ptr(task_host, ctypes.c_int32), _ptr(task_dru, ctypes.c_double),
        _ptr(task_res, ctypes.c_double), _ptr(eligible, ctypes.c_uint8), t,
        _ptr(spare, ctypes.c_double), _ptr(host_ok, ctypes.c_uint8), h,
        _ptr(demand, ctypes.c_double), float(pending_dru),
        float(safe_dru_threshold), float(min_dru_diff),
        _ptr(out_tasks, ctypes.c_int64), _ptr(out_n, ctypes.c_int64),
    )
    if host < 0:
        return None
    return int(host), out_tasks[: out_n[0]].tolist()
