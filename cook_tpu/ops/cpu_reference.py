"""Reference-faithful CPU implementations of the three scheduling solves.

These reproduce, in plain Python, the sequential algorithms of the reference
(Fenzo greedy placement; dru.clj sorted-merge ranking; rebalancer.clj
prefix-scan victim search).  They serve two purposes:

  1. parity oracles for the JAX kernels (tests assert the TPU solve matches
     or beats these on packing efficiency / exact decisions);
  2. the CPU baseline that BASELINE.md requires us to measure against.

No code is copied from the reference; these are re-implementations of the
documented behavior (see each function's citation).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


# --------------------------------------------------------------------- DRU


def ref_dru_order(
    user: np.ndarray,        # [T] int user index
    mem: np.ndarray,         # [T]
    cpus: np.ndarray,        # [T]
    gpus: np.ndarray,        # [T]
    order_key: np.ndarray,   # [T] per-user order (smaller first)
    mem_div: np.ndarray,     # [U]
    cpu_div: np.ndarray,
    gpu_div: np.ndarray,
    gpu_mode: bool = False,
):
    """Sequential DRU scoring + merge, per dru.clj:50-126.

    Returns (dru[T], order) where order lists task indices by ascending
    (dru, order_key) — the k-way sorted-merge output.
    """
    t = len(user)
    dru = np.zeros(t)
    by_user: dict[int, list[int]] = {}
    for i in np.argsort(order_key, kind="stable"):
        by_user.setdefault(int(user[i]), []).append(int(i))
    for u, idxs in by_user.items():
        cum_mem = cum_cpu = cum_gpu = 0.0
        for i in idxs:
            cum_mem += mem[i]
            cum_cpu += cpus[i]
            cum_gpu += gpus[i]
            if gpu_mode:
                dru[i] = cum_gpu / gpu_div[u]
            else:
                dru[i] = max(cum_mem / mem_div[u], cum_cpu / cpu_div[u])
    order = sorted(range(t), key=lambda i: (dru[i], order_key[i]))
    return dru, np.array(order, dtype=np.int64)


# ------------------------------------------------------------------- match


@dataclass
class RefNode:
    mem: float
    cpus: float
    gpus: float = 0.0
    total_mem: float = 0.0
    total_cpus: float = 0.0

    def __post_init__(self):
        if self.total_mem == 0.0:
            self.total_mem = self.mem
        if self.total_cpus == 0.0:
            self.total_cpus = self.cpus


def cpu_mem_bin_packer_fitness(
    used_cpus: float, used_mem: float, req_cpus: float, req_mem: float,
    total_cpus: float, total_mem: float,
) -> float:
    """Fenzo's default fitness calculator (`cpuMemBinPacker`,
    config.clj:108): mean of post-assignment cpu and mem utilization —
    higher is better (prefers filling already-used nodes)."""
    f_cpu = (used_cpus + req_cpus) / total_cpus if total_cpus > 0 else 0.0
    f_mem = (used_mem + req_mem) / total_mem if total_mem > 0 else 0.0
    return (f_cpu + f_mem) / 2.0


def ref_greedy_match(
    demands: np.ndarray,        # [J, 3] (mem, cpus, gpus), in schedule order
    avail: np.ndarray,          # [N, 3] available resources
    totals: np.ndarray,         # [N, 2] (mem, cpus) capacities for fitness
    feasible_mask: Optional[np.ndarray] = None,  # [J, N] constraint mask
) -> np.ndarray:
    """Sequential greedy placement in the spirit of Fenzo `scheduleOnce`
    (used at scheduler.clj:617-687): jobs in priority order; each takes the
    feasible node with max binpacking fitness (first index on ties).
    Returns assignment [J] of node index or -1."""
    avail = avail.astype(np.float64).copy()
    used = totals.astype(np.float64) - avail[:, :2]
    out = np.full(len(demands), -1, dtype=np.int64)
    n = len(avail)
    for j, d in enumerate(demands):
        best, best_fit = -1, -1.0
        for i in range(n):
            if feasible_mask is not None and not feasible_mask[j, i]:
                continue
            if avail[i, 0] < d[0] or avail[i, 1] < d[1] or avail[i, 2] < d[2]:
                continue
            fit = cpu_mem_bin_packer_fitness(
                used[i, 1], used[i, 0], d[1], d[0], totals[i, 1], totals[i, 0]
            )
            if fit > best_fit:
                best, best_fit = i, fit
        if best >= 0:
            avail[best] -= d
            used[best, 0] += d[0]
            used[best, 1] += d[1]
            out[j] = best
    return out


def np_greedy_match(
    demands: np.ndarray,        # [J, 3]
    avail: np.ndarray,          # [N, 3]
    totals: np.ndarray,         # [N, 2]
    feasible_mask: Optional[np.ndarray] = None,
) -> np.ndarray:
    """The same sequential greedy as `ref_greedy_match`, with the per-job
    inner loop vectorized over nodes — the strongest honest CPU baseline for
    the latency benchmarks (identical decisions, numpy speed)."""
    avail = avail.astype(np.float64).copy()
    totals = totals.astype(np.float64)
    used = totals - avail[:, :2]
    denom = np.maximum(totals, 1e-30)
    out = np.full(len(demands), -1, dtype=np.int64)
    for j, d in enumerate(demands):
        feas = (avail >= d).all(axis=1)
        if feasible_mask is not None:
            feas &= feasible_mask[j]
        if not feas.any():
            continue
        fit = ((used[:, 0] + d[0]) / denom[:, 0]
               + (used[:, 1] + d[1]) / denom[:, 1]) * 0.5
        fit[~feas] = -np.inf
        best = int(np.argmax(fit))
        avail[best] -= d
        used[best, 0] += d[0]
        used[best, 1] += d[1]
        out[j] = best
    return out


def packing_quality(
    demands: np.ndarray, assignment: np.ndarray
) -> dict:
    """Measures of a matched schedule: number placed + resources placed."""
    placed = assignment >= 0
    return {
        "num_placed": int(placed.sum()),
        "mem_placed": float(demands[placed, 0].sum()),
        "cpus_placed": float(demands[placed, 1].sum()),
    }


# ----------------------------------------------------------------- elastic


def ref_weighted_demand(res: np.ndarray, valid: np.ndarray,
                        half_life: float) -> np.ndarray:
    """Sequential oracle for ops.elastic.weighted_demand: [P, J, R]
    rank-ordered queued resources -> [P, R], queue position i discounted
    by 0.5 ** (i / half_life)."""
    p, j, r = res.shape
    out = np.zeros((p, r), dtype=np.float64)
    for pi in range(p):
        for ji in range(j):
            if not valid[pi, ji]:
                continue
            out[pi] += res[pi, ji] * 0.5 ** (ji / max(half_life, 1.0))
    return out


def ref_capacity_plan(demand: np.ndarray, supply: np.ndarray,
                      outstanding: np.ndarray, pool_valid: np.ndarray,
                      headroom: float):
    """Sequential oracle for ops.elastic.solve_capacity_plan: the same
    reclaim-first + proportional-loan plan, in plain numpy loops.
    Returns (reclaim [P,P,R], loan [P,P,R], unmet_shortage [P,R])."""
    p, r = demand.shape
    demand = np.where(pool_valid[:, None], demand, 0.0).astype(np.float64)
    supply = np.where(pool_valid[:, None], supply, 0.0).astype(np.float64)
    outstanding = np.where(
        (pool_valid[:, None] & pool_valid[None, :])[:, :, None],
        outstanding, 0.0).astype(np.float64)

    def safe_div(num, den):
        return num / den if den > 0 else 0.0

    # phase 1: lenders short on capacity reclaim proportionally across
    # their borrowers, capped by each borrower's free capacity
    reclaim = np.zeros((p, p, r))
    want = np.zeros((p, p, r))
    for lender in range(p):
        shortage = np.maximum(demand[lender] - supply[lender], 0.0)
        out_total = outstanding[lender].sum(axis=0)
        for ri in range(r):
            frac = min(safe_div(shortage[ri], out_total[ri]), 1.0)
            for b in range(p):
                want[lender, b, ri] = outstanding[lender, b, ri] * frac
    for b in range(p):
        asked = want[:, b, :].sum(axis=0)
        for ri in range(r):
            frac = min(safe_div(max(supply[b, ri], 0.0), asked[ri]), 1.0)
            for lender in range(p):
                if lender == b:
                    continue
                reclaim[lender, b, ri] = want[lender, b, ri] * frac
    supply_after = (supply + reclaim.sum(axis=1) - reclaim.sum(axis=0))

    # phase 2: new loans from net lenders (no inbound loans), keeping a
    # headroom fraction home; proportional lender-surplus x
    # borrower-shortage split
    loan = np.zeros((p, p, r))
    shortage2 = np.maximum(demand - supply_after, 0.0)
    holds_borrowed = (outstanding - reclaim).sum(axis=(0, 2)) > 0
    surplus = np.maximum(supply_after - demand, 0.0) * (1.0 - headroom)
    surplus[~(pool_valid & ~holds_borrowed)] = 0.0
    for ri in range(r):
        tot_surplus = surplus[:, ri].sum()
        tot_shortage = shortage2[:, ri].sum()
        move = min(tot_surplus, tot_shortage)
        for lender in range(p):
            for b in range(p):
                if lender == b or not (pool_valid[lender] and pool_valid[b]):
                    continue
                loan[lender, b, ri] = (
                    safe_div(surplus[lender, ri], tot_surplus)
                    * safe_div(shortage2[b, ri], tot_shortage) * move)
    unmet = np.maximum(shortage2 - loan.sum(axis=0), 0.0)
    return reclaim, loan, unmet


# --------------------------------------------------------------- rebalance


def ref_preemption_decision(
    task_host: np.ndarray,    # [T] int host index of each running task
    task_dru: np.ndarray,     # [T]
    task_mem: np.ndarray,     # [T]
    task_cpus: np.ndarray,    # [T]
    task_gpus: np.ndarray,    # [T]
    task_eligible: np.ndarray,  # [T] bool (quota/user filters, not yet preempted)
    spare: np.ndarray,        # [H, 3] (mem, cpus, gpus) spare per host
    host_ok: np.ndarray,      # [H] bool constraint pass
    demand: tuple,            # (mem, cpus, gpus) of pending job
    pending_dru: float,
    safe_dru_threshold: float,
    min_dru_diff: float,
):
    """Sequential victim search per rebalancer.clj:320-407.

    Tasks above the safe threshold whose dru exceeds pending_dru by more than
    min_dru_diff are preemptable.  Per host, walk tasks in descending dru,
    accumulating freed resources on top of spare; every prefix that covers
    the demand is a candidate whose score is the dru of its last (smallest-
    dru) task; spare-only feasibility scores +inf.  Return the candidate
    with max score: (host, [task indices]) or None.
    """
    d_mem, d_cpus, d_gpus = demand
    h = len(spare)
    mask = (
        task_eligible
        & (task_dru >= safe_dru_threshold)
        & ((task_dru - pending_dru) > min_dru_diff)
    )
    best_score, best = -1.0, None
    for host in range(h):
        if not host_ok[host]:
            continue
        cm, cc, cg = spare[host]
        if cm >= d_mem and cc >= d_cpus and cg >= d_gpus:
            if np.inf > best_score:
                best_score, best = np.inf, (host, [])
            continue
        idxs = [i for i in np.where((task_host == host) & mask)[0]]
        # descending dru, stable on index for determinism
        idxs.sort(key=lambda i: (-task_dru[i], i))
        chosen = []
        for i in idxs:
            cm += task_mem[i]
            cc += task_cpus[i]
            cg += task_gpus[i]
            chosen.append(int(i))
            if cm >= d_mem and cc >= d_cpus and cg >= d_gpus:
                score = float(task_dru[i])  # min dru in the prefix
                if score > best_score:
                    best_score, best = score, (host, list(chosen))
                break  # longer prefixes only lower the min-dru score
    return best
