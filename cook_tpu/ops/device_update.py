"""Donated-buffer O(delta) row updaters for device-resident match state.

The device mirror (scheduler/device_state.py) keeps per-pool encode
tensors resident across match cycles; what changes between cycles is a
handful of rows (new jobs, invalidated feasibility rows).  These
updaters turn those deltas into in-place device scatters:

  * the resident buffer is DONATED (`donate_argnums=0`): XLA may update
    it in place, so a delta cycle allocates and transfers only the
    delta rows, never the full buffer.  On backends without donation
    support (CPU) jax falls back to a copy — semantics identical, the
    transfer saving (the point of the mirror) is unaffected;
  * the delta row count is padded to a power-of-two bucket
    (`update_bucket`) by REPEATING the last (index, row) pair — a
    duplicate-index `.set` with identical payloads is idempotent — so
    one XLA program serves every delta size within a bucket.  The
    CompileObservatory pins this: `device_update` programs are keyed by
    (buffer shape, update bucket), never by the raw delta size.

Transfers are accounted through `obs/data_plane.h2d` like every other
instrumented put; callers pass the tensor family so delta traffic lands
in the same ledger columns the full rebuild would.
"""
from __future__ import annotations

import functools
import warnings

import jax
import numpy as np

from cook_tpu.obs import data_plane
from cook_tpu.ops.common import bucket_size

# the smallest update program: single-row deltas (the steady-state case)
# share one program with anything up to this many rows
UPDATE_BUCKET_MIN = 8


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(buf, idx, rows):
    return buf.at[idx].set(rows)


@jax.jit
def _gather_rows(buf, perm):
    return buf[perm]


def update_bucket(k: int) -> int:
    """Padded row count of a k-row delta update."""
    return bucket_size(max(int(k), 1), minimum=UPDATE_BUCKET_MIN)


def pad_update(idx: np.ndarray, rows: np.ndarray):
    """Pad a delta to its bucket by repeating the last (index, row) pair
    (idempotent under `.set`: duplicates carry identical payloads)."""
    k = idx.shape[0]
    kb = update_bucket(k)
    if kb == k:
        return idx, rows
    idx = np.concatenate([idx, np.full(kb - k, idx[-1], dtype=idx.dtype)])
    rows = np.concatenate([rows, np.repeat(rows[-1:], kb - k, axis=0)])
    return idx, rows


def scatter_rows(buf, idx: np.ndarray, rows: np.ndarray, *,
                 family: str = None, observatory=None,
                 op: str = "device_update"):
    """Scatter `rows` into the DONATED resident `buf` at `idx`; returns
    the updated buffer (the caller must replace its reference — the old
    buffer is consumed).  Only the bucket-padded delta crosses the bus.
    """
    idx, rows = pad_update(np.asarray(idx, dtype=np.int32),
                           np.ascontiguousarray(rows))
    idx_dev = data_plane.h2d(idx, family=family)
    rows_dev = data_plane.h2d(rows, family=family)
    with warnings.catch_warnings():
        # CPU XLA cannot honor donation and jax warns per call; the
        # fallback copy is correct.  Scoped to THIS call so a lost
        # donation anywhere else in the process still surfaces
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        out = _scatter_rows(buf, idx_dev, rows_dev)
    if observatory is not None:
        observatory.observe_solve(
            op, tuple(buf.shape) + (idx.shape[0],), "xla")
    return out


def gather_rows(buf, perm, *, observatory=None, op: str = "device_gather"):
    """Device-side gather of the resident buffer's rows into schedule
    order.  Returns a FRESH array: the mirror's buffers are private (a
    later delta cycle donates them), so the problem tensors handed to
    the solver must never alias them."""
    out = _gather_rows(buf, perm)
    if observatory is not None:
        observatory.observe_solve(
            op, tuple(buf.shape) + (int(perm.shape[0]),), "xla")
    return out
