"""Elastic capacity plane: the loan/reclaim assignment as a tensor solve.

Cook's pools partition a fixed fleet, so one pool starves while another
idles (the gap Aryl's capacity loaning closes, arXiv:2202.07896).  The
CapacityPlanner (cook_tpu/elastic/planner.py) assembles per-pool demand
and supply tensors each planning interval and solves the loan/reclaim
assignment here, as one bucket-padded batched problem:

  * `weighted_demand` — fold each pool's DRU-ranked pending queue
    ([P, J, R] resource vectors, rank order along J) into a [P, R]
    demand tensor.  Rank position discounts demand exponentially: the
    queue head counts at full weight (it is about to run), the deep
    tail barely counts (loaning a fleet for it would thrash).
  * `solve_capacity_plan` — given demand/supply [P, R] and the
    outstanding-loan ledger [P, P, R], produce reclaim and new-loan
    matrices.  Reclaim-first: a lender short on capacity calls its
    outstanding loans home (proportionally across borrowers, capped by
    each borrower's free capacity — reclaim is non-disruptive; pressure
    inside the borrower is the borrower's own rebalancer's problem).
    Remaining shortage is then covered by new loans from pools with
    surplus, split proportionally (a rank-1 outer product over
    lender-surplus x borrower-shortage), with a headroom fraction of
    every surplus kept home so the plan never strips a pool bare.

Both kernels take fixed padded shapes (pool axis padded to a bucket,
job axis to a bucket) so a churning pool/queue count reuses the same
XLA program — solves report to the CompileObservatory exactly like
match/rank/rebalance, and the storm detector would catch unbucketed
shapes here too.

CPU parity oracles: `ops.cpu_reference.ref_weighted_demand` /
`ref_capacity_plan` (tests/test_elastic.py asserts equality).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# resource dimensions a capacity plan moves (mem MB, cpus, gpus)
ELASTIC_RESOURCE_DIMS = ("mem", "cpus", "gpus")


class ElasticProblem(NamedTuple):
    """Padded per-pool tensors for one planning interval."""

    demand: jnp.ndarray       # [P, R] rank-weighted queued demand
    supply: jnp.ndarray       # [P, R] spare (offerable) capacity
    outstanding: jnp.ndarray  # [P, P, R] outstanding[l, b]: loaned l -> b
    pool_valid: jnp.ndarray   # [P] bool (padded rows False)


class ElasticPlan(NamedTuple):
    reclaim: jnp.ndarray    # [P, P, R] reclaim[l, b]: b returns to l
    loan: jnp.ndarray       # [P, P, R] new loans l -> b
    shortage: jnp.ndarray   # [P, R] unmet shortage after the plan (diagnostic)


def _safe_div(num, den):
    return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)


@jax.jit
def weighted_demand(res: jnp.ndarray, valid: jnp.ndarray,
                    half_life: jnp.ndarray) -> jnp.ndarray:
    """[P, J, R] rank-ordered queued-job resources -> [P, R] demand.

    Weight of queue position i is 0.5 ** (i / half_life): the head of
    the DRU order counts fully, demand `half_life` positions deep counts
    half.  `half_life` is a traced scalar so tuning it never mints a new
    XLA program.
    """
    j = res.shape[1]
    w = jnp.power(0.5, jnp.arange(j, dtype=jnp.float32)
                  / jnp.maximum(half_life, 1.0))
    return jnp.sum(res * valid[:, :, None] * w[None, :, None], axis=1)


@jax.jit
def solve_capacity_plan(problem: ElasticProblem,
                        headroom: jnp.ndarray) -> ElasticPlan:
    """One device call plans every pool's loans and reclaims at once."""
    valid = problem.pool_valid
    pair_valid = valid[:, None] & valid[None, :]
    demand = jnp.where(valid[:, None], problem.demand, 0.0)
    supply = jnp.where(valid[:, None], problem.supply, 0.0)
    outstanding = jnp.where(pair_valid[:, :, None], problem.outstanding, 0.0)

    # ---- phase 1: reclaim-first.  Lenders short on capacity call loans
    # home before anyone considers new loans (or in-pool preemption).
    shortage = jnp.maximum(demand - supply, 0.0)                  # [P, R]
    out_total = jnp.sum(outstanding, axis=1)                      # [P, R]
    want_frac = jnp.minimum(_safe_div(shortage, out_total), 1.0)  # [P, R]
    want = outstanding * want_frac[:, None, :]                    # [P, b, R]
    # borrower b can only return capacity it is not running work on:
    # cap total returns from b at b's free (spare) capacity, scaling
    # every lender's claim proportionally when they compete for it
    asked_of = jnp.sum(want, axis=0)                              # [b, R]
    free = jnp.maximum(supply, 0.0)
    return_frac = jnp.minimum(_safe_div(free, asked_of), 1.0)     # [b, R]
    reclaim = want * return_frac[None, :, :]
    # no self-loans can exist, but keep the diagonal structurally zero
    eye = jnp.eye(reclaim.shape[0], dtype=bool)
    reclaim = jnp.where(eye[:, :, None], 0.0, reclaim)

    supply_after = supply + jnp.sum(reclaim, axis=1) - jnp.sum(reclaim, axis=0)

    # ---- phase 2: new loans cover what reclaim could not.  Only pools
    # with no inbound loans may lend (a pool holding borrowed capacity
    # returns it via reclaim, never re-loans it — no loan chains), and a
    # headroom fraction of every surplus stays home.
    shortage2 = jnp.maximum(demand - supply_after, 0.0)
    holds_borrowed = jnp.sum(outstanding - reclaim, axis=(0, 2)) > 0  # [b]
    can_lend = valid & ~holds_borrowed
    surplus = jnp.maximum(supply_after - demand, 0.0) * (1.0 - headroom)
    surplus = jnp.where(can_lend[:, None], surplus, 0.0)
    tot_surplus = jnp.sum(surplus, axis=0)                        # [R]
    tot_shortage = jnp.sum(shortage2, axis=0)                     # [R]
    move = jnp.minimum(tot_surplus, tot_shortage)                 # [R]
    loan = (_safe_div(surplus, tot_surplus)[:, None, :]
            * _safe_div(shortage2, tot_shortage)[None, :, :]
            * move[None, None, :])
    loan = jnp.where(eye[:, :, None], 0.0, loan)
    loan = jnp.where(pair_valid[:, :, None], loan, 0.0)

    unmet = jnp.maximum(shortage2 - jnp.sum(loan, axis=0), 0.0)
    return ElasticPlan(reclaim=reclaim, loan=loan, shortage=unmet)
