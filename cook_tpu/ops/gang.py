"""Gang-placement kernels: all-or-nothing group-sum enforcement on the
topology-block decomposition.

A gang is k jobs (`Job.gang_size=k`, one shared group) that must land on
k distinct hosts INSIDE ONE topology block — the contiguous node ranges
the hierarchical matcher (ops/hierarchical.py) solves per block, which
double as co-location domains (a block is "good interconnect" in the
TPU-pod reading of the fleet).  The matcher solves placement as usual
with gang members as ordinary rows; these kernels then act as the
group-sum constraint: a gang keeps its assignments iff

  * every member row placed (placed count == gang_need),
  * all placed rows fall in one block (block min == block max), and
  * members sit on k DISTINCT hosts (the group's UNIQUE placement —
    enforced here so the device path agrees with
    `validate_group_assignments` instead of racing it).

Anything else strips the WHOLE gang back to -1 (`gang-incomplete`), and
`release_assignments` returns the stripped demand to availability so the
hierarchical refine rounds (or the next cycle) can retry the gang
elsewhere.  The filter is O(J) scatter/gather — negligible next to the
solve — and compiles per (rows, gang-slots) bucket like every other
kernel here.

`np_gang_filter` is the bit-identical numpy twin: the host-side
enforcement chokepoint (`finalize_pool_match`) runs it on every match
path (serial / batched / pipelined / speculative), so a gang can never
partially place no matter which solve produced the assignment; parity
tests pin the two implementations together.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

_NO_BLOCK = 2**30  # sentinel block index for unplaced rows


@functools.partial(jax.jit, static_argnames=("num_gangs", "num_nodes",
                                             "nodes_per_block"))
def gang_filter(assignment: jnp.ndarray, gang_id: jnp.ndarray,
                gang_need: jnp.ndarray, *, num_gangs: int, num_nodes: int,
                nodes_per_block: int):
    """Strip partially-placed / block-split / host-sharing gangs from an
    assignment.

    assignment [J] int32 node index in [0, num_nodes) or -1; gang_id [J]
    int32 gang slot in [0, num_gangs) or -1 for non-gang rows; gang_need
    [J] int32 = k on gang rows (0 otherwise).  nodes_per_block=0 treats
    the whole pool as one block (all-or-nothing + distinct-host only —
    the flat matchers' mode).  Returns (new_assignment [J] int32,
    stripped [J] bool).
    """
    placed = assignment >= 0
    if nodes_per_block > 0:
        blk = jnp.where(placed, assignment // nodes_per_block, _NO_BLOCK)
    else:
        blk = jnp.where(placed, 0, _NO_BLOCK)
    # non-gang rows accumulate into a sentinel slot that is never checked
    gid = jnp.where(gang_id >= 0, gang_id, num_gangs)
    count = jnp.zeros(num_gangs + 1, jnp.int32).at[gid].add(
        placed.astype(jnp.int32))
    need = jnp.zeros(num_gangs + 1, jnp.int32).at[gid].max(gang_need)
    bmin = jnp.full(num_gangs + 1, _NO_BLOCK, jnp.int32).at[gid].min(
        blk.astype(jnp.int32))
    bmax = jnp.full(num_gangs + 1, -1, jnp.int32).at[gid].max(
        jnp.where(placed, blk, -1).astype(jnp.int32))
    # distinct-host count per gang: occupancy scatter over a small
    # [gangs+1, num_nodes] bool grid (gang slots are bucketed, so this
    # stays a few MB at the largest pools and compiles once per shape)
    node = jnp.clip(jnp.where(placed, assignment, 0), 0, num_nodes - 1)
    occupancy = jnp.zeros((num_gangs + 1, num_nodes),
                          jnp.bool_).at[gid, node].max(placed)
    distinct = occupancy.sum(axis=1).astype(jnp.int32)
    complete = (count == need) & (bmin == bmax) & (distinct == need)
    keep = (gang_id < 0) | complete[gid]
    new_assignment = jnp.where(keep, assignment, -1).astype(jnp.int32)
    stripped = placed & ~keep
    return new_assignment, stripped


@jax.jit
def release_assignments(avail: jnp.ndarray, demands: jnp.ndarray,
                        assignment: jnp.ndarray,
                        mask: jnp.ndarray) -> jnp.ndarray:
    """Return masked rows' demand to availability (the inverse of the
    solve's scatter-subtract): avail [N, R], demands [J, R], assignment
    [J] node indices (only rows with mask True are read), mask [J] bool.
    """
    n = avail.shape[0]
    idx = jnp.where(mask, assignment, n - 1)
    delta = jnp.where(mask[:, None], demands, 0.0)
    return avail.at[idx].add(delta)


@functools.partial(jax.jit, static_argnames=("nodes_per_block",))
def block_free_hosts(avail: jnp.ndarray, node_valid: jnp.ndarray,
                     member_demand: jnp.ndarray, *,
                     nodes_per_block: int) -> jnp.ndarray:
    """Per-block count of valid hosts that can hold one gang member:
    avail [N, R] (N a multiple of nodes_per_block), member_demand [R].
    The coarse gang-routing gate (a gang of k only routes to blocks with
    >= k such hosts) and the `gang-incomplete` detail's "best block had
    x/k hosts free" numerator."""
    n = avail.shape[0]
    fits = jnp.all(avail >= member_demand[None, :], axis=-1) & node_valid
    return fits.reshape(n // nodes_per_block,
                        nodes_per_block).sum(axis=-1).astype(jnp.int32)


# ------------------------------------------------------------ numpy twins


def np_gang_filter(assignment: np.ndarray, gang_id: np.ndarray,
                   gang_need: np.ndarray,
                   nodes_per_block: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side twin of `gang_filter` (same semantics, numpy arrays).

    Used by finalize_pool_match as the single enforcement chokepoint and
    by the parity tests that pin the device kernel to it.  Returns
    (new_assignment, stripped)."""
    assignment = np.asarray(assignment, dtype=np.int32).copy()
    gang_id = np.asarray(gang_id)
    gang_need = np.asarray(gang_need)
    placed = assignment >= 0
    stripped = np.zeros(assignment.shape[0], dtype=bool)
    for g in np.unique(gang_id[gang_id >= 0]):
        rows = gang_id == g
        need = int(gang_need[rows].max(initial=0))
        hit = rows & placed
        blocks = (assignment[hit] // nodes_per_block
                  if nodes_per_block > 0
                  else np.zeros(int(hit.sum()), dtype=np.int64))
        distinct = int(np.unique(assignment[hit]).size)
        complete = (int(hit.sum()) == need and need > 0
                    and distinct == need
                    and (blocks.size == 0 or blocks.min() == blocks.max()))
        if not complete:
            stripped |= hit
            assignment[rows] = -1
    return assignment, stripped


def np_gang_repair(assignment: np.ndarray, gang_id: np.ndarray,
                   gang_need: np.ndarray, demands: np.ndarray,
                   avail: np.ndarray, feasible: Optional[np.ndarray],
                   nodes_per_block: int) -> np.ndarray:
    """Greedy host-side completion pass for gangs the solver left partial,
    co-located, or block-split.

    The flat binpack kernels know nothing about gangs: best-fit happily
    stacks all k members on one host, UNIQUE validation then strips the
    duplicates, and the all-or-nothing filter would hold the gang back
    forever.  This pass gives each broken gang one whole-gang retry: free
    its partial placement, then walk the blocks (whole pool when
    nodes_per_block<=0) and take the first block where every member fits
    on a DISTINCT feasible host under remaining capacity.  Non-gang rows
    are never moved; capacity accounting includes everything already
    placed this cycle.  Returns the repaired assignment (rows of gangs
    that still cannot place whole stay/become -1 for `np_gang_filter` to
    finalize)."""
    assignment = np.asarray(assignment, dtype=np.int32).copy()
    gang_id = np.asarray(gang_id)
    gang_need = np.asarray(gang_need)
    demands = np.asarray(demands, dtype=np.float64)
    n = avail.shape[0]
    remaining = np.asarray(avail, dtype=np.float64).copy()
    placed = assignment >= 0
    np.subtract.at(remaining, assignment[placed], demands[placed])
    npb = nodes_per_block if nodes_per_block > 0 else n
    for g in np.unique(gang_id[gang_id >= 0]):
        rows = np.flatnonzero(gang_id == g)
        need = int(gang_need[rows].max(initial=0))
        if need <= 0 or len(rows) < need:
            continue
        hit = rows[assignment[rows] >= 0]
        if hit.size == need:
            hosts = assignment[hit]
            blocks = hosts // npb
            if (np.unique(hosts).size == need
                    and blocks.min() == blocks.max()):
                continue  # already whole: one block, distinct hosts
        # free the broken placement, then retry the gang whole
        np.add.at(remaining, assignment[hit], demands[hit])
        assignment[rows] = -1
        order = rows[np.argsort(-demands[rows].sum(axis=1), kind="stable")]
        n_blocks = (n + npb - 1) // npb
        chosen = None
        for b in range(n_blocks):
            lo, hi = b * npb, min((b + 1) * npb, n)
            if hi - lo < need:
                continue
            rem = remaining[lo:hi].copy()
            used: set = set()
            trial: dict = {}
            for ji in order:
                pick = -1
                for node in range(lo, hi):
                    if node in used:
                        continue
                    if feasible is not None and not feasible[ji, node]:
                        continue
                    if np.all(rem[node - lo] >= demands[ji]):
                        pick = node
                        break
                if pick < 0:
                    break
                used.add(pick)
                rem[pick - lo] -= demands[ji]
                trial[int(ji)] = pick
            if len(trial) == len(order):
                chosen = trial
                break
        if chosen is not None:
            for ji, node in chosen.items():
                assignment[ji] = node
                remaining[node] -= demands[ji]
    return assignment


def np_block_free_hosts(avail: np.ndarray, node_valid: np.ndarray,
                        member_demand: np.ndarray,
                        nodes_per_block: int) -> np.ndarray:
    """Numpy twin of `block_free_hosts` (ragged tail tolerated: the last
    block may be short when N is not a block multiple host-side)."""
    fits = np.all(avail >= member_demand[None, :], axis=-1) & node_valid
    n = fits.shape[0]
    nb = max(1, (n + nodes_per_block - 1) // nodes_per_block) \
        if nodes_per_block > 0 else 1
    out = np.zeros(nb, dtype=np.int32)
    if nodes_per_block <= 0:
        out[0] = int(fits.sum())
        return out
    for b in range(nb):
        out[b] = int(fits[b * nodes_per_block:(b + 1) * nodes_per_block]
                     .sum())
    return out
