"""Pallas TPU kernel: fused feasibility + binpacking fitness + argmax.

The innermost operation of every matcher variant is "for a block of jobs,
find each job's best feasible node": feasibility compare, fitness compute,
masked argmax over the node axis.  Done with stock XLA ops this makes
multiple passes over the [K, N] intermediates; this kernel fuses them into
one pass with the score tile resident in VMEM and a running (max, argmax)
accumulator — the node axis is the grid's inner dimension, so each job
block streams through all node tiles without ever materializing [K, N] in
HBM.

Used as an optional backend for the matchers (`best_node(...)`); the
default path keeps the pure-XLA implementation (which the compiler already
fuses well) — this kernel exists for the tuning headroom on real v5e
hardware and runs under `interpret=True` on CPU for tests.

Layout notes (pallas_guide.md): f32 tiles are (8, 128) minimum; iota must
be >=1D via broadcasted_iota; scalars live in SMEM-shaped (1, 1) refs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from cook_tpu.ops.common import BIG


def _score_tile(d, avail, totals, valid, feas_mask, n_tile):
    """Shared scoring math of every best-* kernel: feasibility +
    cpuMemBinPacker fitness + argmax for one (job-block, node-tile)
    pair.  Returns (local_best [BK], local_idx [BK] — GLOBAL node
    indices).  `feas_mask` is an optional [BK, BN] constraint tile.
    ONE definition so the flat, block-aggregate, and batched-fine
    kernels can never rank candidates by diverging rules."""
    bn = avail.shape[0]

    # feasibility: every resource fits  -> [BK, BN]
    fits = jnp.all(avail[None, :, :] >= d[:, None, :], axis=-1)
    feasible = fits & (valid[None, :] > 0)
    if feas_mask is not None:
        feasible = feasible & feas_mask
    # cpuMemBinPacker fitness
    denom0 = jnp.maximum(totals[:, 0], 1e-30)
    denom1 = jnp.maximum(totals[:, 1], 1e-30)
    used0 = totals[:, 0] - avail[:, 0]
    used1 = totals[:, 1] - avail[:, 1]
    fit = ((used0[None, :] + d[:, 0:1]) / denom0[None, :]
           + (used1[None, :] + d[:, 1:2]) / denom1[None, :]) * 0.5
    score = jnp.where(feasible, fit, -BIG)          # [BK, BN]

    local_best = jnp.max(score, axis=1)             # [BK]
    col = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1)
    local_idx = jnp.max(
        jnp.where(score == local_best[:, None], bn - col, 0), axis=1
    )
    # first-index tie-break: largest (bn - col) = smallest col
    local_idx = (bn - local_idx) + n_tile * bn       # global node index
    return local_best, local_idx.astype(jnp.int32)


def _score_and_accumulate(d, avail, totals, valid, feas_mask,
                          n_tile, best_val_ref, best_idx_ref):
    """`_score_tile` + the (max, argmax) accumulation across node tiles
    (the node axis is the grid's innermost, sequential dimension)."""
    local_best, local_idx = _score_tile(d, avail, totals, valid,
                                        feas_mask, n_tile)

    @pl.when(n_tile == 0)
    def _init():
        best_val_ref[:] = local_best
        best_idx_ref[:] = local_idx

    @pl.when(n_tile > 0)
    def _accum():
        prev_val = best_val_ref[:]
        prev_idx = best_idx_ref[:]
        take_new = local_best > prev_val  # strict: earlier tile wins ties
        best_val_ref[:] = jnp.where(take_new, local_best, prev_val)
        best_idx_ref[:] = jnp.where(take_new, local_idx, prev_idx)


def _best_node_kernel(d_ref, avail_ref, totals_ref, valid_ref,
                      best_val_ref, best_idx_ref):
    """Grid = (jobs/BK, nodes/BN); node axis is innermost (sequential), so
    (best_val, best_idx) accumulate across node tiles."""
    _score_and_accumulate(d_ref[:], avail_ref[:], totals_ref[:],
                          valid_ref[:], None, pl.program_id(1),
                          best_val_ref, best_idx_ref)


def _best_node_masked_kernel(d_ref, avail_ref, totals_ref, valid_ref,
                             feas_ref, best_val_ref, best_idx_ref):
    """`_best_node_kernel` with a per-(job, node) constraint mask block —
    the encoded feasibility_mask tile rides along in VMEM."""
    _score_and_accumulate(d_ref[:], avail_ref[:], totals_ref[:],
                          valid_ref[:], feas_ref[:] > 0, pl.program_id(1),
                          best_val_ref, best_idx_ref)


def _grid_best_call(kernel, *, padded_k, padded_n, block_jobs, block_nodes,
                    in_specs, args, interpret):
    """Shared pallas_call scaffold of the best-* kernels: jobs x node
    tiles grid (node axis innermost/sequential), per-job (val, idx)
    accumulator outputs.  ONE copy so a padding/tie-break fix can never
    silently miss a sibling kernel."""
    return pl.pallas_call(
        kernel,
        grid=(padded_k // block_jobs, padded_n // block_nodes),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_jobs,), lambda i, j: (i,)),
            pl.BlockSpec((block_jobs,), lambda i, j: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded_k,), jnp.float32),
            jax.ShapeDtypeStruct((padded_k,), jnp.int32),
        ],
        interpret=interpret,
    )(*args)


def _unpad_best(best_val, best_idx, k):
    """Shared postlude: drop job padding, -1 where nothing was feasible."""
    best_val = best_val[:k]
    best_idx = best_idx[:k]
    found = best_val > -BIG
    return best_val, jnp.where(found, best_idx, -1)


@functools.partial(jax.jit, static_argnames=("block_jobs", "block_nodes",
                                             "interpret"))
def best_node(
    demands: jnp.ndarray,     # [K, R] (R >= 3; only first 3 scored)
    avail: jnp.ndarray,       # [N, R]
    totals: jnp.ndarray,      # [N, 2]
    node_valid: jnp.ndarray,  # [N] (bool or int)
    feasible=None,            # optional [K, N] constraint mask
    *,
    block_jobs: int = 256,
    block_nodes: int = 512,
    interpret: bool = False,
):
    """Per-job best feasible node: returns (best_score [K], best_idx [K]);
    best_idx is -1 (and score -BIG) when no node is feasible."""
    k, n = demands.shape[0], avail.shape[0]
    # pad up to block multiples rather than shrinking the block: a prime
    # node count would otherwise degenerate to 1-wide tiles (a sequential
    # grid, and a Mosaic lane-tiling violation on real TPUs).  Padded
    # jobs are unsatisfiable, padded nodes invalid — neither can win.
    block_jobs = min(block_jobs, k)
    block_nodes = min(block_nodes, n)
    pad_k = (-k) % block_jobs
    pad_n = (-n) % block_nodes
    valid_i = node_valid.astype(jnp.int32)
    if pad_k:
        demands = jnp.pad(demands, ((0, pad_k), (0, 0)),
                          constant_values=2 * BIG)
    if pad_n:
        avail = jnp.pad(avail, ((0, pad_n), (0, 0)))
        totals = jnp.pad(totals, ((0, pad_n), (0, 0)))
        valid_i = jnp.pad(valid_i, (0, pad_n))
    if feasible is not None and (pad_k or pad_n):
        feasible = jnp.pad(feasible, ((0, pad_k), (0, pad_n)))
    padded_k = k + pad_k
    padded_n = n + pad_n
    r = demands.shape[-1]

    job_specs = [
        pl.BlockSpec((block_jobs, r), lambda i, j: (i, 0)),
        pl.BlockSpec((block_nodes, r), lambda i, j: (j, 0)),
        pl.BlockSpec((block_nodes, 2), lambda i, j: (j, 0)),
        pl.BlockSpec((block_nodes,), lambda i, j: (j,)),
    ]
    args = (demands.astype(jnp.float32), avail.astype(jnp.float32),
            totals.astype(jnp.float32), valid_i)
    if feasible is None:
        best_val, best_idx = _grid_best_call(
            _best_node_kernel, padded_k=padded_k, padded_n=padded_n,
            block_jobs=block_jobs, block_nodes=block_nodes,
            in_specs=job_specs, args=args, interpret=interpret)
    else:
        best_val, best_idx = _grid_best_call(
            _best_node_masked_kernel, padded_k=padded_k, padded_n=padded_n,
            block_jobs=block_jobs, block_nodes=block_nodes,
            in_specs=job_specs + [
                pl.BlockSpec((block_jobs, block_nodes),
                             lambda i, j: (i, j)),
            ],
            args=args + (feasible.astype(jnp.int32),),
            interpret=interpret)
    return _unpad_best(best_val, best_idx, k)


# ---------------------------------------------------- hierarchical coarse


def _best_block_kernel(d_ref, avail_ref, maxn_ref, totals_ref, valid_ref,
                       best_val_ref, best_idx_ref):
    """`_best_node_kernel` for BLOCK aggregates (ops/hierarchical.py
    coarse pass) with the extra max-single-node feasibility gate fused
    in-kernel: a job routes to a block only if the block's aggregate
    capacity fits it AND some single node there could hold it.  The XLA
    path materializes that gate as a host-built [J, B] mask; here it is
    computed on the fly from the [BN, R] max-node tile — the fusion this
    kernel exists for."""
    d = d_ref[:]
    gate = jnp.all(maxn_ref[:][None, :, :] >= d[:, None, :], axis=-1)
    _score_and_accumulate(d, avail_ref[:], totals_ref[:], valid_ref[:],
                          gate, pl.program_id(1),
                          best_val_ref, best_idx_ref)


@functools.partial(jax.jit, static_argnames=("block_jobs", "block_nodes",
                                             "interpret"))
def best_block(
    demands: jnp.ndarray,      # [K, R]
    block_avail: jnp.ndarray,  # [B, R] aggregate availability per block
    block_max: jnp.ndarray,    # [B, R] max single-node availability
    block_totals: jnp.ndarray, # [B, 2] aggregate capacity (fitness denoms)
    block_valid: jnp.ndarray,  # [B] (bool or int)
    *,
    block_jobs: int = 256,
    block_nodes: int = 128,
    interpret: bool = False,
):
    """Per-job best feasible BLOCK for the hierarchical coarse pass:
    returns (best_score [K], best_idx [K]); best_idx is -1 (score -BIG)
    when no block is feasible.  Same layout/padding discipline as
    `best_node`."""
    k, b = demands.shape[0], block_avail.shape[0]
    block_jobs = min(block_jobs, k)
    block_nodes = min(block_nodes, b)
    pad_k = (-k) % block_jobs
    pad_b = (-b) % block_nodes
    valid_i = block_valid.astype(jnp.int32)
    if pad_k:
        demands = jnp.pad(demands, ((0, pad_k), (0, 0)),
                          constant_values=2 * BIG)
    if pad_b:
        block_avail = jnp.pad(block_avail, ((0, pad_b), (0, 0)))
        block_max = jnp.pad(block_max, ((0, pad_b), (0, 0)),
                            constant_values=-1.0)
        block_totals = jnp.pad(block_totals, ((0, pad_b), (0, 0)))
        valid_i = jnp.pad(valid_i, (0, pad_b))
    padded_k = k + pad_k
    padded_b = b + pad_b
    r = demands.shape[-1]

    best_val, best_idx = _grid_best_call(
        _best_block_kernel, padded_k=padded_k, padded_n=padded_b,
        block_jobs=block_jobs, block_nodes=block_nodes,
        in_specs=[
            pl.BlockSpec((block_jobs, r), lambda i, j: (i, 0)),
            pl.BlockSpec((block_nodes, r), lambda i, j: (j, 0)),
            pl.BlockSpec((block_nodes, r), lambda i, j: (j, 0)),
            pl.BlockSpec((block_nodes, 2), lambda i, j: (j, 0)),
            pl.BlockSpec((block_nodes,), lambda i, j: (j,)),
        ],
        args=(demands.astype(jnp.float32), block_avail.astype(jnp.float32),
              block_max.astype(jnp.float32),
              block_totals.astype(jnp.float32), valid_i),
        interpret=interpret)
    return _unpad_best(best_val, best_idx, k)


# ------------------------------------------------------- hierarchical fine


def _batched_accumulate(local_best, local_idx, n_tile,
                        best_val_ref, best_idx_ref):
    """The (max, argmax) accumulation for batched kernels whose output
    blocks carry a leading singleton batch dim ([1, BK])."""
    @pl.when(n_tile == 0)
    def _init():
        best_val_ref[0, :] = local_best
        best_idx_ref[0, :] = local_idx

    @pl.when(n_tile > 0)
    def _accum():
        prev_val = best_val_ref[0, :]
        prev_idx = best_idx_ref[0, :]
        take_new = local_best > prev_val  # strict: earlier tile wins ties
        best_val_ref[0, :] = jnp.where(take_new, local_best, prev_val)
        best_idx_ref[0, :] = jnp.where(take_new, local_idx, prev_idx)


def _fine_kernel(d_ref, avail_ref, totals_ref, valid_ref,
                 best_val_ref, best_idx_ref):
    """Grid = (blocks, slots/BK, npb/BN); node axis innermost.  Every
    ref carries a leading singleton batch dim — the block axis is owned
    by the GRID, so the fine batch never rides jax.vmap (whose
    pallas_call batching is not guaranteed)."""
    local_best, local_idx = _score_tile(
        d_ref[0], avail_ref[0], totals_ref[0], valid_ref[0], None,
        pl.program_id(2))
    _batched_accumulate(local_best, local_idx, pl.program_id(2),
                        best_val_ref, best_idx_ref)


def _fine_masked_kernel(d_ref, avail_ref, totals_ref, valid_ref,
                        feas_ref, best_val_ref, best_idx_ref):
    """`_fine_kernel` with the per-(block, slot, node) constraint-mask
    tile riding along in VMEM."""
    local_best, local_idx = _score_tile(
        d_ref[0], avail_ref[0], totals_ref[0], valid_ref[0],
        feas_ref[0] > 0, pl.program_id(2))
    _batched_accumulate(local_best, local_idx, pl.program_id(2),
                        best_val_ref, best_idx_ref)


@functools.partial(jax.jit, static_argnames=("block_jobs", "block_nodes",
                                             "interpret"))
def best_node_batched(
    demands: jnp.ndarray,     # [B, S, R]
    avail: jnp.ndarray,       # [B, N, R]
    totals: jnp.ndarray,      # [B, N, 2]
    node_valid: jnp.ndarray,  # [B, N] (bool or int)
    feasible=None,            # optional [B, S, N] constraint mask
    *,
    block_jobs: int = 256,
    block_nodes: int = 512,
    interpret: bool = False,
):
    """Per-job best feasible node for a BATCH of per-block problems —
    the fused fine-pass scorer of the hierarchical matcher
    (ops/hierarchical.py): fit + fitness + argmax in one VMEM sweep per
    (block, job-tile), with the block axis as the grid's outer
    dimension.  Returns (best_score [B, S], best_idx [B, S]); idx -1
    (score -BIG) where nothing is feasible.  Same layout/padding
    discipline as `best_node`."""
    b, s = demands.shape[0], demands.shape[1]
    n = avail.shape[1]
    block_jobs = min(block_jobs, s)
    block_nodes = min(block_nodes, n)
    pad_s = (-s) % block_jobs
    pad_n = (-n) % block_nodes
    valid_i = node_valid.astype(jnp.int32)
    if pad_s:
        demands = jnp.pad(demands, ((0, 0), (0, pad_s), (0, 0)),
                          constant_values=2 * BIG)
    if pad_n:
        avail = jnp.pad(avail, ((0, 0), (0, pad_n), (0, 0)))
        totals = jnp.pad(totals, ((0, 0), (0, pad_n), (0, 0)))
        valid_i = jnp.pad(valid_i, ((0, 0), (0, pad_n)))
    if feasible is not None and (pad_s or pad_n):
        feasible = jnp.pad(feasible, ((0, 0), (0, pad_s), (0, pad_n)))
    padded_s = s + pad_s
    padded_n = n + pad_n
    r = demands.shape[-1]

    in_specs = [
        pl.BlockSpec((1, block_jobs, r), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_nodes, r), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_nodes, 2), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_nodes), lambda b, i, j: (b, j)),
    ]
    args = (demands.astype(jnp.float32), avail.astype(jnp.float32),
            totals.astype(jnp.float32), valid_i)
    kernel = _fine_kernel
    if feasible is not None:
        in_specs.append(
            pl.BlockSpec((1, block_jobs, block_nodes),
                         lambda b, i, j: (b, i, j)))
        args = args + (feasible.astype(jnp.int32),)
        kernel = _fine_masked_kernel
    best_val, best_idx = pl.pallas_call(
        kernel,
        grid=(b, padded_s // block_jobs, padded_n // block_nodes),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_jobs), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_jobs), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, padded_s), jnp.float32),
            jax.ShapeDtypeStruct((b, padded_s), jnp.int32),
        ],
        interpret=interpret,
    )(*args)
    best_val = best_val[:, :s]
    best_idx = best_idx[:, :s]
    found = best_val > -BIG
    return best_val, jnp.where(found, best_idx, -1)
