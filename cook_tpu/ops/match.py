"""Jobs x nodes bin-packing as an on-device solve: the Fenzo replacement.

The reference's match cycle hands ranked jobs + offers to Netflix Fenzo's
single-threaded greedy `scheduleOnce` under a lock
(/root/reference/scheduler/src/cook/scheduler/scheduler.clj:617-687, fitness
knobs config.clj:108-116).  Here the same decision problem — place each job,
in fair-share order, on the feasible node with the best binpacking fitness —
is computed on TPU:

  * `greedy_match`: a `lax.scan` over ranked jobs; each step is a fully
    vectorized feasibility mask + fitness argmax over all N nodes.
    Bit-exact with the sequential CPU reference
    (`cpu_reference.ref_greedy_match`) including tie-breaks, so packing
    parity is exact by construction.  O(J) scan steps — the exactness
    oracle, not the fast path.

  * `chunked_match`: the fast path.  Jobs are processed in chunks of K (in
    schedule order).  Per chunk, ONE [K, N] fitness pass ranks each job's
    top-`kc` candidate nodes (`lax.approx_max_k` — the TPU-native partial
    reduce); then `rounds` cheap conflict-resolution rounds run entirely on
    [K, kc] candidate tensors:

      1. each unplaced job takes its first still-feasible candidate;
      2. jobs contending for the same node are spread: the c-th contender
         (in chunk order) takes its c-th feasible candidate — the parallel
         analog of "earlier jobs grabbed it first";
      3. a pick is accepted iff the node holds the cumulative demand of all
         earlier accepted picks on it (segmented prefix-sum over the K jobs
         sorted by picked node — O(K log K), never materializing [K, N]);
      4. accepted demand is scatter-subtracted and the next round retries
         the rest.

    Divergence from pure sequential greedy: fitness is snapshotted per
    chunk, candidate lists are top-kc (a job whose kc best nodes all fill
    up this chunk waits a cycle), and approx_max_k has a recall target
    (~0.95 by default).  Parity tests bound the packing gap; on the
    BASELINE headline config it packs >= the CPU greedy baseline.

Constraints enter as a [J, N] boolean mask (see scheduler/constraints.py
for the encoders); when `feasible` is None no mask is materialized.  Group
constraints that depend on earlier placements in the same cycle are enforced
by a host-side post-pass (scheduler/constraints.py:validate_group_assignments).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from cook_tpu.ops.common import BIG, binpack_fitness, lexsort_perm


class MatchProblem(NamedTuple):
    """One pool's padded matching problem."""

    demands: jnp.ndarray     # [J, R] (mem, cpus, gpus[, disk...]) in schedule order
    job_valid: jnp.ndarray   # [J] bool
    avail: jnp.ndarray       # [N, R] currently-available (offered) resources
    totals: jnp.ndarray      # [N, 2] (mem, cpus) capacity — fitness denominators
    node_valid: jnp.ndarray  # [N] bool
    feasible: Optional[jnp.ndarray] = None  # [J, N] bool constraint mask
    # [N] additive score term — the topology distance bonus (matcher
    # `topology_weight`): nodes in already-warm topology blocks score
    # higher, so placements co-locate even for non-gang jobs.  None (the
    # default) keeps the pre-gang XLA programs byte-identical; the
    # pallas candidate backend ignores it (its fused best-node kernel
    # ranks by raw fitness — co-location there rides on the
    # hierarchical block routing instead).
    node_bonus: Optional[jnp.ndarray] = None


class MatchResult(NamedTuple):
    assignment: jnp.ndarray  # [J] int32 node index or -1
    new_avail: jnp.ndarray   # [N, R] availability after placements


def backend_flags(backend: str) -> dict:
    """Map a candidate-pass backend name to chunked_match flags; the ONE
    place backend strings are interpreted (and rejected) for every caller
    — scheduler config, mesh solve, sweep, bench."""
    if backend not in ("xla", "pallas", "bucketed"):
        raise ValueError(f"unknown match backend {backend!r} "
                         "(expected xla | pallas | bucketed)")
    return {"use_pallas": backend == "pallas",
            "bucketed": backend == "bucketed"}


def vmap_safe_backend(backend: str) -> str:
    """Backend to use on a pool-batched (vmapped / shard_map-of-vmap)
    solve.  pallas_call batching under jax.vmap is not guaranteed, so the
    batched paths coerce pallas -> xla; every vmapped caller (scheduler
    batched match, pool-sharded mesh solve, bench multipool) must route
    through this so a pool configured with backend='pallas' degrades
    predictably instead of failing at trace time."""
    backend_flags(backend)  # validate the name with the canonical error
    return "xla" if backend == "pallas" else backend


def _job_step(avail, totals, node_valid, demand, job_ok, feas_row,
              node_bonus=None):
    """Place one job: feasibility mask + binpacking-fitness argmax."""
    fits = jnp.all(avail >= demand[None, :], axis=-1)
    feasible = fits & node_valid & feas_row & job_ok
    used = totals - avail[:, :2]
    denom = jnp.maximum(totals, 1e-30)
    fit = binpack_fitness(used[:, 0], used[:, 1], demand[0], demand[1],
                          denom[:, 0], denom[:, 1])
    if node_bonus is not None:
        fit = fit + node_bonus
    score = jnp.where(feasible, fit, -BIG)
    best = jnp.argmax(score)
    placed = score[best] > -BIG
    delta = jnp.where(placed, demand, 0.0)
    new_avail = avail.at[best].add(-delta)
    return new_avail, jnp.where(placed, best, -1).astype(jnp.int32)


@jax.jit
def greedy_match(problem: MatchProblem) -> MatchResult:
    """Sequential-order greedy matcher via lax.scan (exact Fenzo-order
    semantics; O(J) scan steps of O(N) vector work each)."""
    j = problem.demands.shape[0]
    # shape [J,1] when unconstrained: broadcasts against [N] without ever
    # materializing a [J,N] mask (100k x 10k bool would be ~1 GB)
    feas = (
        problem.feasible
        if problem.feasible is not None
        else jnp.ones((j, 1), dtype=bool)
    )

    def step(avail, inputs):
        demand, ok, feas_row = inputs
        new_avail, choice = _job_step(
            avail, problem.totals, problem.node_valid, demand, ok, feas_row,
            node_bonus=problem.node_bonus,
        )
        return new_avail, choice

    new_avail, assignment = jax.lax.scan(
        step, problem.avail, (problem.demands, problem.job_valid, feas)
    )
    return MatchResult(assignment=assignment, new_avail=new_avail)


def _segment_rank(keys, order):
    """Rank of each element within its run of equal keys, where runs are
    taken over `keys` sorted with tie-break `order`.  Returns ranks in the
    original index space."""
    k = keys.shape[0]
    idxs = jnp.arange(k)
    perm = lexsort_perm(keys, order)
    sk = keys[perm]
    starts = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    seg_first = jax.lax.cummax(jnp.where(starts, idxs, 0))
    rank_sorted = idxs - seg_first
    return jnp.zeros(k, jnp.int32).at[perm].set(rank_sorted.astype(jnp.int32))


def conflict_round(avail, assignment, cand_val, cand_idx, d, n, *,
                   recheck_mask=None):
    """One conflict-resolution round over candidate lists — THE shared
    acceptance step of every chunked matcher variant (single-device,
    node-sharded, pallas, bucketed):

      1. each unplaced job takes its first still-feasible candidate;
      2. contenders for the same node spread onto their c-th feasible
         alternates (skipped for single-candidate lists, where the
         prefix-accept below admits as many contenders as fit — extra
         rounds still progress: a contender whose pick stops fitting
         drops out of the segment, unblocking jobs queued behind it);
      3. a pick is accepted iff the node holds the cumulative demand of
         earlier accepted picks (segmented prefix-sum over sorted picks);
      4. accepted demand is scatter-subtracted from availability.

    `recheck_mask` ([K, N] bool) re-applies a constraint mask on the
    candidate gather — needed when candidate lists were built without it
    (class-shared bucketed lists).  Returns (new_avail, assignment)."""
    k = cand_idx.shape[0]
    n_res = d.shape[-1]
    order = jnp.arange(k)
    idxs = jnp.arange(k)
    cand_ok = cand_val > -BIG  # [K,kc]
    unplaced = assignment < 0
    # candidate feasibility vs CURRENT availability (tiny gather)
    avail_cand = avail[cand_idx]  # [K,kc,R]
    feas_cand = (
        jnp.all(avail_cand >= d[:, None, :], axis=-1)
        & cand_ok
        & unplaced[:, None]
    )
    if recheck_mask is not None:
        feas_cand &= jnp.take_along_axis(recheck_mask, cand_idx, axis=1)
    has = feas_cand.any(axis=1)
    f0 = jnp.argmax(feas_cand, axis=1)
    pick0 = jnp.where(
        has,
        jnp.take_along_axis(cand_idx, f0[:, None], axis=1)[:, 0],
        n,
    )
    if cand_idx.shape[1] == 1:
        pick = pick0
        take = has
    else:
        # contention spreading: c-th contender takes its c-th feasible
        # candidate
        c = _segment_rank(pick0, order)
        cum = jnp.cumsum(feas_cand, axis=1)
        sel = (cum == (c + 1)[:, None]) & feas_cand
        has_c = sel.any(axis=1)
        pos = jnp.argmax(sel, axis=1)
        pick = jnp.take_along_axis(cand_idx, pos[:, None], axis=1)[:, 0]
        take = has & has_c
    pick_key = jnp.where(take, pick, n)
    # prefix-accept: per-node cumulative demand among this round's picks
    # must fit availability (segmented over sorted picks)
    perm2 = lexsort_perm(pick_key, order)
    sp2 = pick_key[perm2]
    d2 = jnp.where((sp2 < n)[:, None], d[perm2], 0.0)
    cums = jnp.cumsum(d2, axis=0)
    starts2 = jnp.concatenate([jnp.ones(1, bool), sp2[1:] != sp2[:-1]])
    seg_first2 = jax.lax.cummax(jnp.where(starts2, idxs, 0))
    base = jnp.where(
        (seg_first2 > 0)[:, None],
        cums[jnp.maximum(seg_first2 - 1, 0)],
        0.0,
    )
    segcum = cums - base
    have2 = avail[jnp.clip(sp2, 0, n - 1)]
    accept2 = (sp2 < n) & jnp.all(segcum <= have2 + 1e-9, axis=-1)
    accept = jnp.zeros(k, bool).at[perm2].set(accept2)
    assignment = jnp.where(accept, pick, assignment).astype(jnp.int32)
    delta = (
        jnp.zeros((n, n_res), d.dtype)
        .at[jnp.where(accept, pick, n - 1)]
        .add(jnp.where(accept[:, None], d, 0.0))
    )
    return avail - delta, assignment


@functools.partial(
    jax.jit, static_argnames=("chunk", "rounds", "kc", "use_approx",
                              "passes", "use_pallas", "bucketed")
)
def chunked_match(
    problem: MatchProblem,
    *,
    chunk: int = 1024,
    rounds: int = 4,
    kc: int = 128,
    use_approx: bool = True,
    passes: int = 2,
    use_pallas: bool = False,
    bucketed: bool = False,
) -> MatchResult:
    """Fast chunked greedy matcher (see module docstring for the scheme).

    `passes` controls how many times per chunk the [K, N] fitness pass and
    top-kc candidate lists are recomputed against updated availability;
    between recomputes, `rounds` cheap [K, kc] conflict-resolution rounds
    run.  passes=2 recovers the placements that candidate-list truncation
    would otherwise lose when >kc jobs contend for the same nodes.

    `use_pallas` swaps the candidate pass for the fused Pallas kernel
    (ops/pallas_match.py): feasibility + fitness + argmax in ONE VMEM-
    resident sweep per job block, returning each job's single best node
    (kc is effectively 1, so give the pallas backend more `passes` —
    every pass re-picks fresh best nodes against updated availability).

    `bucketed` quantizes the chunk's jobs into at most 128 demand classes
    (log-spaced mem x cpu levels, gpu/disk presence bits) and computes ONE
    candidate list per class over the class's segment-max demand — a
    [B, N] candidate pass instead of [K, N], ~K/B x cheaper.  Real
    workloads cluster on a few requested shapes, so classes are dense.
    Class feasibility (segment-max demand) is conservative for the class's
    smaller jobs; the conflict rounds re-check exact per-job demands (and
    the constraint mask, which class-shared lists cannot pre-apply), so
    acceptance stays exact — the cost is candidate recall, recovered by
    `passes` like any other truncation."""
    j, n = problem.demands.shape[0], problem.avail.shape[0]
    assert j % chunk == 0, "pad jobs to a multiple of chunk"
    assert not (use_pallas and bucketed), "pick one candidate backend"
    if bucketed and passes < 2:
        # the bucketed scheme's acceptance-exactness story depends on the
        # final exact per-job pass; with passes=1 that pass would never
        # run and candidate recall silently collapses
        raise ValueError("bucketed candidate mode requires passes >= 2 "
                         "(the final pass is the exact per-job cleanup)")
    kc = min(kc, n)
    n_res = problem.demands.shape[-1]  # (mem, cpus, gpus[, disk...])
    demands_c = problem.demands.reshape(j // chunk, chunk, n_res)
    ok_c = problem.job_valid.reshape(j // chunk, chunk)
    if problem.feasible is not None:
        feas_c = problem.feasible.reshape(j // chunk, chunk, n)
    else:
        feas_c = jnp.ones((j // chunk, 1, 1), dtype=bool)
    denom = jnp.maximum(problem.totals, 1e-30)
    node_valid = problem.node_valid
    totals = problem.totals

    if use_pallas:
        import jax as jax_mod

        from cook_tpu.ops.pallas_match import best_node

        # Mosaic compiles only on real TPUs; everywhere else the kernel
        # runs in interpret mode (tests, CPU fallback)
        pallas_interpret = jax_mod.default_backend() != "tpu"

    # demand classes: 8 log-mem levels x 4 log-cpu levels x gpu bit
    # (x disk bit when the resource column exists)
    n_buckets = 8 * 4 * 2 * (2 if n_res > 3 else 1)

    def _bucket_ids(d, active):
        def levels(x, n_levels):
            lo = jnp.min(jnp.where(active, x, jnp.inf))
            hi = jnp.max(jnp.where(active, x, -jnp.inf))
            scale = jnp.maximum(hi - lo, 1e-6)
            lv = jnp.floor((x - lo) / scale * n_levels)
            return jnp.clip(lv, 0, n_levels - 1).astype(jnp.int32)

        b = levels(jnp.log(jnp.maximum(d[:, 0], 1e-3)), 8) * 4
        b = b + levels(jnp.log(jnp.maximum(d[:, 1], 1e-3)), 4)
        b = b * 2 + (d[:, 2] > 0).astype(jnp.int32)
        if n_res > 3:
            b = b * 2 + (d[:, 3] > 0).astype(jnp.int32)
        return b

    def chunk_step(avail, inputs):
        d, ok, fr = inputs  # [K,3], [K], [K,N]|[1,1]

        def score_topk(avail, demand_matrix, gate):
            """Shared candidate scoring: feasibility x fitness over the
            rows of `demand_matrix` ([M, R], jobs or demand classes),
            gated by `gate` ([M, N]-broadcastable), -> top-kc per row.
            ONE pipeline so the bucketed passes and the exact cleanup
            pass can never rank candidates by diverging rules."""
            fits = jnp.all(avail[None, :, :] >= demand_matrix[:, None, :],
                           axis=-1)
            feasible = fits & gate
            used0 = totals[:, 0] - avail[:, 0]
            used1 = totals[:, 1] - avail[:, 1]
            fit = binpack_fitness(used0[None, :], used1[None, :],
                                  demand_matrix[:, 0:1],
                                  demand_matrix[:, 1:2],
                                  denom[None, :, 0], denom[None, :, 1])
            if problem.node_bonus is not None:
                fit = fit + problem.node_bonus[None, :]
            score = jnp.where(feasible, fit, -BIG)
            if use_approx:
                return jax.lax.approx_max_k(score, kc, recall_target=0.95)
            return jax.lax.top_k(score, kc)

        def candidate_pass(avail, assignment, use_bucket=False):
            # full fitness pass for still-unplaced jobs vs current avail
            unplaced = assignment < 0
            if use_bucket:
                active = ok & unplaced
                bid = _bucket_ids(d, active)
                bdem = (jnp.zeros((n_buckets, n_res), d.dtype)
                        .at[bid].max(jnp.where(active[:, None], d, 0.0)))
                bval, bidx = score_topk(avail, bdem, node_valid[None, :])
                return (jnp.where(active[:, None], bval[bid], -BIG),
                        bidx[bid])
            if use_pallas:
                # fused feasibility+fitness+argmax; placed/invalid jobs
                # are excluded by an unsatisfiable demand
                d_eff = jnp.where((ok & unplaced)[:, None], d, 2 * BIG)
                feas_arg = (None if problem.feasible is None
                            else (fr & node_valid[None, :]))
                valid_arg = node_valid if problem.feasible is None \
                    else jnp.ones_like(node_valid)
                val, idx = best_node(d_eff, avail, totals,
                                     valid_arg, feas_arg,
                                     interpret=pallas_interpret)
                return val[:, None], jnp.maximum(idx, 0)[:, None]
            return score_topk(
                avail, d,
                node_valid[None, :] & fr & (ok & unplaced)[:, None])

        def round_step(carry, _):
            avail, assignment, cand_val, cand_idx = carry
            recheck = (fr if bucketed and problem.feasible is not None
                       else None)
            avail, assignment = conflict_round(
                avail, assignment, cand_val, cand_idx, d, n,
                recheck_mask=recheck)
            return (avail, assignment, cand_val, cand_idx), None

        # derive the init from chunk data rather than a constant: under
        # shard_map a replicated (unvarying) carry init clashes with the
        # varying carry the scan body produces (scan-vma typing)
        assignment = (d[:, 0] * 0).astype(jnp.int32) - 1
        for p in range(passes):
            # bucketed mode: cheap class-shared candidates for the early
            # passes, then ONE exact per-job pass so stragglers whose
            # class ordering diverged from their own fitness still land
            # (the early passes place the bulk, so most of the [K, N]
            # saving is kept)
            use_bucket = bucketed and p < passes - 1
            cand_val, cand_idx = candidate_pass(avail, assignment,
                                                use_bucket=use_bucket)
            (avail, assignment, _, _), _ = jax.lax.scan(
                round_step, (avail, assignment, cand_val, cand_idx),
                None, length=rounds,
            )
        return avail, assignment

    new_avail, assignment = jax.lax.scan(
        chunk_step, problem.avail, (demands_c, ok_c, feas_c)
    )
    return MatchResult(assignment=assignment.reshape(j), new_avail=new_avail)


# Pool-batched variants: vmap over a leading pool axis; `parallel.mesh`
# shards that axis across devices so per-pool problems solve concurrently
# over ICI (SURVEY §2.4: pools become a batch dimension of one TPU solve).
greedy_match_pools = jax.vmap(greedy_match)
chunked_match_pools = jax.vmap(chunked_match, in_axes=(0,))
