"""Jobs x nodes bin-packing as an on-device solve: the Fenzo replacement.

The reference's match cycle hands ranked jobs + offers to Netflix Fenzo's
single-threaded greedy `scheduleOnce` under a lock
(/root/reference/scheduler/src/cook/scheduler/scheduler.clj:617-687, fitness
knobs config.clj:108-116).  Here the same decision problem — place each job,
in fair-share order, on the feasible node with the best binpacking fitness —
is computed on TPU:

  * `greedy_match`: a `lax.scan` over ranked jobs; each step is a fully
    vectorized feasibility mask + fitness argmax over all N nodes (the MXU/
    VPU-friendly inner loop).  Bit-exact with the sequential CPU reference
    (`cpu_reference.ref_greedy_match`) including tie-breaks, so packing
    parity is exact by construction.

  * `chunked_match`: processes jobs in chunks of K with one conflict-
    resolution pass per chunk — each chunk computes all K best-node choices
    against a frozen availability snapshot, then accepts the longest prefix
    of non-conflicting picks per node via segmented prefix sums.  Identical
    results to `greedy_match` (conflicts are re-tried next chunk), but the
    scan length drops from J to J/K, which is what makes 100k-job cycles
    fast on TPU.

Constraints enter as a [J, N] boolean mask (see scheduler/constraints.py for
the encoders) and via node validity; group constraints that depend on
earlier placements in the same cycle are handled with on-device updates of
per-group host counts.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from cook_tpu.ops.common import BIG


class MatchProblem(NamedTuple):
    """One pool's padded matching problem."""

    demands: jnp.ndarray     # [J, 3] (mem, cpus, gpus) in schedule order
    job_valid: jnp.ndarray   # [J] bool
    avail: jnp.ndarray       # [N, 3] currently-available (offered) resources
    totals: jnp.ndarray      # [N, 2] (mem, cpus) capacity — fitness denominators
    node_valid: jnp.ndarray  # [N] bool
    feasible: Optional[jnp.ndarray] = None  # [J, N] bool constraint mask


class MatchResult(NamedTuple):
    assignment: jnp.ndarray  # [J] int32 node index or -1
    new_avail: jnp.ndarray   # [N, 3] availability after placements


def _job_step(avail, totals, node_valid, demand, job_ok, feas_row):
    """Place one job: feasibility mask + binpacking-fitness argmax."""
    fits = jnp.all(avail >= demand[None, :], axis=-1)
    feasible = fits & node_valid & feas_row & job_ok
    used = totals - avail[:, :2]
    denom = jnp.maximum(totals, 1e-30)
    fit = ((used[:, 0] + demand[0]) / denom[:, 0]
           + (used[:, 1] + demand[1]) / denom[:, 1]) * 0.5
    score = jnp.where(feasible, fit, -BIG)
    best = jnp.argmax(score)
    placed = score[best] > -BIG
    delta = jnp.where(placed, demand, 0.0)
    new_avail = avail.at[best].add(-delta)
    return new_avail, jnp.where(placed, best, -1).astype(jnp.int32)


@jax.jit
def greedy_match(problem: MatchProblem) -> MatchResult:
    """Sequential-order greedy matcher via lax.scan (exact Fenzo-order
    semantics; O(J) scan steps of O(N) vector work each)."""
    j = problem.demands.shape[0]
    # shape [J,1] when unconstrained: broadcasts against [N] without ever
    # materializing a [J,N] mask (100k x 10k bool would be ~1 GB)
    feas = (
        problem.feasible
        if problem.feasible is not None
        else jnp.ones((j, 1), dtype=bool)
    )

    def step(avail, inputs):
        demand, ok, feas_row = inputs
        new_avail, choice = _job_step(
            avail, problem.totals, problem.node_valid, demand, ok, feas_row
        )
        return new_avail, choice

    new_avail, assignment = jax.lax.scan(
        step, problem.avail, (problem.demands, problem.job_valid, feas)
    )
    return MatchResult(assignment=assignment, new_avail=new_avail)


@functools.partial(jax.jit, static_argnames=("chunk", "rounds"))
def chunked_match(
    problem: MatchProblem, *, chunk: int = 128, rounds: int = 4
) -> MatchResult:
    """Greedy matcher with chunked conflict resolution.

    Per chunk of K jobs (in schedule order):
      1. every job picks its best feasible node against the chunk-start
         availability snapshot;
      2. a pick is accepted iff its node can hold the cumulative demand of
         all earlier picks in the chunk that chose the same node (per-node
         prefix-sum test), so intra-chunk over-subscription is impossible;
      3. accepted placements are subtracted and the next chunk proceeds.

    Jobs whose pick conflicts in a round are retried in the next round
    against updated availability (`rounds` fixed rounds per chunk), so the
    only divergence from pure sequential greedy is (a) fitness choices made
    against a round-start snapshot rather than job-by-job, and (b) jobs
    still conflicted after the last round stay unplaced this cycle (as in a
    Fenzo cycle, they just wait).  Parity tests bound the packing gap vs
    `greedy_match`; use `greedy_match` where exactness is required.
    """
    j, n = problem.demands.shape[0], problem.avail.shape[0]
    assert j % chunk == 0, "pad jobs to a multiple of chunk"
    demands = problem.demands.reshape(j // chunk, chunk, 3)
    job_ok = problem.job_valid.reshape(j // chunk, chunk)
    if problem.feasible is not None:
        feas = problem.feasible.reshape(j // chunk, chunk, n)
    else:
        # [C,1,1]: broadcasts inside each chunk step without a [J,N] mask
        feas = jnp.ones((j // chunk, 1, 1), dtype=bool)
    denom = jnp.maximum(problem.totals, 1e-30)

    def round_step(carry, _):
        avail, assignment, d, fr = carry
        unplaced = assignment < 0
        fits = jnp.all(avail[None, :, :] >= d[:, None, :], axis=-1)  # [K,N]
        feasible = fits & problem.node_valid[None, :] & fr & unplaced[:, None]
        used = problem.totals - avail[:, :2]
        fit = ((used[None, :, 0] + d[:, 0:1]) / denom[None, :, 0]
               + (used[None, :, 1] + d[:, 1:2]) / denom[None, :, 1]) * 0.5
        score = jnp.where(feasible, fit, -BIG)         # [K,N]
        ranked = jnp.argsort(-score, axis=-1)          # [K,N] best-first
        first = ranked[:, 0]
        had_any = jnp.max(score, axis=-1) > -BIG
        # Contention spreading: if c earlier unplaced jobs (chunk order)
        # share my best node, I take my (c)th-best node instead — the
        # parallel analog of "earlier jobs grabbed it first".
        onehot0 = jax.nn.one_hot(first, n, dtype=jnp.float32) * had_any[:, None]
        crank = (jnp.cumsum(onehot0, axis=0) - onehot0)  # [K,N]
        c = jnp.take_along_axis(crank, first[:, None], axis=1)[:, 0]  # [K]
        c = jnp.clip(c.astype(jnp.int32), 0, n - 1)
        pick = jnp.take_along_axis(ranked, c[:, None], axis=1)[:, 0]
        pick_score = jnp.take_along_axis(score, pick[:, None], axis=1)[:, 0]
        picked = pick_score > -BIG
        # per-node prefix demand in chunk order: job k accepted iff its
        # node's cumulative demand through k fits that node's availability
        onehot = jax.nn.one_hot(pick, n, dtype=d.dtype) * picked[:, None]  # [K,N]
        prefix = jnp.cumsum(onehot[:, :, None] * d[:, None, :], axis=0)   # [K,N,3]
        need = jnp.take_along_axis(
            prefix, pick[:, None, None].repeat(3, axis=2), axis=1
        )[:, 0, :]                                      # [K,3]
        have = avail[pick]                              # [K,3]
        accept = picked & jnp.all(need <= have + 1e-9, axis=-1)
        assignment = jnp.where(accept, pick, assignment).astype(jnp.int32)
        placed_delta = jnp.sum(
            (onehot * accept[:, None])[:, :, None] * d[:, None, :], axis=0
        )                                               # [N,3]
        return (avail - placed_delta, assignment, d, fr), None

    def chunk_step(avail, inputs):
        d, ok, fr = inputs  # [K,3], [K], [K,N]
        assignment = jnp.where(ok, -1, -2).astype(jnp.int32)  # -2: never place
        (avail, assignment, _, _), _ = jax.lax.scan(
            round_step, (avail, assignment, d, fr), None, length=rounds
        )
        return avail, jnp.maximum(assignment, -1)

    new_avail, assignment = jax.lax.scan(
        chunk_step, problem.avail, (demands, job_ok, feas)
    )
    return MatchResult(
        assignment=assignment.reshape(j), new_avail=new_avail
    )


# Pool-batched variants: vmap over a leading pool axis; `parallel.mesh`
# shards that axis across devices so per-pool problems solve concurrently
# over ICI (SURVEY §2.4: pools become a batch dimension of one TPU solve).
greedy_match_pools = jax.vmap(greedy_match)
chunked_match_pools = jax.vmap(chunked_match, in_axes=(0,))
