"""DRU fair-share ranking as a batched tensor solve.

Replaces the reference's lazy k-way sorted merge
(/root/reference/scheduler/src/cook/scheduler/dru.clj:50-126 and
`sort-jobs-by-dru-pool`, scheduler/scheduler.clj:2073-2175) with:

  1. lexicographic sort of all tasks by (user, order_key)  -- the reference's
     per-user sorted task lists, flattened;
  2. per-user segmented cumulative sums of (mem, cpus) / divisors, DRU =
     elementwise max  -- `compute-task-scored-task-pairs`;
  3. one global stable sort by (dru, order)  -- `sorted-merge`.

Semantics preserved: within a user, tasks are ordered by the caller-provided
order key ((-priority, start-time, id) in the rank cycle); each task's DRU is
the cumulative dominant share of that user's tasks up to and including it;
ties in DRU may break arbitrarily (dru.clj docstring for
`sorted-task-scored-task-pairs` explicitly allows any order on equal dru).

All inputs are fixed-size padded arrays (mask via `valid`); the whole thing
is jit-able and vmap-able over a pool batch axis.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from cook_tpu.ops.common import BIG, inverse_permutation, lexsort_perm, segmented_cumsum


class DruTasks(NamedTuple):
    """Padded task tensors for one pool.  Tasks cover BOTH running tasks and
    pending jobs (treated as hypothetical tasks), exactly like the rank
    cycle's input."""

    user: jnp.ndarray       # [T] int32 user index
    mem: jnp.ndarray        # [T] f32
    cpus: jnp.ndarray       # [T] f32
    gpus: jnp.ndarray       # [T] f32
    order_key: jnp.ndarray  # [T] f32/int — per-user task order (smaller first)
    valid: jnp.ndarray      # [T] bool


class DruResult(NamedTuple):
    dru: jnp.ndarray        # [T] f32 per-task cumulative DRU (BIG on padding)
    rank: jnp.ndarray       # [T] int32 global rank position per task
                            # (0 = schedule first; padding ranks last)
    order: jnp.ndarray      # [T] int32 task indices in global DRU order


@functools.partial(jax.jit, static_argnames=("gpu_mode",))
def dru_rank(
    tasks: DruTasks,
    mem_div: jnp.ndarray,   # [U] per-user mem divisor (share)
    cpu_div: jnp.ndarray,   # [U]
    gpu_div: jnp.ndarray,   # [U]
    *,
    gpu_mode: bool = False,
    backfill: jnp.ndarray = None,        # [T] f32 in [0, 1], or None
    backfill_weight: jnp.ndarray = None,  # scalar weight of the term
) -> DruResult:
    """Compute per-task cumulative DRU and the global fair-share order.

    gpu_mode selects the reference's `:pool.dru-mode/gpu` scoring
    (cumulative gpus/divisor) instead of max(mem, cpus) dominant share.

    `backfill` is the predicted-duration column (scheduler/prediction.py):
    a per-task normalized duration fraction in [0, 1] added to the DRU as
    `dru + backfill_weight * fraction` BEFORE the global order sort, so
    predicted-short jobs backfill ahead of predicted-long ones at
    near-equal fairness.  BOUNDED by construction: the shift is at most
    `backfill_weight`, so a short job can only jump jobs within that DRU
    band — fairness inversions are capped, and weight 0 (or backfill
    None) reproduces the unadjusted order bit-for-bit.  The returned
    `dru` column stays the raw fair-share score either way (the term
    reorders; it never rewrites the fairness accounting).
    """
    user = tasks.user
    valid = tasks.valid
    t = user.shape[0]

    # Push padding to the end of every sort: invalid users sort as +inf.
    user_sort_key = jnp.where(valid, user, jnp.iinfo(jnp.int32).max)
    perm = lexsort_perm(user_sort_key, tasks.order_key)

    s_user = user[perm]
    s_valid = valid[perm]
    res = jnp.stack([tasks.mem[perm], tasks.cpus[perm], tasks.gpus[perm]], axis=-1)
    res = jnp.where(s_valid[:, None], res, 0.0)

    cum = segmented_cumsum(res, jnp.where(s_valid, s_user, -1))
    s_mem_div = jnp.take(mem_div, s_user, mode="clip")
    s_cpu_div = jnp.take(cpu_div, s_user, mode="clip")
    s_gpu_div = jnp.take(gpu_div, s_user, mode="clip")
    if gpu_mode:
        dru_sorted = cum[:, 2] / jnp.maximum(s_gpu_div, 1e-30)
    else:
        dru_sorted = jnp.maximum(
            cum[:, 0] / jnp.maximum(s_mem_div, 1e-30),
            cum[:, 1] / jnp.maximum(s_cpu_div, 1e-30),
        )
    dru_sorted = jnp.where(s_valid, dru_sorted, BIG)

    # back to original task order
    inv = inverse_permutation(perm)
    dru = dru_sorted[inv]

    # global order: stable sort by dru, tie-broken by the per-user position
    # so the within-user order is preserved even on equal dru (critical: a
    # user's later task must never schedule before an earlier one).
    score = dru
    if backfill is not None:
        w = backfill_weight if backfill_weight is not None else 0.0
        score = jnp.where(valid,
                          dru + w * jnp.clip(backfill, 0.0, 1.0), BIG)
    order = lexsort_perm(score, tasks.order_key)
    rank = inverse_permutation(order)
    return DruResult(dru=dru, rank=rank.astype(jnp.int32),
                     order=order.astype(jnp.int32))


# Batched over a leading pool axis; shard this axis over the device mesh for
# the multi-pool solve (parallel/mesh.py wires the shardings).
dru_rank_pools = jax.vmap(
    lambda tasks, md, cd, gd: dru_rank(tasks, md, cd, gd),
    in_axes=(DruTasks(0, 0, 0, 0, 0, 0), 0, 0, 0),
)
