"""Preemption-victim search as a tensor solve.

Replaces the reference rebalancer's per-host sequential prefix scan
(/root/reference/scheduler/src/cook/rebalancer.clj:320-407): among all
(host, prefix-of-highest-DRU-tasks) candidates that free enough resources
for the pending job, pick the one whose minimum preempted DRU is largest
(preempt the least-deserving work possible); a host whose spare resources
alone cover the demand scores +inf (preempt nothing).

Tensorized as: mask-filter tasks -> sort by (host, -dru) -> per-host
segmented prefix sums seeded with host spare -> first-feasible-prefix per
host (the max-min-DRU prefix for that host) -> global argmax over hosts.
One kernel call evaluates all 100k tasks x 10k hosts at once.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cook_tpu.ops.common import BIG, lexsort_perm, segmented_cumsum


class RebalanceState(NamedTuple):
    """Padded running-task + host tensors for one pool."""

    task_host: jnp.ndarray      # [T] int32 host index
    task_dru: jnp.ndarray       # [T] f32
    task_res: jnp.ndarray       # [T, R] (mem, cpus, gpus[, disk...])
    task_eligible: jnp.ndarray  # [T] bool (valid & quota/user filters & not preempted)
    spare: jnp.ndarray          # [H, R] spare resources per host
    host_ok: jnp.ndarray        # [H] bool (constraints pass for the pending job)


class PreemptionDecision(NamedTuple):
    host: jnp.ndarray          # int32 chosen host, -1 if none
    score: jnp.ndarray         # f32 min-preempted-dru of the decision (BIG = spare-only)
    preempt_mask: jnp.ndarray  # [T] bool — tasks to preempt
    freed: jnp.ndarray         # [R] resources freed on the chosen host (spare + preempted)


def _decide_sorted_core(s_host, s_dru, s_res, s_valid, spare, host_ok,
                        demand) -> PreemptionDecision:
    """The decision tail shared by both kernels, over host-sorted arrays
    (s_* sorted by (host asc, dru desc)); returns the preempt mask in
    SORTED space.  `s_valid` is the per-decision validity (eligibility +
    dru thresholds); invalid rows must already contribute zero `s_res`.
    """
    t = s_host.shape[0]
    h = spare.shape[0]
    # Per-host prefix sums of freed resources, seeded with the host's spare.
    cum = segmented_cumsum(s_res, s_host)
    in_range = (s_host >= 0) & (s_host < h)
    spare_of = jnp.where(
        in_range[:, None], spare[jnp.clip(s_host, 0, h - 1)], 0.0)
    freed = cum + spare_of
    prefix_feasible = jnp.all(freed >= demand[None, :], axis=-1) & s_valid

    host_allowed = jnp.where(
        in_range, host_ok[jnp.clip(s_host, 0, h - 1)], False)
    # Candidate score: dru of the last task in the prefix (== min in prefix,
    # since sorted desc).  Only the FIRST feasible prefix per host matters —
    # longer ones can only lower the min-dru — and within a host that is the
    # prefix ending at the first position where prefix_feasible flips true.
    feas_cum = segmented_cumsum(prefix_feasible.astype(jnp.int32), s_host)
    first_feasible = prefix_feasible & (feas_cum == 1)

    cand_score = jnp.where(first_feasible & host_allowed, s_dru, -BIG)

    # Spare-only candidates: hosts whose spare covers demand preempt nothing
    # and score BIG (reference: Double/MAX_VALUE pseudo-task).
    spare_fits = jnp.all(spare >= demand[None, :], axis=-1) & host_ok
    spare_score = jnp.where(spare_fits, BIG, -BIG)

    best_task_pos = jnp.argmax(cand_score)
    best_task_score = cand_score[best_task_pos]
    best_spare_host = jnp.argmax(spare_score)
    best_spare_score = spare_score[best_spare_host]

    use_spare = best_spare_score >= best_task_score
    none_found = (best_task_score <= -BIG) & (best_spare_score <= -BIG)

    chosen_host = jnp.where(
        use_spare, best_spare_host, s_host[best_task_pos]
    ).astype(jnp.int32)
    chosen_host = jnp.where(none_found, -1, chosen_host)
    score = jnp.where(use_spare, best_spare_score, best_task_score)

    # Preempt-mask: tasks in the chosen host's prefix up through best_task_pos.
    same_host = s_host == s_host[best_task_pos]
    in_prefix = same_host & (jnp.arange(t) <= best_task_pos) & s_valid
    take_tasks = (~use_spare) & (~none_found)
    preempt_sorted = in_prefix & take_tasks

    freed_amount = jnp.where(
        none_found,
        jnp.zeros_like(demand),
        jnp.where(
            use_spare,
            spare[jnp.clip(best_spare_host, 0, h - 1)],
            freed[best_task_pos],
        ),
    )
    return PreemptionDecision(
        host=chosen_host,
        score=jnp.where(none_found, -BIG, score),
        preempt_mask=preempt_sorted,
        freed=freed_amount,
    )


@jax.jit
def find_preemption_decision(
    state: RebalanceState,
    demand: jnp.ndarray,        # [R] pending job resources
    pending_dru: jnp.ndarray,   # scalar
    safe_dru_threshold: jnp.ndarray,
    min_dru_diff: jnp.ndarray,
) -> PreemptionDecision:
    t = state.task_host.shape[0]

    mask = (
        state.task_eligible
        & (state.task_dru >= safe_dru_threshold)
        & ((state.task_dru - pending_dru) > min_dru_diff)
    )

    # Sort tasks by (host asc, dru desc, index asc); masked-out tasks sink to
    # a sentinel host so they never join a real segment.
    host_key = jnp.where(mask, state.task_host, jnp.iinfo(jnp.int32).max)
    idx = jnp.arange(t)
    perm = lexsort_perm(host_key, -state.task_dru, idx)
    s_host = host_key[perm]
    s_dru = state.task_dru[perm]
    s_res = jnp.where(mask[perm][:, None], state.task_res[perm], 0.0)
    s_valid = mask[perm]

    decision = _decide_sorted_core(s_host, s_dru, s_res, s_valid,
                                   state.spare, state.host_ok, demand)
    # scatter the sorted-space mask back to original task order
    preempt = jnp.zeros(t, dtype=bool).at[perm].set(decision.preempt_mask)
    return decision._replace(preempt_mask=preempt)


class SortedRebalanceState(NamedTuple):
    """Task tensors pre-sorted by (host asc, dru desc) ONCE per cycle.

    The full find_preemption_decision re-sorts all T tasks every call; at
    the reference's max-preemption=100 decisions per cycle that is 100
    sorts of the same data.  DRU values and task rows are immutable
    within a fast cycle (see decide_from_sorted for the divergences), so
    the sort is amortized: each decision is a per-decision [T] validity
    mask + segmented cumsums + argmax — no sort.
    """

    perm: jnp.ndarray    # [T] original row index per sorted position
    s_host: jnp.ndarray  # [T] host key (sentinel INT32_MAX for ineligible)
    s_dru: jnp.ndarray   # [T]
    s_res: jnp.ndarray   # [T, R]


@jax.jit
def sort_rebalance_state(
    task_host: jnp.ndarray,
    task_dru: jnp.ndarray,
    task_res: jnp.ndarray,
    task_eligible: jnp.ndarray,
) -> SortedRebalanceState:
    """One fused multi-key sort of the cycle's tasks (see docstring)."""
    t = task_host.shape[0]
    host_key = jnp.where(task_eligible, task_host,
                         jnp.iinfo(jnp.int32).max)
    perm = lexsort_perm(host_key, -task_dru, jnp.arange(t))
    return SortedRebalanceState(
        perm=perm,
        s_host=host_key[perm],
        s_dru=task_dru[perm],
        s_res=task_res[perm],
    )


@jax.jit
def decide_from_sorted(
    ss: SortedRebalanceState,
    row_ok_sorted: jnp.ndarray,  # [T] per-decision validity, sorted space
    dru_sorted: jnp.ndarray,     # [T] LIVE dru values, sorted space
    spare: jnp.ndarray,          # [H, R]
    host_ok: jnp.ndarray,        # [H] bool
    demand: jnp.ndarray,         # [R]
    pending_dru: jnp.ndarray,
    safe_dru_threshold: jnp.ndarray,
    min_dru_diff: jnp.ndarray,
) -> PreemptionDecision:
    """find_preemption_decision against a pre-sorted cycle state.

    Masked rows (preempted earlier this cycle, quota-restricted, below
    threshold for THIS pending job) stay in their host segment with zero
    resource contribution, which yields the same prefix sums over the
    remaining valid rows as a fresh sort would.  `dru_sorted` carries the
    LIVE rescored values (cheap per-decision gather), so the safety
    threshold, min-diff guard, and min-preempted-dru score are exact; the
    residual divergences vs the exact kernel are (a) the within-host
    ORDER is frozen at cycle start — a user whose dru changed mid-cycle
    keeps the stale prefix order — and (b) simulated launches consume
    host spare instead of joining the task rows (they cannot be
    re-preempted within the cycle).

    The returned preempt_mask is in SORTED space; map positions back with
    `ss.perm`."""
    h = spare.shape[0]
    m = (
        row_ok_sorted
        & (dru_sorted >= safe_dru_threshold)
        & ((dru_sorted - pending_dru) > min_dru_diff)
        & (ss.s_host < h)
    )
    res_eff = jnp.where(m[:, None], ss.s_res, 0.0)
    return _decide_sorted_core(ss.s_host, dru_sorted, res_eff, m,
                               spare, host_ok, demand)
