"""Config file handling: JSON -> validated settings dataclasses.

Reference: cook.config (/root/reference/scheduler/src/cook/config.clj —
EDN + prismatic-schema validation, docs/configuration.adoc) including the
pool-regex-scoped scheduler configs (`pool-schedulers`, regexp_tools.clj)
and runtime-mutable sections.
"""
from __future__ import annotations

import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.scheduler.rebalancer import RebalancerParams

log = logging.getLogger(__name__)


def tuned_match_defaults(path: Optional[str] = None) -> dict:
    """Hardware-sweep-promoted matcher defaults.

    `tools/pick_tuned.py` writes the best measured sweep config (packing
    efficiency >= its --min-eff bar vs the sequential-greedy oracle) to
    `tuned_match.json`; the service treats it as the DEFAULT matcher
    config so production gets the tuned chunked kernel, not the exact
    O(J)-scan fallback.  Explicit `match` config keys always win.
    Exactly ONE source is consulted: the `path` arg when given;
    otherwise $COOK_TUNED_MATCH when set (""/"none"/"off" disables tuned
    defaults entirely); otherwise the repo-root tuned_match.json.
    Returns {} (pure dataclass defaults) when the consulted source is
    absent or unreadable.
    """
    env = os.environ.get("COOK_TUNED_MATCH")
    if path:
        candidates = [path]
    elif env is not None:
        candidates = [] if env.lower() in ("", "none", "off") else [env]
    else:
        candidates = [os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "tuned_match.json")]
    for p in candidates:
        try:
            with open(p) as f:
                loaded = json.load(f)
        except FileNotFoundError:
            continue
        except (OSError, ValueError) as e:
            # an EXISTING tuned file that cannot be read/parsed silently
            # reverting production to the untuned exact kernel is the
            # perf trap this mechanism exists to prevent — say so
            log.warning("tuned match config %s exists but is unusable "
                        "(%s); falling back to untuned defaults", p, e)
            continue
        if not isinstance(loaded, dict):
            log.warning("tuned match config %s is not a JSON object; "
                        "falling back to untuned defaults", p)
            continue
        # pick_tuned writes sweep-style names (rounds/passes/kc);
        # translate to the MatchConfig field names
        out = {}
        for src, dst in (("chunk", "chunk"), ("rounds", "chunk_rounds"),
                         ("passes", "chunk_passes"), ("kc", "chunk_kc"),
                         ("backend", "backend")):
            if src in loaded:
                out[dst] = loaded[src]
        return out
    return {}


@dataclass
class PoolSchedulerConfig:
    """Per-pool-regex matcher knobs (reference `pool-schedulers`)."""

    pool_regex: str
    match: MatchConfig = field(default_factory=MatchConfig)

    def matches(self, pool_name: str) -> bool:
        return re.fullmatch(self.pool_regex, pool_name) is not None


@dataclass
class Settings:
    port: int = 12321
    default_pool: str = "default"
    mea_culpa_failure_limit: int = 5
    rank_interval_s: float = 5.0
    match_interval_s: float = 1.0
    rebalancer_interval_s: float = 20.0
    lingering_interval_s: float = 60.0
    straggler_interval_s: float = 60.0
    cancelled_interval_s: float = 3.0
    optimizer_interval_s: float = 0.0   # 0 = disabled
    rebalancer: RebalancerParams = field(default_factory=RebalancerParams)
    match: MatchConfig = field(default_factory=MatchConfig)
    pool_schedulers: list[PoolSchedulerConfig] = field(default_factory=list)
    pools: list[dict] = field(default_factory=lambda: [{"name": "default"}])
    clusters: list[dict] = field(default_factory=list)
    # one batched device call for all pools per match tick instead of
    # round-robin one-pool-per-tick (docs/tpu-design.md pool sharding)
    batched_match: bool = False
    # pipelined multi-pool match pass (scheduler/pipeline.py): overlap
    # host encode/launch with the device solve; takes precedence over
    # batched_match when both are set
    pipelined_match: bool = False
    # prediction-assisted speculative cycles (scheduler/prediction.py):
    # pre-dispatch cycle N+1's solve against the predicted offer set
    # while cycle N's launches drain; a stale speculation is dropped at
    # commit, never repaired.  Off by default.
    speculation: bool = False
    # how far ahead (wall ms) a running task's predicted finish may sit
    # and still be assumed complete by the speculative solve
    speculation_horizon_ms: float = 30_000.0
    # runtime-predictor knobs (per-(user, command-fingerprint) rolling
    # quantiles; scheduler/prediction.QuantileRuntimePredictor)
    predictor_quantile: float = 0.75
    predictor_window: int = 64
    predictor_min_samples: int = 3
    # predicted-duration backfill: bounded DRU scoring term (ops/dru.py);
    # 0 disables (rank order untouched)
    backfill_weight: float = 0.0
    backfill_norm_ms: float = 600_000.0
    leader_lease_path: str = ""
    # networked election (control/lease_server.py — the ZK role): takes
    # precedence over leader_lease_path when set
    leader_endpoint: str = ""
    leader_group: str = "cook"
    leader_ttl_s: float = 10.0
    # URL peers reach THIS node at (lease advertisement + standby
    # replication); default http://127.0.0.1:{port}
    advertised_url: str = ""
    # identity standbys present to the leader's /replication endpoints
    # (must be in the leader's admins)
    replication_user: str = "admin"
    # durable-on-ack submissions (datomic.clj:79 semantics): POST /jobs
    # blocks until >= replication_min_acks standbys confirmed the write,
    # bounded by replication_ack_timeout_s (a timeout still commits but
    # the response carries "replicated": false)
    replication_sync_ack: bool = False
    replication_min_acks: int = 1
    replication_ack_timeout_s: float = 5.0
    # acks older than this stop counting toward min_acks (decommissioned
    # standbys are pruned); <= 0 disables liveness qualification
    replication_ack_liveness_s: float = 30.0
    data_dir: str = ""                  # "" = in-memory only
    snapshot_interval_s: float = 300.0
    # sharded control plane (cook_tpu/shard/): partition the store,
    # journal, idempotency table, and replication stream into this many
    # shards (per-pool routing, hashed-user fallback).  1 = the classic
    # single-store layout, byte-for-byte unchanged.  A data_dir laid out
    # for the single journal auto-migrates (exactly once, manifest-
    # stamped) at startup when shards > 1.
    shards: int = 1
    # replica-served reads (cook_tpu/shard/replica.py): non-leader nodes
    # serve heavy read endpoints from their replayed journal with
    # bounded staleness (X-Cook-Staleness-Ms); above the ceiling the
    # read falls back to the leader, and a replica that stopped applying
    # for replica_refuse_after_s refuses reads
    replica_reads: bool = True
    replica_staleness_ceiling_ms: float = 5000.0
    replica_refuse_after_s: float = 30.0
    # pin jax to a platform at process start ("cpu", "tpu", ...); "" =
    # environment default.  Scheduler nodes doing pure control-plane
    # work (tests, standbys on cpu machines) set "cpu" so a wedged or
    # slow accelerator can never stall the scheduling loops.
    platform: str = ""
    admins: tuple = ("admin",)
    queue_limit_per_pool: int = 1_000_000
    queue_limit_per_user: int = 100_000
    submission_rate_per_minute: float = 0.0
    cors_origins: tuple = ()  # exact strings or regexes; empty = no CORS
    # authenticator config ({"kind": "dev"|"basic"|"spnego"|"composite"});
    # empty = the permissive dev stack (rest/auth.py)
    auth: dict = field(default_factory=dict)
    # shared secret for executor heartbeat/progress posts ("" = not
    # enforced); executors read it from COOK_EXECUTOR_TOKEN
    executor_token: str = ""
    # plugin seams: dotted paths per seam + pool-mover rules
    # (scheduler/plugins.py registry_from_config)
    plugins: dict = field(default_factory=dict)
    # elastic capacity plane (cook_tpu/elastic/): planning-interval
    # trigger (0 = disabled) + planner knobs ({"headroom": ...,
    # "rank_half_life": ..., "reclaim_window": ...})
    elastic_interval_s: float = 0.0
    elastic: dict = field(default_factory=dict)
    # REST-layer knobs beyond the dedicated top-level keys
    # ({"max_gang_size": ...}; docs/configuration.md "Gang scheduling")
    api: dict = field(default_factory=dict)
    # resilience plane (docs/resilience.md):
    # POST /debug/faults arm/disarm — NEVER enable outside a chaos drill
    fault_injection: bool = False
    # what a journal fsync FAILURE means: "fail-stop" (commit reports
    # undurable + leader demotes) or "degrade-async" (keep committing
    # without the disk barrier, health reason journal-fsync-degraded)
    journal_fsync_policy: str = "fail-stop"
    # 429 + Retry-After on heavy reads while the commit-ack SLO burns
    load_shedding: bool = True
    # incident observatory (cook_tpu/obs/incident.py): ok->degraded
    # health transitions snapshot evidence bundles (GET /debug/incidents).
    # incident_dir "" = data_dir/incidents when data_dir is set, else
    # in-memory only; the health-watch loop evaluates the merged verdict
    # every interval so capture doesn't depend on external probes
    incident_dir: str = ""
    incident_capacity: int = 32
    incident_cooldown_s: float = 30.0
    health_watch_interval_s: float = 15.0
    # durable multi-resolution metrics history (cook_tpu/obs/tsdb.py):
    # a background sampler polls the metrics registry every
    # history_sample_s into raw -> 1m -> 10m rollup rings, persisted
    # under data_dir/metrics/ and served at GET /debug/history.
    # <= 0 disables the sampler (the endpoint still serves, empty).
    history_sample_s: float = 10.0
    # retention overrides ({"raw_points": .., "rollup_points": ..,
    # "segment_lines": .., "max_segments": .., "key_series": [..],
    # "incident_window_s": ..}); {} = HistoryConfig defaults
    history_retention: dict = field(default_factory=dict)
    # fleet observatory (cook_tpu/obs/fleet.py), a leader duty: poll
    # every known peer (this list + every standby registered through
    # replication acks) for health/staleness every fleet_poll_s and
    # serve the merged verdict at GET /debug/fleet.  <= 0 disables.
    peers: tuple = ()
    fleet_poll_s: float = 5.0
    # automatic device-profile capture on device-latency-shaped
    # degradations (solve-latency-regression, device-degraded),
    # cooldown-rate-limited; POST /debug/profile works regardless.
    # commit-ack-slo-burn deliberately never auto-profiles: the
    # capture's overhead deepens a control-plane burn (obs/profiling.py)
    auto_profile: bool = True
    profile_dir: str = ""

    def match_config_for_pool(self, pool_name: str) -> MatchConfig:
        for ps in self.pool_schedulers:
            if ps.matches(pool_name):
                return ps.match
        return self.match


def _match_config(d: dict) -> MatchConfig:
    tuned = tuned_match_defaults()
    d = {**tuned, **d}  # explicit config keys override tuned defaults
    return MatchConfig(
        max_jobs_considered=int(d.get("max_jobs_considered", 1000)),
        scaleback=float(d.get("scaleback", 0.95)),
        chunk=int(d.get("chunk", 0)),
        chunk_rounds=int(d.get("chunk_rounds", 6)),
        chunk_passes=int(d.get("chunk_passes", 2)),
        chunk_kc=int(d.get("chunk_kc", 128)),
        backend=str(d.get("backend", "xla")),
        quality_audit_every=int(d.get("quality_audit_every", 50)),
        completion_multiplier=float(d.get("completion_multiplier", 0.0)),
        host_lifetime_mins=float(d.get("host_lifetime_mins", 0.0)),
        agent_start_grace_mins=float(d.get("agent_start_grace_mins", 10.0)),
        checkpoint_memory_overhead_mb=float(
            d.get("checkpoint_memory_overhead_mb", 0.0)),
        device_fallback_cycles=int(d.get("device_fallback_cycles", 8)),
        device_latency_guard=float(d.get("device_latency_guard", 0.0)),
        # hierarchical two-level matcher (ops/hierarchical.py): engages
        # when padded jobs x nodes reaches the threshold (0 = off)
        hierarchical_threshold=int(d.get("hierarchical_threshold", 0)),
        hierarchical_nodes_per_block=int(
            d.get("hierarchical_nodes_per_block", 0)),
        hierarchical_jobs_per_block=int(
            d.get("hierarchical_jobs_per_block", 0)),
        hierarchical_refine_rounds=int(
            d.get("hierarchical_refine_rounds", 2)),
        # superblock (DCN-domain) layer above the topology blocks:
        # nodes per superblock, 0 = single-level coarse pass.  Primary
        # key `hier_superblock_nodes`; the long form is an alias.
        hierarchical_superblock_nodes=int(
            d.get("hier_superblock_nodes",
                  d.get("hierarchical_superblock_nodes", 0))),
        hierarchical_coarse_backend=str(
            d.get("hierarchical_coarse_backend", "xla")),
        hierarchical_use_mesh=bool(d.get("hierarchical_use_mesh", True)),
        hierarchical_fine_backend=str(
            d.get("hierarchical_fine_backend", "xla")),
        # device-resident match state + quantized cost tensors
        # (scheduler/device_state.py; docs/configuration.md)
        device_residency=bool(d.get("device_residency", False)),
        quantized=bool(d.get("quantized", False)),
        quantization_parity_floor=float(
            d.get("quantization_parity_floor", 0.98)),
        # topology-aware gang scheduling (scheduler/gang.py;
        # docs/configuration.md "Gang scheduling")
        gang_enabled=bool(d.get("gang_enabled", True)),
        topology_weight=float(d.get("topology_weight", 0.0)),
        topology_block_hosts=int(d.get("topology_block_hosts", 0)),
    )


def default_match_config(**overrides) -> MatchConfig:
    """The service/sim default matcher config: dataclass defaults merged
    under the hardware-tuned `tuned_match.json` (when present) and any
    explicit overrides (highest precedence)."""
    return _match_config(overrides)


def read_config(path: Optional[str] = None,
                overrides: Optional[dict] = None) -> Settings:
    data: dict[str, Any] = {}
    if path:
        with open(path) as f:
            data = json.load(f)
    if overrides:
        data.update(overrides)
    settings = Settings()
    for key in ("port", "default_pool", "mea_culpa_failure_limit",
                "rank_interval_s", "match_interval_s",
                "rebalancer_interval_s", "optimizer_interval_s",
                "leader_lease_path", "leader_endpoint", "leader_group",
                "leader_ttl_s", "advertised_url", "replication_user",
                "replication_sync_ack", "replication_min_acks",
                "replication_ack_timeout_s", "replication_ack_liveness_s",
                "data_dir", "snapshot_interval_s", "platform",
                "shards", "replica_reads",
                "replica_staleness_ceiling_ms", "replica_refuse_after_s",
                "batched_match", "pipelined_match", "speculation",
                "speculation_horizon_ms", "predictor_quantile",
                "predictor_window", "predictor_min_samples",
                "backfill_weight", "backfill_norm_ms",
                "elastic_interval_s",
                "fault_injection", "journal_fsync_policy", "load_shedding",
                "incident_dir", "incident_capacity", "incident_cooldown_s",
                "health_watch_interval_s", "auto_profile", "profile_dir",
                "history_sample_s", "fleet_poll_s",
                "queue_limit_per_pool",
                "queue_limit_per_user", "submission_rate_per_minute"):
        if key in data:
            setattr(settings, key, data[key])
    if "admins" in data:
        settings.admins = tuple(data["admins"])
    if "cors_origins" in data:
        settings.cors_origins = tuple(data["cors_origins"])
    if "auth" in data:
        settings.auth = dict(data["auth"])
    if "plugins" in data:
        settings.plugins = dict(data["plugins"])
    if "elastic" in data:
        settings.elastic = dict(data["elastic"])
    if "api" in data:
        settings.api = dict(data["api"])
    if "executor_token" in data:
        settings.executor_token = str(data["executor_token"])
    if "peers" in data:
        settings.peers = tuple(data["peers"])
    if "history_retention" in data:
        settings.history_retention = dict(data["history_retention"])
    if "pools" in data:
        settings.pools = data["pools"]
    if "clusters" in data:
        settings.clusters = data["clusters"]
    if "rebalancer" in data:
        rb = data["rebalancer"]
        settings.rebalancer = RebalancerParams(
            safe_dru_threshold=float(rb.get("safe_dru_threshold", 1.0)),
            min_dru_diff=float(rb.get("min_dru_diff", 0.5)),
            max_preemption=int(rb.get("max_preemption", 100)),
            fast_cycle=bool(rb.get("fast_cycle", False)),
            gang_enabled=bool(rb.get("gang_enabled", True)),
            gang_max_admissions=int(rb.get("gang_max_admissions", 4)),
            gang_drain_max_wait_ms=float(
                rb.get("gang_drain_max_wait_ms", 300_000.0)),
            gang_drain_wasted_factor=float(
                rb.get("gang_drain_wasted_factor", 1.0)),
            resident=bool(rb.get("resident", False)),
        )
    # resident-mirror shorthands (docs/configuration.md): top-level
    # bools feeding the rebalancer / elastic `resident` knobs; an
    # explicit section-level `resident` wins
    if "resident_rebalancer" in data:
        rb = data.get("rebalancer")
        if not isinstance(rb, dict) or "resident" not in rb:
            settings.rebalancer.resident = bool(data["resident_rebalancer"])
    if "resident_elastic" in data and "resident" not in settings.elastic:
        settings.elastic["resident"] = bool(data["resident_elastic"])
    # always route through _match_config so the tuned hardware defaults
    # apply even when the operator config has no `match` section — a bare
    # config must not fall into the exact-kernel (chunk=0) perf trap
    settings.match = _match_config(data.get("match", {}))
    for ps in data.get("pool_schedulers", []):
        settings.pool_schedulers.append(
            PoolSchedulerConfig(
                pool_regex=ps["pool_regex"],
                match=_match_config(ps.get("match", {})),
            )
        )
    _validate(settings)
    return settings


def _validate(s: Settings) -> None:
    if not (0 < s.port < 65536):
        raise ValueError(f"bad port {s.port}")
    if not (0.0 < s.predictor_quantile <= 1.0):
        raise ValueError(f"bad predictor_quantile {s.predictor_quantile} "
                         "(expected (0, 1])")
    if s.backfill_weight < 0:
        raise ValueError(f"bad backfill_weight {s.backfill_weight}")
    for url in s.peers:
        if not str(url).startswith(("http://", "https://")):
            raise ValueError(f"bad peer url {url!r} (http(s)://... "
                             "required)")
    if s.journal_fsync_policy not in ("fail-stop", "degrade-async"):
        raise ValueError(
            f"bad journal_fsync_policy {s.journal_fsync_policy!r} "
            f"(fail-stop | degrade-async)")
    if s.match.scaleback <= 0 or s.match.scaleback > 1:
        raise ValueError(f"bad scaleback {s.match.scaleback}")
    if not s.pools:
        raise ValueError("at least one pool required")
    names = [p["name"] for p in s.pools]
    if len(names) != len(set(names)):
        raise ValueError("duplicate pool names")
