"""Metrics registry: counters, gauges, histograms with Prometheus text
rendering.

Reference: cook.prometheus-metrics (/root/reference/scheduler/src/cook/
prometheus_metrics.clj — ~200 named metrics + `with-duration` wrappers
around every hot section) and the codahale stack (reporter.clj).  One
process-global registry; the REST /metrics endpoint renders it.
"""
from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Iterable, Optional


def _labels_key(labels: Optional[dict]) -> tuple:
    return tuple(sorted((labels or {}).items()))


def prometheus_name(name: str) -> str:
    """The exposition-time mapping from registry names to Prometheus
    identifiers — THE definition; every consumer that needs to match
    rendered names against registry names (obs/fleet.parse_headline,
    tools/lint_metrics standalone copy) must agree with it."""
    return "cook_" + name.replace(".", "_").replace("-", "_")


class BoundCounter:
    """A counter pre-bound to one label set (the prometheus-client
    `labels()` child pattern): `inc()` skips the per-call label-dict
    sort, for call sites hot enough that microseconds add up (the
    store-lock profiler)."""

    __slots__ = ("_parent", "_key")

    def __init__(self, parent: "Counter", key: tuple):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        parent = self._parent
        with parent._lock:
            parent._values[self._key] = \
                parent._values.get(self._key, 0.0) + amount


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, labels: Optional[dict] = None) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def bind(self, labels: Optional[dict] = None) -> BoundCounter:
        return BoundCounter(self, _labels_key(labels))

    def value(self, labels: Optional[dict] = None) -> float:
        return self._values.get(_labels_key(labels), 0.0)


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[dict] = None) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = value

    def bind(self, labels: Optional[dict] = None) -> "BoundGauge":
        return BoundGauge(self, _labels_key(labels))

    def remove(self, labels: Optional[dict] = None) -> None:
        """Drop one label set entirely (a per-user/per-entity gauge
        whose subject went away must stop being exported, not freeze at
        its last value)."""
        with self._lock:
            self._values.pop(_labels_key(labels), None)

    def value(self, labels: Optional[dict] = None) -> float:
        return self._values.get(_labels_key(labels), 0.0)


class BoundGauge:
    """See BoundCounter."""

    __slots__ = ("_parent", "_key")

    def __init__(self, parent: Gauge, key: tuple):
        self._parent = parent
        self._key = key

    def set(self, value: float) -> None:
        parent = self._parent
        with parent._lock:
            parent._values[self._key] = value


_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, math.inf)


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: Iterable[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        if not self.buckets or self.buckets[-1] != math.inf:
            # every observation must land in a bucket or _count undercounts
            self.buckets += (math.inf,)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[dict] = None) -> None:
        self._observe_key(_labels_key(labels), value)

    def _observe_key(self, key: tuple, value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            self._sums[key] = self._sums.get(key, 0.0) + value

    def bind(self, labels: Optional[dict] = None) -> "BoundHistogram":
        return BoundHistogram(self, _labels_key(labels))

    def count(self, labels: Optional[dict] = None) -> int:
        return sum(self._counts.get(_labels_key(labels), []))

    def sum(self, labels: Optional[dict] = None) -> float:
        return self._sums.get(_labels_key(labels), 0.0)

    @contextmanager
    def time(self, labels: Optional[dict] = None):
        """The `with-duration` analog."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, labels)


class BoundHistogram:
    """See BoundCounter."""

    __slots__ = ("_parent", "_key")

    def __init__(self, parent: Histogram, key: tuple):
        self._parent = parent
        self._key = key

    def observe(self, value: float) -> None:
        self._parent._observe_key(self._key, value)


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get(
            name,
            lambda: Histogram(name, help_, buckets or _DEFAULT_BUCKETS),
            Histogram)

    def _get(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            if not isinstance(m, cls):
                raise TypeError(f"metric {name} is {type(m)}, wanted {cls}")
            return m

    def render_prometheus(self) -> str:
        # snapshot the metric set under the registry lock, then each
        # metric's values under ITS lock: a writer mutating a dict (or a
        # histogram's counts/sums pair) mid-render would corrupt (or
        # tear) the exposition otherwise
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, metric in metrics:
            pname = prometheus_name(name)
            if metric.help:
                lines.append(f"# HELP {pname} {_escape_help(metric.help)}")
            if isinstance(metric, Counter):
                lines.append(f"# TYPE {pname} counter")
                with metric._lock:
                    values = sorted(metric._values.items())
                for key, v in values:
                    lines.append(f"{pname}{_fmt_labels(key)} {v}")
            elif isinstance(metric, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                with metric._lock:
                    values = sorted(metric._values.items())
                for key, v in values:
                    lines.append(f"{pname}{_fmt_labels(key)} {v}")
            elif isinstance(metric, Histogram):
                lines.append(f"# TYPE {pname} histogram")
                with metric._lock:
                    all_counts = sorted(
                        (key, list(counts), metric._sums.get(key, 0.0))
                        for key, counts in metric._counts.items())
                for key, counts, total in all_counts:
                    cum = 0
                    for b, c in zip(metric.buckets, counts):
                        cum += c
                        le = "+Inf" if b == math.inf else repr(b)
                        lines.append(
                            f"{pname}_bucket{_fmt_labels(key + (('le', le),))} {cum}"
                        )
                    lines.append(f"{pname}_count{_fmt_labels(key)} {cum}")
                    lines.append(f"{pname}_sum{_fmt_labels(key)} {total}")
        return "\n".join(lines) + "\n"


def _escape_label_value(value) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote, and newline would otherwise corrupt the output line."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


global_registry = Registry()
