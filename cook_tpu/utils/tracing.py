"""Lightweight tracing spans around cycle phases.

Reference: opentracing spans around every match-cycle phase
(/root/reference/scheduler/src/cook/scheduler/scheduler.clj:626-671 uses
`tracing/with-span`).  Spans record wall durations into the metrics
registry (histogram per span name) and an optional in-memory trace ring for
debugging; `jax.profiler` can be layered on for device-side traces.

Correlation: a thread-local correlation id (the transaction id from the
commit pipeline, i.e. the client's `X-Cook-Txn-Id`) tags every span opened
while it is set, so the span ring links a mutation's spans — REST commit,
txn apply, store ops — back to the transaction.  The correlation tag is
ring-only: it is excluded from metric labels (an unbounded-cardinality
label would explode the registry).
"""
from __future__ import annotations

import collections
import threading
import time
from contextlib import contextmanager
from typing import Optional

from cook_tpu.utils.metrics import global_registry

_trace_ring: collections.deque = collections.deque(maxlen=4096)
_lock = threading.Lock()
_active: dict[int, list[str]] = {}
_correlation = threading.local()

# tags that carry per-request identity: kept in the trace ring, stripped
# from metric labels (label cardinality must stay bounded).  "process" is
# the cross-process track tag (obs/distributed.py): it identifies which
# fleet member recorded the span, which the merged-trace export needs but
# a metric label does not (the registry is already per-process).
_RING_ONLY_TAGS = ("txn_id", "error", "process")


def set_correlation(txn_id: Optional[str]) -> Optional[str]:
    """Set the current thread's correlation id; returns the previous one
    so nested scopes can restore it."""
    prev = getattr(_correlation, "txn_id", None)
    _correlation.txn_id = txn_id
    return prev


def current_correlation() -> Optional[str]:
    return getattr(_correlation, "txn_id", None)


@contextmanager
def correlate(txn_id: Optional[str]):
    """Scope a correlation id: every span opened inside carries it."""
    prev = set_correlation(txn_id)
    try:
        yield
    finally:
        set_correlation(prev)


@contextmanager
def span(name: str, parent: Optional[str] = None, **tags):
    """with span("match_cycle", pool="default"): ...

    `parent` overrides the thread-local stack-derived parent — the
    cross-process case, where the causal parent arrived in an
    `X-Cook-Parent-Span` header rather than on this thread's stack."""
    tid = threading.get_ident()
    with _lock:
        stack = _active.setdefault(tid, [])
        if parent is None:
            parent = stack[-1] if stack else None
        stack.append(name)
    corr = current_correlation()
    if corr is not None and "txn_id" not in tags:
        tags["txn_id"] = corr
    error = False
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        error = True
        raise
    finally:
        duration = time.perf_counter() - t0
        if error:
            tags["error"] = True
        thread_name = threading.current_thread().name
        with _lock:
            stack = _active.get(tid)
            if stack:
                stack.pop()
                if not stack:
                    # drop the empty entry: a pool of short-lived threads
                    # would otherwise leak one dict slot per thread forever
                    del _active[tid]
            _trace_ring.append({
                "name": name,
                "parent": parent,
                "duration_s": duration,
                "tags": tags,
                "t": time.time(),
                # thread identity for the chrome-trace export's tracks
                "tid": tid,
                "thread": thread_name,
            })
        metric_tags = {k: v for k, v in tags.items()
                       if k not in _RING_ONLY_TAGS}
        global_registry.histogram(
            f"span.{name}", "wall seconds of the traced section").observe(
            duration, labels=metric_tags or None
        )


def record_span(name: str, duration_s: float, *,
                parent: Optional[str] = None,
                t: Optional[float] = None, **tags) -> None:
    """Append an already-completed span to the ring and observe its
    histogram, WITHOUT touching the per-thread `_active` stack.

    This is the async-safe recorder: the front end's aiohttp handlers
    interleave many requests on one event-loop thread, so the LIFO
    stack discipline `span()` relies on would mis-pair parents there.
    Callers measure the wall themselves and record the finished span
    with an explicit parent."""
    corr = current_correlation()
    if corr is not None and "txn_id" not in tags:
        tags["txn_id"] = corr
    with _lock:
        _trace_ring.append({
            "name": name,
            "parent": parent,
            "duration_s": duration_s,
            "tags": tags,
            "t": t if t is not None else time.time(),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
        })
    metric_tags = {k: v for k, v in tags.items()
                   if k not in _RING_ONLY_TAGS}
    global_registry.histogram(
        f"span.{name}", "wall seconds of the traced section").observe(
        duration_s, labels=metric_tags or None
    )


def spans_for_txn(txn_id: str, limit: Optional[int] = None) -> list[dict]:
    """Slice the ring by correlation id (the `txn_id` tag) — the
    per-process half of the federated `GET /debug/trace?txn_id=`."""
    with _lock:
        entries = [e for e in _trace_ring
                   if (e.get("tags") or {}).get("txn_id") == txn_id]
    return entries[-limit:] if limit else entries


def record_event(name: str, **tags) -> None:
    """Append a zero-duration marker to the trace ring WITHOUT touching
    the metrics registry — for correlation points (e.g. a replication
    ack) where a duration histogram would be meaningless noise."""
    corr = current_correlation()
    if corr is not None and "txn_id" not in tags:
        tags["txn_id"] = corr
    with _lock:
        _trace_ring.append({
            "name": name,
            "parent": None,
            "duration_s": 0.0,
            "tags": tags,
            "t": time.time(),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
        })


def ring_capacity() -> int:
    return _trace_ring.maxlen


def recent_spans(limit: int = 100) -> list[dict]:
    with _lock:
        return list(_trace_ring)[-limit:]


def active_thread_count() -> int:
    """Threads currently holding an open span (observability for the
    leak regression test)."""
    with _lock:
        return len(_active)


# ------------------------------------------------------- chrome-trace export
# Perfetto/chrome://tracing-compatible rendering of the span ring
# (GET /debug/trace?format=chrome, sim run --trace-out, incident bundles).
# Tracks: every span lands on its host thread's track (pid 1, one tid per
# thread); spans tagged with a pool additionally land on that pool's
# track (pid 2) so per-pool cycle phases read as one lane regardless of
# which scheduler/launcher thread executed them.  txn_id and every other
# ring tag ride in `args`, so a mutation's spans stay correlatable after
# export.

_THREAD_PID = 1
_POOL_PID = 2


def chrome_trace(spans: Optional[list] = None,
                 limit: Optional[int] = None) -> dict:
    """Render ring entries (newest `limit`, default the whole ring) as a
    Chrome Trace Event Format object: {"traceEvents": [...]}.  Complete
    spans become "X" (duration) events, zero-duration markers
    (record_event) become "i" (instant) events."""
    if spans is None:
        spans = recent_spans(limit or ring_capacity())
    events: list[dict] = []
    track_tids: dict[tuple, int] = {}

    def track(pid: int, name: str) -> int:
        key = (pid, name)
        tid = track_tids.get(key)
        if tid is None:
            tid = len(track_tids) + 1
            track_tids[key] = tid
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        return tid

    for pid, pname in ((_THREAD_PID, "host threads"), (_POOL_PID, "pools")):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": pname}})
    for entry in spans:
        tags = entry.get("tags") or {}
        args = dict(tags)
        if entry.get("parent"):
            args["parent"] = entry["parent"]
        duration_us = entry.get("duration_s", 0.0) * 1e6
        start_us = entry.get("t", 0.0) * 1e6 - duration_us
        base = {"name": entry.get("name", "?"), "cat": "span",
                "ts": start_us, "args": args}
        if duration_us > 0:
            base.update({"ph": "X", "dur": duration_us})
        else:
            base.update({"ph": "i", "s": "t"})
        thread = entry.get("thread") or f"thread-{entry.get('tid', 0)}"
        events.append({**base, "pid": _THREAD_PID,
                       "tid": track(_THREAD_PID, thread)})
        pool = tags.get("pool")
        if pool:
            events.append({**base, "pid": _POOL_PID,
                           "tid": track(_POOL_PID, f"pool:{pool}")})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


@contextmanager
def device_trace(log_dir: str):
    """Capture a device-side profile of the wrapped section with
    jax.profiler (view with TensorBoard/XProf).  Layered over `span` for
    end-to-end cycle investigations on real hardware."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
