"""Lightweight tracing spans around cycle phases.

Reference: opentracing spans around every match-cycle phase
(/root/reference/scheduler/src/cook/scheduler/scheduler.clj:626-671 uses
`tracing/with-span`).  Spans record wall durations into the metrics
registry (histogram per span name) and an optional in-memory trace ring for
debugging; `jax.profiler` can be layered on for device-side traces.
"""
from __future__ import annotations

import collections
import threading
import time
from contextlib import contextmanager
from cook_tpu.utils.metrics import global_registry

_trace_ring: collections.deque = collections.deque(maxlen=4096)
_lock = threading.Lock()
_active: dict[int, list[str]] = {}


@contextmanager
def span(name: str, **tags):
    """with span("match-cycle", pool="default"): ..."""
    tid = threading.get_ident()
    with _lock:
        stack = _active.setdefault(tid, [])
        parent = stack[-1] if stack else None
        stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        duration = time.perf_counter() - t0
        with _lock:
            _active[tid].pop()
            _trace_ring.append({
                "name": name,
                "parent": parent,
                "duration_s": duration,
                "tags": tags,
                "t": time.time(),
            })
        global_registry.histogram(f"span.{name}").observe(
            duration, labels=tags or None
        )


def recent_spans(limit: int = 100) -> list[dict]:
    with _lock:
        return list(_trace_ring)[-limit:]


@contextmanager
def device_trace(log_dir: str):
    """Capture a device-side profile of the wrapped section with
    jax.profiler (view with TensorBoard/XProf).  Layered over `span` for
    end-to-end cycle investigations on real hardware."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
