"""Safe listener fan-out: the one notify-all idiom shared by every
subscriber surface (encode-cache invalidations, quality-sample
listeners).  A sick listener is logged and skipped — observers must
never block or fail the producer's hot path."""
from __future__ import annotations

import logging

log = logging.getLogger(__name__)


def notify_all(listeners, context: str, *args, **kwargs) -> None:
    """Call every listener with (*args, **kwargs); exceptions are logged
    (tagged with `context`) and never propagate.  Iterates a snapshot so
    a listener registering mid-delivery neither breaks iteration nor
    receives this event."""
    for listener in list(listeners):
        try:
            listener(*args, **kwargs)
        except Exception:  # noqa: BLE001 — a sick listener must never
            # take down the producer (the observer rebuilds from its own
            # staleness checks; losing one notification is recoverable)
            log.exception("listener failed (%s)", context)
