"""Cross-cutting utilities: metrics, structured logging, tracing, config."""
from cook_tpu.utils.metrics import Registry, global_registry  # noqa: F401
