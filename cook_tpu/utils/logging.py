"""Structured JSON logging + passport audit events.

Reference: cook.log-structured (/root/reference/scheduler/src/cook/
log_structured.clj — JSON log lines with standard keys) and cook.passport
(passport.clj — an audit event stream on a dedicated logger: job-created,
job-launched, pod-completed, ...).
"""
from __future__ import annotations

import json
import logging
from typing import Any, Optional

structured_logger = logging.getLogger("cook_tpu.structured")
passport_logger = logging.getLogger("cook_tpu.passport")


def log_structured(
    level: int,
    message: str,
    *,
    pool: Optional[str] = None,
    user: Optional[str] = None,
    job: Optional[str] = None,
    instance: Optional[str] = None,
    compute_cluster: Optional[str] = None,
    component: Optional[str] = None,
    **extra: Any,
) -> None:
    record = {"message": message}
    for key, value in [
        ("pool", pool), ("user", user), ("job", job), ("instance", instance),
        ("compute-cluster", compute_cluster), ("component", component),
    ]:
        if value is not None:
            record[key] = value
    record.update(extra)
    structured_logger.log(level, json.dumps(record, default=str))


def log_info(message: str, **kw) -> None:
    log_structured(logging.INFO, message, **kw)


def log_error(message: str, **kw) -> None:
    log_structured(logging.ERROR, message, **kw)


# Passport event types (the reference enumerates these as keywords)
JOB_CREATED = "job-created"
JOB_SUBMITTED = "job-submitted"
JOB_LAUNCHED = "job-launched"
JOB_COMPLETED = "job-completed"
INSTANCE_COMPLETED = "instance-completed"
INSTANCE_PREEMPTED = "instance-preempted"
CLUSTER_STATE_CHANGED = "cluster-state-changed"


def passport(event_type: str, **data: Any) -> None:
    """Emit one audit event (reference: passport.clj `log-event`)."""
    passport_logger.info(
        json.dumps({"event-type": event_type, **data}, default=str)
    )


_STORE_EVENT_TO_PASSPORT = {
    "job/created": JOB_CREATED,
    "instance/created": JOB_LAUNCHED,
    "instance/status": INSTANCE_COMPLETED,   # terminal statuses only
    "job/state": JOB_COMPLETED,              # completed transitions only
}


def attach_passport(store) -> None:
    """Bridge the store's transaction feed onto the passport audit stream
    (the reference sprinkles passport calls through the code; the event
    log lets us derive the same audit trail in one place)."""

    def on_event(event) -> None:
        kind = event.kind
        mapped = _STORE_EVENT_TO_PASSPORT.get(kind)
        if mapped is None:
            return
        if kind == "instance/status" and event.data.get("status") not in (
            "success", "failed"
        ):
            return
        if kind == "job/state" and event.data.get("state") != "completed":
            return
        passport(mapped, seq=event.seq, **event.data)

    store.add_watcher(on_event)
