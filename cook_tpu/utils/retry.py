"""Shared retry policy: jittered exponential backoff with a deadline.

One policy object, one call wrapper, one metric family — adopted by the
cluster RPC paths (cluster/k8s_http.py idempotent GETs), the replication
follower's reconnect loop (control/replication.py), and the async launch
fan-out.  Ad-hoc `time.sleep(constant)` retry loops hide two failure
modes this module makes explicit: synchronized retry storms (no jitter)
and retries outliving the caller's latency budget (no deadline).

Import discipline: only stdlib + utils.metrics — the replication and
journal layers import this at module level and must stay jax-free.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff knobs.

    Delay before retry `n` (1-based failure count) is drawn uniformly
    from [d * (1 - jitter), d] where d = min(cap_s, base_s *
    multiplier**(n-1)) — full-jitter-style so a fleet of callers hitting
    the same dead dependency does not resynchronize into retry storms.
    `deadline_s` bounds the WHOLE call (attempts + sleeps); 0 disables.
    """

    max_attempts: int = 3
    base_s: float = 0.1
    multiplier: float = 2.0
    cap_s: float = 5.0
    jitter: float = 0.5
    deadline_s: float = 0.0


def backoff_s(policy: RetryPolicy, failures: int,
              rng: Optional[random.Random] = None) -> float:
    """Sleep before the retry following the `failures`-th consecutive
    failure (1-based)."""
    exp = min(policy.cap_s,
              policy.base_s * policy.multiplier ** max(failures - 1, 0))
    if policy.jitter <= 0:
        return exp
    r = rng.random() if rng is not None else random.random()
    return exp * (1.0 - policy.jitter * r)


class RetryBudgetExceeded(Exception):
    """The policy's deadline lapsed before the next retry could run; the
    last failure is the __cause__."""


_attempts = global_registry.counter(
    "retry.attempts",
    "calls made under a retry policy (first tries AND retries) per op")
_retries = global_registry.counter(
    "retry.retries", "retries performed per op")
_exhausted = global_registry.counter(
    "retry.exhausted",
    "retry budgets exhausted (attempts or deadline) per op")


def call_with_retry(
    fn: Callable,
    policy: RetryPolicy,
    *,
    op: str = "call",
    retry_on: Callable[[BaseException], bool] = (
        lambda e: isinstance(e, OSError)),
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Run `fn` under the policy: retry failures `retry_on` accepts, with
    jittered exponential backoff, never past `max_attempts` or the
    deadline.  Non-retryable failures propagate immediately; exhausted
    retries re-raise the LAST failure (callers keep their existing
    except clauses).  `op` labels the retry metrics so /metrics shows
    which dependency is burning retry budget."""
    labels = {"op": op}
    t0 = clock()
    failures = 0
    while True:
        _attempts.inc(1, labels)
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not retry_on(e):
                raise
            failures += 1
            if failures >= policy.max_attempts:
                _exhausted.inc(1, labels)
                raise
            delay = backoff_s(policy, failures, rng)
            if policy.deadline_s and \
                    clock() - t0 + delay > policy.deadline_s:
                _exhausted.inc(1, labels)
                raise
            _retries.inc(1, labels)
            sleep(delay)
