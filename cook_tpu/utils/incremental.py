"""Incremental (percentage-rollout) config values.

Reference: cook.config-incremental (/root/reference/scheduler/src/cook/
config_incremental.clj): a runtime-mutable key maps to a list of
{value, portion} entries; an entity (job/user uuid) hashes to [0,1) and
picks the value whose cumulative portion covers it
(`select-config-from-values`, config_incremental.clj:89).  Used to roll
out defaults (e.g. container images) to a fraction of jobs.
"""
from __future__ import annotations

import hashlib
from typing import Any, Optional, Sequence

from cook_tpu.models.store import JobStore

INCREMENTAL_PREFIX = "incremental:"


def entity_fraction(entity_id: str) -> float:
    """Stable hash of an entity id to [0, 1)."""
    digest = hashlib.sha256(entity_id.encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2**64


def select_from_values(values: Sequence[dict], entity_id: str) -> Optional[Any]:
    """values: [{"value": v, "portion": 0.2}, ...] — portions should sum to
    1.0; the tail value absorbs any remainder."""
    if not values:
        return None
    x = entity_fraction(entity_id)
    cumulative = 0.0
    for entry in values:
        cumulative += float(entry.get("portion", 0.0))
        if x < cumulative:
            return entry.get("value")
    return values[-1].get("value")


def write_incremental(store: JobStore, key: str,
                      values: Sequence[dict]) -> None:
    store.update_dynamic_config({INCREMENTAL_PREFIX + key: list(values)})


def read_incremental(store: JobStore, key: str) -> list[dict]:
    return store.dynamic_config.get(INCREMENTAL_PREFIX + key, [])


def resolve_incremental(store: JobStore, key: str, entity_id: str,
                        default: Any = None) -> Any:
    value = select_from_values(read_incremental(store, key), entity_id)
    return default if value is None else value
