"""cook-tpu: a TPU-native multitenant batch-scheduling framework.

A from-scratch rebuild of the capabilities of twosigma/Cook (reference layout
documented in SURVEY.md): DRU fair-share ranking, jobs x nodes bin-packing
with constraints and groups, preemptive rebalancing, pools, quotas/shares,
rate limits, a pluggable compute-cluster boundary, a REST API with clients,
and a deterministic faster-than-real-time trace simulator.

The defining difference from the reference: the per-cycle matchmaking core
(DRU scoring, bin-packing, preemption-victim search) is implemented as batched
dense-tensor solves in JAX (see `cook_tpu.ops`), sharded over the TPU ICI mesh
(see `cook_tpu.parallel`).
"""

__version__ = "0.1.0"


def __getattr__(name):
    """Lazy convenience exports (kept lazy so `import cook_tpu` stays
    cheap and JAX-free for clients that only need the REST client)."""
    if name in ("JobStore", "Job", "Instance", "Pool", "Resources"):
        from cook_tpu import models

        return getattr(models, name)
    if name == "Scheduler":
        from cook_tpu.scheduler import Scheduler

        return Scheduler
    if name == "JobClient":
        from cook_tpu.client import JobClient

        return JobClient
    if name == "Simulator":
        from cook_tpu.sim import Simulator

        return Simulator
    raise AttributeError(f"module 'cook_tpu' has no attribute {name!r}")
