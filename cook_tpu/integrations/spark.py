"""Spark-on-cook: the coarse-grained scheduler backend, cook-side.

Reference: spark/ — patches teaching Spark 1.5/1.6 a `cook://user@host:port`
master URL whose backend submits each Spark executor as a Cook job and
(in the 1.6.1 patch) supports dynamic allocation.  Spark dropped those
patch points long ago; the durable shape of the integration is the one
implemented here: a driver-side backend object that

  * parses the `cook://` master URL,
  * runs each executor as a cook job carrying a distinct executor id and
    the driver's coordination URL (Spark's CoarseGrainedExecutorBackend
    contract),
  * sizes the fleet from `spark.cores.max` / `spark.executor.cores`,
  * implements Spark's ExecutorAllocationClient verbs
    (`request_total_executors`, `kill_executors`) for dynamic allocation,
  * retries lost executors through cook's own retry machinery
    (max_retries + mea-culpa preemption retries, like the patch relied
    on).
"""
from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Optional
from urllib.parse import urlsplit

from cook_tpu.client.jobclient import JobClient


@dataclass(frozen=True)
class CookMaster:
    """Parsed `cook://user@host:port` master URL (spark/README.md)."""

    user: str
    host: str
    port: int

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def parse_master_url(master: str) -> CookMaster:
    if not master.startswith("cook://"):
        raise ValueError(f"not a cook master URL: {master!r}")
    parts = urlsplit(master)
    if not parts.hostname or not parts.port:
        raise ValueError(f"cook master URL needs host:port: {master!r}")
    return CookMaster(user=parts.username or "spark",
                      host=parts.hostname, port=parts.port)


@dataclass
class SparkExecutorSpec:
    """What one Spark executor job looks like.

    `command_template` receives {driver_url}, {executor_id}, {cores},
    {mem} — the arguments CoarseGrainedExecutorBackend needs."""

    command_template: str = (
        "spark-class org.apache.spark.executor.CoarseGrainedExecutorBackend"
        " --driver-url {driver_url} --executor-id {executor_id}"
        " --cores {cores} --app-id cook-spark"
    )
    executor_cores: float = 1.0    # spark.executor.cores
    executor_mem: float = 4096.0   # spark.executor.memory (MB)
    max_cores: float = 0.0         # spark.cores.max; 0 = no initial fleet
    pool: Optional[str] = None
    max_retries: int = 10          # executors ride cook's retry machinery
    env: dict = field(default_factory=dict)


class SparkCookBackend:
    """Driver-side executor fleet manager (the patched
    CoarseGrainedSchedulerBackend subclass, cook-side half)."""

    def __init__(self, master: str, driver_url: str,
                 spec: Optional[SparkExecutorSpec] = None,
                 client: Optional[JobClient] = None):
        self.master = parse_master_url(master)
        self.driver_url = driver_url
        self.spec = spec or SparkExecutorSpec()
        self.client = client or JobClient(self.master.url,
                                          user=self.master.user)
        self.app_group = str(uuid_mod.uuid4())
        # executor id -> job uuid (live fleet)
        self.executors: dict[str, str] = {}
        self._next_executor_id = 0
        self._started = False

    # ------------------------------------------------------------- fleet

    @property
    def target_executors(self) -> int:
        if self.spec.max_cores <= 0:
            return 0
        return max(int(self.spec.max_cores // self.spec.executor_cores), 1)

    def start(self) -> list[str]:
        """Submit the initial fleet per spark.cores.max (the patch refuses
        to launch executors without it, spark/README.md)."""
        self._started = True
        return self.request_total_executors(self.target_executors)

    def _executor_job(self, executor_id: str) -> dict:
        spec = self.spec
        return {
            "name": f"spark-executor-{executor_id}",
            "command": spec.command_template.format(
                driver_url=self.driver_url,
                executor_id=executor_id,
                cores=int(spec.executor_cores),
                mem=int(spec.executor_mem),
            ),
            "mem": spec.executor_mem,
            "cpus": spec.executor_cores,
            "max_retries": spec.max_retries,
            "group": self.app_group,
            "env": {
                "SPARK_EXECUTOR_ID": executor_id,
                "SPARK_DRIVER_URL": self.driver_url,
                **spec.env,
            },
            "labels": {"spark-app-group": self.app_group},
            **({"pool": spec.pool} if spec.pool else {}),
        }

    # Spark ExecutorAllocationClient verbs (dynamic allocation)

    def request_total_executors(self, n: int) -> list[str]:
        """Grow/shrink the fleet to n executors; returns live job uuids."""
        if len(self.executors) < n:
            # one batched submit for the whole growth step: fleet startup
            # is O(1) round-trips and never half-submitted on failure
            new_ids = []
            while len(self.executors) + len(new_ids) < n:
                new_ids.append(str(self._next_executor_id))
                self._next_executor_id += 1
            groups = ([{"uuid": self.app_group, "name": "spark-app"}]
                      if not self.executors else ())
            uuids = self.client.submit(
                [self._executor_job(eid) for eid in new_ids], groups=groups)
            self.executors.update(zip(new_ids, uuids))
        if len(self.executors) > n:
            surplus = sorted(self.executors, key=int, reverse=True)
            victims = surplus[: len(self.executors) - n]
            self.kill_executors(victims)
        return list(self.executors.values())

    def kill_executors(self, executor_ids: list[str]) -> None:
        uuids = [self.executors.pop(e) for e in executor_ids
                 if e in self.executors]
        if uuids:
            self.client.kill(uuids)

    def executor_status(self) -> dict[str, str]:
        """executor id -> job status (the backend's heartbeat view)."""
        if not self.executors:
            return {}
        by_uuid = {uuid: eid for eid, uuid in self.executors.items()}
        return {
            by_uuid[job["uuid"]]: job["status"]
            for job in self.client.query(list(self.executors.values()))
        }

    def stop(self) -> None:
        if self.executors:
            self.client.kill(list(self.executors.values()))
            self.executors = {}

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
