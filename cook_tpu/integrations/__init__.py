"""Framework integrations: run Dask/Spark-style worker fleets as jobs."""
from cook_tpu.integrations.workerpool import (  # noqa: F401
    DaskCookCluster,
    WorkerPool,
    WorkerSpec,
)
