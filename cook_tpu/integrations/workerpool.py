"""Elastic worker-pool adapter: run framework workers (Dask, Spark, ...)
as cook-tpu jobs.

Reference intent: spark/ (patches adding Cook as a Spark scheduler
backend) and dask/docs/design.md (a `CookCluster` Dask deployment class).
This module is the transport both need: submit N identical worker jobs
pointed at a coordinator address, scale the count up/down, tear down.

`DaskCookCluster` implements the Dask `Cluster` duck-type (scale /
close / scheduler_address) when `distributed` is importable; the plain
`WorkerPool` works with no extra dependencies.
"""
from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Optional, Sequence

from cook_tpu.client.jobclient import JobClient


@dataclass
class WorkerSpec:
    command_template: str      # e.g. "dask-worker {address} --nthreads {cpus}"
    mem: float = 4096.0
    cpus: float = 2.0
    gpus: float = 0.0
    pool: Optional[str] = None
    max_retries: int = 5       # workers restart on failure/preemption
    env: dict = field(default_factory=dict)


class WorkerPool:
    """N identical long-running worker jobs, grouped for lifecycle ops."""

    def __init__(self, client: JobClient, spec: WorkerSpec,
                 coordinator_address: str, *, name: str = "workerpool"):
        self.client = client
        self.spec = spec
        self.coordinator_address = coordinator_address
        self.name = name
        self.group_uuid = str(uuid_mod.uuid4())
        self.worker_uuids: list[str] = []

    def _worker_job(self) -> dict:
        spec = self.spec
        return {
            "command": spec.command_template.format(
                address=self.coordinator_address,
                cpus=spec.cpus,
                mem=spec.mem,
            ),
            "name": f"{self.name}-worker",
            "mem": spec.mem,
            "cpus": spec.cpus,
            "gpus": spec.gpus,
            "max_retries": spec.max_retries,
            "env": spec.env,
            "group": self.group_uuid,
            **({"pool": spec.pool} if spec.pool else {}),
        }

    def scale(self, n: int) -> list[str]:
        """Grow or shrink to n workers; returns the current worker uuids."""
        current = len(self.worker_uuids)
        if n > current:
            new = self.client.submit(
                [self._worker_job() for _ in range(n - current)],
                groups=[{"uuid": self.group_uuid, "name": self.name}]
                if current == 0 else (),
            )
            self.worker_uuids.extend(new)
        elif n < current:
            victims = self.worker_uuids[n:]
            self.worker_uuids = self.worker_uuids[:n]
            self.client.kill(victims)
        return list(self.worker_uuids)

    def status(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        if self.worker_uuids:
            for job in self.client.query(self.worker_uuids):
                counts[job["status"]] = counts.get(job["status"], 0) + 1
        return counts

    def close(self) -> None:
        if self.worker_uuids:
            self.client.kill(self.worker_uuids)
            self.worker_uuids = []


class DaskCookCluster:
    """Dask `Cluster`-shaped deployment over a cook-tpu scheduler
    (the class dask/docs/design.md sketches).

    Usage (requires `distributed` at runtime):

        cluster = DaskCookCluster(JobClient(url, user=me),
                                  scheduler_address="tcp://...:8786")
        cluster.scale(16)
        client = distributed.Client(cluster.scheduler_address)
    """

    def __init__(self, client: JobClient, scheduler_address: str,
                 spec: Optional[WorkerSpec] = None):
        self.scheduler_address = scheduler_address
        self.pool = WorkerPool(
            client,
            spec or WorkerSpec(
                command_template=(
                    "dask-worker {address} --nthreads {cpus} "
                    "--memory-limit {mem}MB"
                )
            ),
            scheduler_address,
            name="dask",
        )

    def scale(self, n: int) -> None:
        self.pool.scale(n)

    @property
    def workers(self) -> list[str]:
        return list(self.pool.worker_uuids)

    def close(self) -> None:
        self.pool.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
