"""The commit pipeline: one place every mutation becomes durable.

Pipeline (the reference's `transact-with-retries`, datomic.clj:79):

  1. idempotency — a txn_id already in the store's transaction table is
     answered from the recorded outcome, nothing re-applied;
  2. in-memory apply — the op handler runs under the store lock and the
     store emits the entity events, followed by a `txn/committed`
     record event carrying (txn_id, op, result).  Attached journal
     writers receive every event synchronously via the watcher fan-out,
     so by the time the lock drops the commit is written (not yet
     necessarily fsynced);
  3. journal durability — `JournalWriter.sync()` group-fsyncs: one
     fsync covers every event flushed so far, so concurrent commits
     share the disk barrier instead of paying one each;
  4. replication — callers that enforce a sync-ack bound await follower
     acks covering the commit's seq (rest/api.py `_await_replication`);
     the outcome records whether the bound was met.

Bounded retries (`DurabilityPolicy.max_attempts`) apply to handlers
raising `TransientTxnError`; `TransactionVetoed` is a definitive veto
and never retried.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Optional

from cook_tpu.models.store import JobStore
from cook_tpu.txn.ops import OPS, UnknownOperation
from cook_tpu.txn.transaction import Transaction, TxnOutcome, new_txn_id
from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

# commits span lock-acquire + apply + group fsync: µs (in-memory dupe
# answer) to seconds (fsync stall on a loaded disk)
_COMMIT_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, float("inf"))


class TransientTxnError(Exception):
    """An op failure worth retrying (the reference retries Datomic
    transactor hiccups a bounded number of times, datomic.clj:79)."""


@dataclass
class DurabilityPolicy:
    """The single knob-set for how hard a commit is."""

    # fsync the journal before the commit is reported (group commit:
    # one fsync covers all concurrently-flushed events)
    sync_journal: bool = True
    # bounded retries for TransientTxnError
    max_attempts: int = 3
    retry_backoff_s: float = 0.01


class TransactionLog:
    """Commit seam in front of a JobStore (+ optional journal writer)."""

    def __init__(self, store: JobStore, *,
                 journal: Any = None,
                 policy: Optional[DurabilityPolicy] = None):
        self.store = store
        self.journal = journal
        self.policy = policy or DurabilityPolicy()

    def commit(self, op: str, payload: Optional[dict] = None, *,
               txn_id: Optional[str] = None) -> TxnOutcome:
        txn = Transaction(op=op, payload=payload or {},
                          txn_id=txn_id or new_txn_id())
        return self.commit_txn(txn)

    def commit_txn(self, txn: Transaction) -> TxnOutcome:
        import time as _time

        handler = OPS.get(txn.op)
        if handler is None:
            raise UnknownOperation(txn.op)
        store = self.store
        attempts = 0
        t_commit = _time.perf_counter()
        while True:
            attempts += 1
            try:
                t_apply = _time.perf_counter()
                with store._lock:
                    cached = store.txn_results.get(txn.txn_id)
                    if cached is not None:
                        return TxnOutcome(
                            txn_id=txn.txn_id, op=cached.get("op", txn.op),
                            seq=cached.get("seq", 0),
                            result=cached.get("result"),
                            duplicate=True, attempts=attempts)
                    # correlation scope: every span opened while the op
                    # applies (including nested store spans) carries the
                    # transaction id, linking the span ring back to the
                    # client's X-Cook-Txn-Id
                    with tracing.correlate(txn.txn_id), \
                            tracing.span("txn.apply", op=txn.op):
                        result = handler(store, txn.payload)
                        seq = store.note_txn(txn.txn_id, txn.op, result)
                break
            except TransientTxnError:
                if attempts >= self.policy.max_attempts:
                    raise
                log.warning("transient failure committing %s (%s), "
                            "attempt %d/%d", txn.op, txn.txn_id, attempts,
                            self.policy.max_attempts)
                time.sleep(self.policy.retry_backoff_s)
        t_sync = _time.perf_counter()
        if self.journal is not None and self.policy.sync_journal:
            self.journal.sync()
        # phase walls feed the mp per-hop attribution: lock+apply vs
        # the group fsync (obs/distributed.py HOPS)
        phase_walls = {"apply": t_sync - t_apply,
                       "fsync": _time.perf_counter() - t_sync}
        # commit wall per op (apply under the store lock + group fsync;
        # idempotent replays answered from the txn table are excluded —
        # they pay neither), the txn-side half of the commit-ack latency
        # /debug/contention attributes
        global_registry.histogram(
            "txn.commit_seconds",
            "transaction commit wall seconds per op (apply + fsync)",
            buckets=_COMMIT_BUCKETS).observe(
            _time.perf_counter() - t_commit, {"op": txn.op})
        return TxnOutcome(txn_id=txn.txn_id, op=txn.op, seq=seq,
                          result=result, attempts=attempts,
                          phase_walls=phase_walls)
