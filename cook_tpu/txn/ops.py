"""Operation registry: op name -> handler(store, payload) -> result.

Every mutation type the REST surface (or an internal caller) can commit
is registered here, so the commit pipeline — idempotency, journal
durability, replication acks, bounded retries — is enforced in exactly
one place (`txn/log.py`) instead of per call site.  Handlers run under
the store lock (the store's RLock makes nested store calls safe), apply
via the store's transition methods (which emit the entity events), and
return a JSON-able result that is recorded with the transaction for
idempotent replays.
"""
from __future__ import annotations

from typing import Any, Callable

from cook_tpu.models.store import JobStore, TransactionVetoed

OPS: dict[str, Callable[[JobStore, dict], Any]] = {}


class UnknownOperation(KeyError):
    pass


def txn_op(name: str):
    def deco(fn: Callable[[JobStore, dict], Any]):
        OPS[name] = fn
        return fn
    return deco


@txn_op("jobs/submit")
def _submit(store: JobStore, payload: dict) -> Any:
    uuids = store.submit_jobs(payload["jobs"], payload.get("groups", ()))
    return {"jobs": uuids}


@txn_op("jobs/kill")
def _kill(store: JobStore, payload: dict) -> Any:
    return {"killed": store.kill_jobs(payload["uuids"])}


@txn_op("group/kill")
def _group_kill(store: JobStore, payload: dict) -> Any:
    # membership resolves at apply time so a replayed record kills the
    # same set the original commit saw (the group events replicated with
    # the original commit carry the membership)
    killed = []
    for guuid in payload["groups"]:
        group = store.groups.get(guuid)
        if group is None:
            raise TransactionVetoed(f"no such group {guuid}")
        killed += store.kill_jobs(group.job_uuids)
    return {"killed": killed}


@txn_op("job/retry")
def _retry(store: JobStore, payload: dict) -> Any:
    job = store.retry_job(payload["uuid"], int(payload["retries"]),
                          increment=bool(payload.get("increment", False)))
    return {"uuid": job.uuid, "retries": job.max_retries,
            "state": job.state.value}


@txn_op("job/pool-move")
def _pool_move(store: JobStore, payload: dict) -> Any:
    moved = store.move_job_pool(payload["uuid"], payload["pool"])
    return {"uuid": payload["uuid"], "pool": payload["pool"], "moved": moved}


@txn_op("share/set")
def _share_set(store: JobStore, payload: dict) -> Any:
    share = payload["share"]
    store.set_share(share)
    return {"user": share.user, "pool": share.pool}


@txn_op("share/retract")
def _share_retract(store: JobStore, payload: dict) -> Any:
    store.retract_share(payload["user"], payload["pool"])
    return {"user": payload["user"], "pool": payload["pool"]}


@txn_op("quota/set")
def _quota_set(store: JobStore, payload: dict) -> Any:
    quota = payload["quota"]
    store.set_quota(quota)
    return {"user": quota.user, "pool": quota.pool}


@txn_op("quota/retract")
def _quota_retract(store: JobStore, payload: dict) -> Any:
    store.retract_quota(payload["user"], payload["pool"])
    return {"user": payload["user"], "pool": payload["pool"]}


@txn_op("pool/capacity-delta")
def _capacity_delta(store: JobStore, payload: dict) -> Any:
    """Elastic capacity plan deltas (cook_tpu/elastic/): loan/reclaim
    moves apply to the capacity ledger durably BEFORE any cluster is
    resized, so a failover between commit and resize leaves the new
    leader a consistent ledger to reconcile capacity from.  Idempotent
    like pool-move: a retried commit (same txn id) is answered from the
    transaction table; reclaims clamp at outstanding amounts."""
    moves = payload["moves"]
    for move in moves:
        if move.get("kind", "loan") not in ("loan", "reclaim"):
            raise TransactionVetoed(f"bad capacity move kind {move!r}")
        for side in ("from", "to"):
            if move.get(side) not in store.pools:
                raise TransactionVetoed(
                    f"unknown pool {move.get(side)!r} in capacity move")
        if move["from"] == move["to"]:
            raise TransactionVetoed("capacity move from a pool to itself")
        if any(float(move.get(d, 0.0)) < 0.0
               for d in store.CAPACITY_DIMS):
            raise TransactionVetoed("negative capacity move amount")
    return store.apply_capacity_moves(moves)


@txn_op("instance/cancel")
def _instance_cancel(store: JobStore, payload: dict) -> Any:
    cancelled = [tid for tid in payload["task_ids"]
                 if store.mark_instance_cancelled(tid)]
    return {"cancelled": cancelled}


@txn_op("config/update")
def _config_update(store: JobStore, payload: dict) -> Any:
    store.update_dynamic_config(payload["updates"])
    return {"updated": sorted(payload["updates"])}
