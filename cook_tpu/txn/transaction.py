"""Transaction records: the unit every state mutation commits as.

The reference's analog is one Datomic transaction (datomic.clj:79): a
named operation plus its data, identified well enough that a retried
commit is detected and answered from the log instead of re-applied.
"""
from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional


def new_txn_id() -> str:
    return uuid.uuid4().hex


@dataclass(frozen=True)
class Transaction:
    """One mutation heading into the commit pipeline.

    `payload` is op-specific and may hold live entity objects (e.g. the
    parsed `Job`s of a submission) — it is consumed by the op handler,
    never serialized.  What reaches the journal/replication feed is the
    `txn/committed` event (txn_id, op, JSON-able result) plus the entity
    events the op itself emitted.
    """

    op: str
    payload: dict[str, Any] = field(default_factory=dict)
    txn_id: str = field(default_factory=new_txn_id)


@dataclass
class TxnOutcome:
    """What a commit produced.

    `duplicate` means the idempotency key matched an already-committed
    transaction: nothing was re-applied and `result`/`seq` come from the
    recorded outcome.  `replicated` is None until a caller awaits the
    replication stage (rest/api.py), then True/False per the configured
    durability bound.
    """

    txn_id: str
    op: str
    seq: int
    result: Any
    duplicate: bool = False
    attempts: int = 1
    replicated: Optional[bool] = None
    # sharded control plane (cook_tpu/shard/): shard id -> the commit's
    # sequence number ON THAT SHARD.  Sequence numbers are only
    # comparable within one shard's history, so sync-ack replication
    # awaits each entry separately.  None on unsharded commits.
    shard_seqs: Optional[dict[int, int]] = None
    # per-phase wall seconds ({"apply": ..., "fsync": ...}; rest/api.py
    # adds "replication_ack") — the server-side half of the mp front
    # end's per-hop attribution, returned in the X-Cook-Hop-Walls
    # response header (obs/distributed.py).  None on duplicate answers.
    phase_walls: Optional[dict[str, float]] = None
