"""Unified durable transaction log: every state mutation is a Transaction.

Reference: every Cook mutation — submit, kill, retry, share/quota, group
ops, pool moves — goes through Datomic's `transact-with-retries`
(/root/reference/scheduler/src/cook/datomic.clj:79) and is durable the
moment the REST call returns.  This package is that seam for the
rebuild: mutations are first-class `Transaction` records with
idempotency keys, committed through ONE pipeline

    in-memory apply (store lock) -> journal append (group fsync)
        -> sync-ack replication to live followers

with bounded retries and a single place to enforce durability policy
(`DurabilityPolicy`).  Followers replay the same records off the
journal feed, so leader and standby converge by construction and a
promoted standby answers idempotent re-submissions of already-acked
transactions without re-applying them.
"""
from cook_tpu.txn.log import DurabilityPolicy, TransactionLog, TransientTxnError
from cook_tpu.txn.ops import OPS, UnknownOperation, txn_op
from cook_tpu.txn.transaction import Transaction, TxnOutcome

__all__ = [
    "DurabilityPolicy",
    "OPS",
    "Transaction",
    "TransactionLog",
    "TransientTxnError",
    "TxnOutcome",
    "UnknownOperation",
    "txn_op",
]
