"""Control-plane overload reactions: load shedding + admission scaleback.

PR 6's contention observatory computes `commit-ack-slo-burn` and
`store-lock-saturation`; this module is what those verdicts now DO:

  * `LoadShedder` — when a shed reason is active, heavy read endpoints
    (job listings, /queue, /unscheduled_jobs, ...) answer 429 +
    Retry-After instead of queueing more work behind the saturated
    store lock (rest/api.py calls `should_shed()` at the top of each
    heavy GET handler; mutations are never shed — they are the work the
    SLO protects).  The health evaluation is TTL-cached so the per-
    request cost is a clock read, not a full contention sweep.

  * `AdmissionController` — the scheduler-side reaction (Cook's head-
    of-queue scaleback, scaled by overload instead of head failure):
    while overloaded, each pool's considerable window shrinks x0.95 per
    cycle down to a floor; when the burn clears, the cap resets to the
    configured maximum.  Applied as a CLAMP on PoolMatchState at cycle
    start, so it composes with (never fights) the matcher's own
    head-of-queue backoff.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from cook_tpu.obs.contention import (
    COMMIT_ACK_SLO_BURN,
    STORE_LOCK_SATURATION,
)
from cook_tpu.utils.metrics import global_registry

DEFAULT_SHED_REASONS = (COMMIT_ACK_SLO_BURN, STORE_LOCK_SATURATION)


class LoadShedder:
    """TTL-cached view over ContentionObservatory.evaluate() answering
    "should this heavy read be shed right now?"."""

    def __init__(self, contention, *,
                 reasons: tuple = DEFAULT_SHED_REASONS,
                 ttl_s: float = 1.0, retry_after_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.contention = contention
        self.reasons = tuple(reasons)
        self.ttl_s = ttl_s
        self.retry_after_s = retry_after_s
        self.clock = clock
        self._lock = threading.Lock()
        self._cached_at = -1e18
        self._active: tuple = ()
        self._active_gauge = global_registry.gauge(
            "shed.active", "1 while heavy reads are being shed")
        self._rejected = global_registry.counter(
            "shed.rejected", "requests answered 429 by load shedding "
            "per route")

    def active_reasons(self) -> tuple:
        """The shed-relevant degradation reasons active right now
        (evaluated at most every ttl_s)."""
        now = self.clock()
        with self._lock:
            if now - self._cached_at < self.ttl_s:
                return self._active
            # mark before evaluating so concurrent requests don't stack
            # sweeps behind the lock
            self._cached_at = now
        degradations, _checks = self.contention.evaluate()
        active = tuple(sorted(
            {d["reason"] for d in degradations} & set(self.reasons)))
        with self._lock:
            self._active = active
        self._active_gauge.set(1.0 if active else 0.0)
        return active

    def overloaded(self) -> bool:
        """The scheduler-facing signal (AdmissionController overload_fn)."""
        return bool(self.active_reasons())

    def should_shed(self, route: str = "") -> Optional[dict]:
        """None = serve; else a verdict dict for the 429 body."""
        active = self.active_reasons()
        if not active:
            return None
        self._rejected.inc(1, {"route": route or "unknown"})
        return {
            "reasons": list(active),
            "retry_after_s": self.retry_after_s,
            "detail": ("control plane overloaded ("
                       + ", ".join(active)
                       + "); heavy reads are shed until the burn clears"
                       " — see /debug/contention"),
        }


class AdmissionController:
    """Overload-driven considerable-window scaleback.

    `clamp(pool, state, max_considered)` runs at match-cycle start:
    overloaded -> this pool's cap shrinks by `scaleback` (floored at
    `floor_fraction * max`); clear -> the cap resets to max.  The cap
    CLAMPS `state.num_considerable`, which the matcher's own
    head-of-queue backoff still owns below the cap."""

    def __init__(self, *, overload_fn: Optional[Callable[[], bool]] = None,
                 scaleback: float = 0.95, floor_fraction: float = 0.1):
        self.overload_fn = overload_fn
        self.scaleback = scaleback
        self.floor_fraction = floor_fraction
        self._caps: dict[str, int] = {}
        self._cap_gauge = global_registry.gauge(
            "admission.considerable_cap",
            "overload-scaled considerable-window cap per pool")
        self._scalebacks = global_registry.counter(
            "admission.scalebacks",
            "overload scaleback steps applied per pool")

    def overloaded(self) -> bool:
        if self.overload_fn is None:
            return False
        try:
            return bool(self.overload_fn())
        except Exception:  # noqa: BLE001 — a broken signal must not
            # take the match cycle down with it
            return False

    def clamp(self, pool: str, state, max_considered: int) -> None:
        cap = self._caps.get(pool, max_considered)
        if self.overloaded():
            floor = max(1, int(max_considered * self.floor_fraction))
            shrunk = max(floor, int(cap * self.scaleback))
            if shrunk < cap:
                # count only actual shrink steps: a cap held at the
                # floor is not another scaleback
                self._scalebacks.inc(1, {"pool": pool})
            cap = min(shrunk, max_considered)
        else:
            cap = max_considered
        self._caps[pool] = cap
        self._cap_gauge.set(cap, {"pool": pool})
        if state.num_considerable > cap:
            state.num_considerable = cap

    def cap(self, pool: str) -> Optional[int]:
        return self._caps.get(pool)
