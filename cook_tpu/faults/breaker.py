"""Per-cluster circuit breakers: closed -> open -> half-open.

A cluster whose launch/kill RPCs are failing should stop receiving
work BEFORE every matched job burns a mea-culpa retry against it: the
breaker watches the recent launch/kill outcome window and, past the
error-rate threshold, opens — `ComputeCluster.accepts_work` goes False,
so the cluster's offers vanish from rank/match/elastic scans and jobs
skip with the flight-recorder reason `cluster-circuit-open` (a queue
decision, not a failed instance).  After `cooldown_s` the breaker goes
half-open: offers flow again and the next launch is the probe — success
closes the breaker, failure re-opens it for another cooldown.

Kills are NEVER gated by the breaker (safe_kill_task runs regardless —
a sick cluster must still honor kills); their outcomes only feed the
error window.
"""
from __future__ import annotations

import collections
import enum
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"


_STATE_VALUE = {BreakerState.CLOSED: 0.0, BreakerState.HALF_OPEN: 1.0,
                BreakerState.OPEN: 2.0}


@dataclass(frozen=True)
class BreakerParams:
    """Trip thresholds.  Outcomes are BATCH-level (one launch_tasks RPC,
    one kill RPC), so the window measures backend health, not workload
    size."""

    window: int = 16           # recent RPC outcomes considered
    min_samples: int = 6       # don't judge on fewer
    error_threshold: float = 0.5
    cooldown_s: float = 15.0   # open -> half-open


class CircuitBreaker:
    def __init__(self, name: str, params: Optional[BreakerParams] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.params = params or BreakerParams()
        self.clock = clock
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._recent: collections.deque[bool] = collections.deque(
            maxlen=self.params.window)  # True = error
        self._opened_at = 0.0
        self.opens = 0
        self._labels = {"cluster": name}
        self._state_gauge = global_registry.gauge(
            "breaker.state",
            "circuit-breaker state per cluster (0 closed, 1 half-open, "
            "2 open)")
        self._opens_counter = global_registry.counter(
            "breaker.opens", "circuit-breaker open transitions per cluster")
        self._outcome_counter = global_registry.counter(
            "breaker.outcomes",
            "launch/kill RPC outcomes observed per cluster")
        self._state_gauge.set(0.0, self._labels)

    # ------------------------------------------------------------ feeding

    def note_success(self, *, probe: bool = False) -> None:
        """`probe=True` marks a LAUNCH outcome — the only path that may
        close a half-open breaker.  A successful kill is evidence the
        kill endpoint works, not that launches do (the outage that
        opened the breaker was launch-path): it feeds the closed-state
        window but never closes a half-open breaker."""
        self._outcome_counter.inc(1, {**self._labels, "outcome": "ok"})
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                if not probe:
                    return
                # the probe came back healthy: close and forget the
                # pre-open error history (it described the outage)
                self._recent.clear()
                self._set_state(BreakerState.CLOSED)
                return
            self._recent.append(False)

    def note_failure(self, *, probe: bool = False) -> None:
        """`probe=True` marks a LAUNCH outcome (mirror of note_success):
        only the launch probe's failure may re-trip a half-open breaker.
        A kill failing while half-open is evidence about the kill
        endpoint, not about the launch probe the breaker is waiting on —
        it feeds the window without deciding the transition (else a
        cluster with a broken kill RPC but healthy launches re-trips on
        every ungated kill and starves forever)."""
        self._outcome_counter.inc(1, {**self._labels, "outcome": "error"})
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                if probe:
                    self._trip()  # the probe failed: straight back open
                else:
                    self._recent.append(True)
                return
            self._recent.append(True)
            if self._state is BreakerState.CLOSED:
                p = self.params
                if len(self._recent) >= p.min_samples and \
                        sum(self._recent) / len(self._recent) \
                        >= p.error_threshold:
                    self._trip()

    def _trip(self) -> None:
        """Caller holds self._lock."""
        self._opened_at = self.clock()
        self.opens += 1
        self._opens_counter.inc(1, self._labels)
        self._set_state(BreakerState.OPEN)

    def _set_state(self, state: BreakerState) -> None:
        self._state = state
        self._state_gauge.set(_STATE_VALUE[state], self._labels)

    # ------------------------------------------------------------- gating

    def allows_work(self) -> bool:
        """Whether the cluster should receive offers/launches right now.
        An open breaker past its cooldown transitions to half-open HERE
        (the next launch through it is the probe)."""
        with self._lock:
            if self._state is BreakerState.OPEN:
                if self.clock() - self._opened_at \
                        >= self.params.cooldown_s:
                    self._set_state(BreakerState.HALF_OPEN)
                    return True
                return False
            return True

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            recent = list(self._recent)
            return {
                "cluster": self.name,
                "state": self._state.value,
                "opens": self.opens,
                "recent_errors": sum(recent),
                "recent_samples": len(recent),
                "error_rate": (sum(recent) / len(recent)
                               if recent else 0.0),
                "opened_age_s": (self.clock() - self._opened_at
                                 if self._state is not BreakerState.CLOSED
                                 and self._opened_at else 0.0),
            }
