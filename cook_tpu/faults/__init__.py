"""Deterministic fault-injection plane: named points, scriptable schedules.

PR 6 finished the *detection* half of robustness (/debug/health names
nine degradation reasons); this package closes the loop — a seeded,
scriptable `FaultSchedule` injects failures and latency at named points
threaded through the REAL code paths, so every health verdict and every
automatic reaction (circuit breakers, CPU solve fallback, load shedding,
fsync policy) is provable on demand: from tests, from the simulator
(`SimConfig.fault_schedule`), from the chaos harness (`tools/chaos.py`),
and from the admin endpoint (`POST /debug/faults`, off by default).

Injection points (each a no-op unless a schedule is armed — the
off-path cost at a site is ONE module-attribute check):

  * `journal.fsync`      — models/persistence.JournalWriter: fsync error
                           (mode `error`) or stall (mode `delay`).
  * `replication.fetch`  — control/replication.JournalFollower leader
                           fetch: drop (`error` -> transport failure) or
                           delayed/wedged follower (`delay`).
  * `replication.ack`    — the follower's ack POST: dropped or delayed.
  * `leader.heartbeat`   — control/leader heartbeats: `error` = lease
                           loss (the elector reports leadership gone).
  * `cluster.launch`     — cluster/base launch RPC (serial AND async
                           fan-out): failure or latency.
  * `cluster.kill`       — cluster kill RPC.
  * `cluster.offers`     — the per-cluster offer scan.
  * `k8s.request`        — cluster/k8s_http.HttpKubeApi apiserver calls.
  * `device.solve`       — scheduler/matcher.dispatch_pool_solve: solve
                           exception or latency spike.

Rules are matched in order; `times`/`after` window the firings, `match`
filters on call-site context (e.g. {"cluster": "k8s-a"} or {"path":
leader_journal_path} — essential when one process hosts several
journals/clusters), `probability` draws from the schedule's SEEDED rng
so runs replay deterministically.

`FaultInjected` subclasses OSError on purpose: injected failures flow
through exactly the error-handling paths a real transport/disk/device
error takes — no test-only except clauses anywhere in the tree.

Import discipline: stdlib + utils.metrics only (the journal writer and
cluster base import this at module level and must stay cheap/jax-free).
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from cook_tpu.utils.metrics import global_registry

# ------------------------------------------------------------ named points

JOURNAL_FSYNC = "journal.fsync"
REPLICATION_FETCH = "replication.fetch"
REPLICATION_ACK = "replication.ack"
LEADER_HEARTBEAT = "leader.heartbeat"
CLUSTER_LAUNCH = "cluster.launch"
CLUSTER_KILL = "cluster.kill"
CLUSTER_OFFERS = "cluster.offers"
K8S_REQUEST = "k8s.request"
DEVICE_SOLVE = "device.solve"

POINTS = (JOURNAL_FSYNC, REPLICATION_FETCH, REPLICATION_ACK,
          LEADER_HEARTBEAT, CLUSTER_LAUNCH, CLUSTER_KILL, CLUSTER_OFFERS,
          K8S_REQUEST, DEVICE_SOLVE)


class FaultInjected(OSError):
    """An injected failure.  An OSError so it rides the SAME error paths
    a real disk/transport/device fault takes."""


@dataclass
class FaultRule:
    """One scripted fault at one point.

    `after` skips the first N hits of the point (arm mid-traffic);
    `times` bounds firings (-1 = until disarmed); `match` must be a
    subset of the call site's context kwargs for the rule to apply;
    `probability` < 1 draws from the schedule's seeded rng.
    """

    point: str
    mode: str = "error"                # "error" | "delay"
    times: int = -1
    after: int = 0
    delay_s: float = 0.0
    probability: float = 1.0
    error: str = ""
    match: dict = field(default_factory=dict)
    # mutable firing state (owned by the schedule's lock)
    hits: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(f"unknown fault point {self.point!r} "
                             f"(known: {', '.join(POINTS)})")
        if self.mode not in ("error", "delay"):
            raise ValueError(f"unknown fault mode {self.mode!r}")

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        return cls(
            point=str(d["point"]),
            mode=str(d.get("mode", "error")),
            times=int(d.get("times", -1)),
            after=int(d.get("after", 0)),
            delay_s=float(d.get("delay_s", 0.0)),
            probability=float(d.get("probability", 1.0)),
            error=str(d.get("error", "")),
            match=dict(d.get("match", {})),
        )

    def to_dict(self) -> dict:
        return {
            "point": self.point, "mode": self.mode, "times": self.times,
            "after": self.after, "delay_s": self.delay_s,
            "probability": self.probability, "error": self.error,
            "match": dict(self.match), "hits": self.hits,
            "fired": self.fired,
        }


class FaultSchedule:
    """An armed set of rules.  Thread-safe: injection points fire from
    REST executors, scheduler threads, launch workers, and the follower
    loop concurrently."""

    def __init__(self, rules: list[FaultRule], *, seed: int = 0,
                 sleep=time.sleep):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._injected = global_registry.counter(
            "faults.injected",
            "faults fired by the armed schedule per point/mode")

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSchedule":
        return cls([FaultRule.from_dict(r) for r in d.get("rules", [])],
                   seed=int(d.get("seed", 0)))

    def to_dict(self) -> dict:
        with self._lock:
            return {"seed": self.seed,
                    "rules": [r.to_dict() for r in self.rules]}

    # ------------------------------------------------------------- firing

    def hit(self, point: str, **ctx) -> None:
        """Evaluate the point against the schedule: sleeps for matching
        delay rules, raises FaultInjected for matching error rules.  A
        site that reaches this unarmed paid one module-attribute check
        and never a call."""
        delay = 0.0
        raise_msg: Optional[str] = None
        with self._lock:
            for rule in self.rules:
                if rule.point != point:
                    continue
                if any(ctx.get(k) != v for k, v in rule.match.items()):
                    continue
                rule.hits += 1
                if rule.hits <= rule.after:
                    continue
                if 0 <= rule.times <= rule.fired:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                rule.fired += 1
                self._injected.inc(1, {"point": point, "mode": rule.mode})
                if rule.mode == "delay":
                    delay += rule.delay_s
                else:
                    raise_msg = (rule.error
                                 or f"injected fault at {point}")
                    break  # an error ends the evaluation (site dies here)
        if delay > 0:
            self._sleep(delay)
        if raise_msg is not None:
            raise FaultInjected(raise_msg)

    def fired_total(self, point: Optional[str] = None) -> int:
        with self._lock:
            return sum(r.fired for r in self.rules
                       if point is None or r.point == point)


# --------------------------------------------------------------- the switch

# THE module global every injection site checks: `if faults.ACTIVE is not
# None: faults.ACTIVE.hit(...)`.  Process-global by design — a chaos run
# targets one process, and rule `match` filters scope within it.
ACTIVE: Optional[FaultSchedule] = None

_armed_gauge = global_registry.gauge(
    "faults.armed", "1 while a fault schedule is armed in this process")


def arm(schedule: FaultSchedule) -> FaultSchedule:
    global ACTIVE
    ACTIVE = schedule
    _armed_gauge.set(1.0)
    return schedule


def disarm() -> None:
    global ACTIVE
    ACTIVE = None
    _armed_gauge.set(0.0)


class injected:
    """Context manager arming an ad-hoc schedule:

        with faults.injected({"point": "journal.fsync", "mode": "delay",
                              "delay_s": 0.1}):
            ...

    Disarms on exit even when the body raises; restores a previously
    armed schedule (nesting composes for test fixtures)."""

    def __init__(self, *rules: dict, seed: int = 0):
        self.schedule = FaultSchedule(
            [FaultRule.from_dict(r) for r in rules], seed=seed)
        self._prev: Optional[FaultSchedule] = None

    def __enter__(self) -> FaultSchedule:
        self._prev = ACTIVE
        return arm(self.schedule)

    def __exit__(self, *exc) -> None:
        if self._prev is not None:
            arm(self._prev)
        else:
            disarm()
