"""Periodic stats gauges: per-user/pool usage, waiting counts, starvation.

Reference: cook.monitor (/root/reference/scheduler/src/cook/monitor.clj):
`set-stats-counters!` publishes per-pool gauges of running/waiting users
and resources, total utilization, and "starved" users — users below their
share who have waiting work (monitor.clj:177).
"""
from __future__ import annotations

from dataclasses import dataclass

from cook_tpu.models.entities import Resources
from cook_tpu.models.store import Event, JobStore
from cook_tpu.utils.metrics import global_registry

# job-lifecycle latencies span milliseconds (a hot match) to days (a
# starved batch queue) — the default request-scale buckets top out at 60s
LIFECYCLE_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0,
                     600.0, 1800.0, 3600.0, 7200.0, 21600.0, 86400.0,
                     float("inf"))


def observe_commit_ack(seconds: float) -> None:
    """submit -> commit-ack: wall time the REST layer spent committing a
    submission (apply + journal fsync + replication wait).  Wide buckets:
    a commit stalled minutes on a recovering standby is exactly what this
    metric exists to expose, and must not collapse into +Inf.  The REST
    layer additionally feeds the same sample into its contention
    observatory's windowed SLO burn-rate tracker (rest/api.py) — the
    cumulative histogram can't answer "how fast are we burning budget
    RIGHT NOW"."""
    global_registry.histogram(
        "job.latency.submit_commit_ack",
        "seconds from submission arrival to durable commit ack",
        buckets=LIFECYCLE_BUCKETS,
    ).observe(seconds)


class JobLifecycleTracker:
    """Store watcher that turns lifecycle transitions into the job-latency
    SLO histograms exported at /metrics:

      * submit -> matched   (instance created for a waiting job)
      * matched -> running  (backend reported the task running)
      * submit -> completed (end-to-end)

    Times come from the store clock (virtual in the simulator, epoch ms in
    production), so the histograms measure scheduler-visible latency, not
    wall time spent in this process.

    `enabled` is the standby effect-gate (same pattern as the scheduler's
    kill fan-out): a passive node applies REPLICATED events at apply
    time, so a backlog replayed after downtime would observe latencies
    inflated by the outage — and the contaminated process-global
    histograms would survive promotion."""

    def __init__(self, store: JobStore, enabled=None):
        self.store = store
        self._enabled = enabled
        self._submit_to_matched = global_registry.histogram(
            "job.latency.submit_to_matched",
            "seconds from job submission to first match",
            buckets=LIFECYCLE_BUCKETS)
        self._matched_to_running = global_registry.histogram(
            "job.latency.matched_to_running",
            "seconds from match (instance created) to running",
            buckets=LIFECYCLE_BUCKETS)
        self._end_to_end = global_registry.histogram(
            "job.latency.end_to_end",
            "seconds from job submission to completion",
            buckets=LIFECYCLE_BUCKETS)
        store.add_watcher(self._on_event)

    def _on_event(self, event: Event) -> None:
        if self._enabled is not None and not self._enabled():
            return
        now_ms = self.store.clock()
        if event.kind == "instance/created":
            job = self.store.jobs.get(event.data.get("job", ""))
            # first instance only: a retry matched days later must not
            # re-observe the full submit->now interval into the
            # first-match histogram
            if job is not None and len(job.instance_ids) == 1:
                self._submit_to_matched.observe(
                    max(0.0, (now_ms - job.submit_time_ms) / 1000.0),
                    {"pool": job.pool})
        elif (event.kind == "instance/status"
              and event.data.get("status") == "running"):
            inst = self.store.instances.get(event.data.get("task_id", ""))
            if inst is not None:
                job = self.store.jobs.get(inst.job_uuid)
                self._matched_to_running.observe(
                    max(0.0, (now_ms - inst.start_time_ms) / 1000.0),
                    {"pool": job.pool} if job is not None else None)
        elif (event.kind == "job/state"
              and event.data.get("state") == "completed"):
            job = self.store.jobs.get(event.data.get("uuid", ""))
            if job is not None:
                self._end_to_end.observe(
                    max(0.0, (now_ms - job.submit_time_ms) / 1000.0),
                    {"pool": job.pool})


def starvation_stats(store: JobStore, pool: str,
                     *, top_users: int = 10) -> dict:
    """Queued-wait visibility for one pool: the oldest queued job's age,
    and per-user max waits (how long each user's most-starved job has
    sat WAITING, measured from `last_waiting_start_time_ms` on the store
    clock — a retried job's wait restarts when it re-queues).  Shared by
    `collect_pool_stats` (gauges), the contention observatory's
    `job-starvation` health check, and the `/unscheduled_jobs` echo."""
    now = store.clock()
    oldest_age_s = 0.0
    oldest_job = ""
    user_waits: dict[str, float] = {}
    waiting = store.pending_jobs(pool)
    for job in waiting:
        start = job.last_waiting_start_time_ms or job.submit_time_ms
        age_s = max(0.0, (now - start) / 1000.0)
        if age_s > oldest_age_s:
            oldest_age_s, oldest_job = age_s, job.uuid
        user_waits[job.user] = max(user_waits.get(job.user, 0.0), age_s)
    ranked = sorted(user_waits.items(), key=lambda kv: kv[1], reverse=True)
    stats = {
        "waiting_jobs": len(waiting),
        "oldest_age_s": oldest_age_s,
        "oldest_job": oldest_job,
        "user_max_wait_s": dict(ranked[:top_users]),
    }
    if ranked:
        stats["worst_user"], stats["worst_user_wait_s"] = ranked[0]
    return stats


# pool -> user labels currently exported on monitor.user_max_wait_seconds
# (so collect_pool_stats can retract users who stopped waiting)
_exported_user_waits: dict[str, set] = {}


@dataclass
class PoolStats:
    running_jobs: int
    waiting_jobs: int
    running_users: int
    waiting_users: int
    starved_users: int
    used: Resources
    waiting_demand: Resources


def collect_pool_stats(store: JobStore, pool: str) -> PoolStats:
    running = store.running_jobs(pool)
    waiting = store.pending_jobs(pool)
    usage = store.user_usage(pool)
    waiting_users = {j.user for j in waiting}
    used = Resources()
    for r in usage.values():
        used = used + r
    demand = Resources()
    for job in waiting:
        demand = demand + job.resources

    starved = 0
    for user in waiting_users:
        share = store.get_share(user, pool)
        u = usage.get(user, Resources())
        # starved: waiting work while using less than their share
        if (u.mem < share.mem and u.cpus < share.cpus) or not usage.get(user):
            starved += 1

    stats = PoolStats(
        running_jobs=len(running),
        waiting_jobs=len(waiting),
        running_users=len(usage),
        waiting_users=len(waiting_users),
        starved_users=starved,
        used=used,
        waiting_demand=demand,
    )
    labels = {"pool": pool}
    g = global_registry.gauge
    g("monitor.running_jobs", "running jobs per pool").set(
        stats.running_jobs, labels)
    g("monitor.waiting_jobs", "waiting jobs per pool").set(
        stats.waiting_jobs, labels)
    g("monitor.running_users", "users with running work per pool").set(
        stats.running_users, labels)
    g("monitor.waiting_users", "users with waiting work per pool").set(
        stats.waiting_users, labels)
    g("monitor.starved_users",
      "users below their share with waiting work").set(
        stats.starved_users, labels)
    g("monitor.used_mem", "running memory usage (MB) per pool").set(
        stats.used.mem, labels)
    g("monitor.used_cpus", "running cpu usage per pool").set(
        stats.used.cpus, labels)
    g("monitor.waiting_mem", "waiting memory demand (MB) per pool").set(
        stats.waiting_demand.mem, labels)
    g("monitor.waiting_cpus", "waiting cpu demand per pool").set(
        stats.waiting_demand.cpus, labels)
    # starvation visibility: the age of the pool's oldest queued job and
    # each (top-10) user's most-starved wait — the signal that flips the
    # `job-starvation` degradation at /debug/health
    sv = starvation_stats(store, pool)
    g("monitor.oldest_waiting_age_seconds",
      "age of the oldest queued job per pool").set(
        sv["oldest_age_s"], labels)
    user_gauge = g("monitor.user_max_wait_seconds",
                   "longest current queued wait per user (top waiting "
                   "users)")
    # a user who scheduled (or fell out of the top set) must stop being
    # exported — a frozen last value reads as ongoing starvation, and
    # the label set would otherwise grow with workload user churn
    for user in _exported_user_waits.get(pool, set()) - \
            set(sv["user_max_wait_s"]):
        user_gauge.remove({"pool": pool, "user": user})
    for user, wait_s in sv["user_max_wait_s"].items():
        user_gauge.set(wait_s, {"pool": pool, "user": user})
    _exported_user_waits[pool] = set(sv["user_max_wait_s"])
    return stats


def collect_all(store: JobStore) -> dict[str, PoolStats]:
    return {pool: collect_pool_stats(store, pool) for pool in store.pools}
