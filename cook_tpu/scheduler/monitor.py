"""Periodic stats gauges: per-user/pool usage, waiting counts, starvation.

Reference: cook.monitor (/root/reference/scheduler/src/cook/monitor.clj):
`set-stats-counters!` publishes per-pool gauges of running/waiting users
and resources, total utilization, and "starved" users — users below their
share who have waiting work (monitor.clj:177).
"""
from __future__ import annotations

from dataclasses import dataclass

from cook_tpu.models.entities import Resources
from cook_tpu.models.store import JobStore
from cook_tpu.utils.metrics import global_registry


@dataclass
class PoolStats:
    running_jobs: int
    waiting_jobs: int
    running_users: int
    waiting_users: int
    starved_users: int
    used: Resources
    waiting_demand: Resources


def collect_pool_stats(store: JobStore, pool: str) -> PoolStats:
    running = store.running_jobs(pool)
    waiting = store.pending_jobs(pool)
    usage = store.user_usage(pool)
    waiting_users = {j.user for j in waiting}
    used = Resources()
    for r in usage.values():
        used = used + r
    demand = Resources()
    for job in waiting:
        demand = demand + job.resources

    starved = 0
    for user in waiting_users:
        share = store.get_share(user, pool)
        u = usage.get(user, Resources())
        # starved: waiting work while using less than their share
        if (u.mem < share.mem and u.cpus < share.cpus) or not usage.get(user):
            starved += 1

    stats = PoolStats(
        running_jobs=len(running),
        waiting_jobs=len(waiting),
        running_users=len(usage),
        waiting_users=len(waiting_users),
        starved_users=starved,
        used=used,
        waiting_demand=demand,
    )
    labels = {"pool": pool}
    g = global_registry.gauge
    g("monitor.running_jobs").set(stats.running_jobs, labels)
    g("monitor.waiting_jobs").set(stats.waiting_jobs, labels)
    g("monitor.running_users").set(stats.running_users, labels)
    g("monitor.waiting_users").set(stats.waiting_users, labels)
    g("monitor.starved_users").set(stats.starved_users, labels)
    g("monitor.used_mem").set(stats.used.mem, labels)
    g("monitor.used_cpus").set(stats.used.cpus, labels)
    g("monitor.waiting_mem").set(stats.waiting_demand.mem, labels)
    g("monitor.waiting_cpus").set(stats.waiting_demand.cpus, labels)
    return stats


def collect_all(store: JobStore) -> dict[str, PoolStats]:
    return {pool: collect_pool_stats(store, pool) for pool in store.pools}
