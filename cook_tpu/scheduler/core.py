"""Scheduler composition: cycles, status handling, kill fan-out, monitors.

This is the equivalent of the reference's leader-side wiring
(/root/reference/scheduler/src/cook/mesos.clj:153-328 +
scheduler/scheduler.clj:2473-2517): per-pool rank/match/rebalance cycles
driven by triggers, backend status updates flowing into the store's state
machine, the store's event feed driving kill fan-out for completed jobs, and
the task-lifecycle monitors (lingering/straggler/cancelled killers,
reconciliation).
"""
from __future__ import annotations

import itertools
import logging
import statistics
from dataclasses import dataclass, field
from typing import Optional, Sequence

from cook_tpu.cluster.base import ComputeCluster
from cook_tpu.elastic.planner import ElasticParams
from cook_tpu.models.entities import (
    InstanceStatus,
    Job,
    Pool,
    Resources,
)
from cook_tpu.models.store import Event, JobStore
from cook_tpu.scheduler.flight_recorder import FlightRecorder, PreemptionRecord
from cook_tpu.scheduler.matcher import (
    MatchConfig,
    MatchOutcome,
    PoolMatchState,
    match_pool,
)
from cook_tpu.scheduler.ranking import RankedQueue, rank_pool
from cook_tpu.utils.metrics import global_registry
from cook_tpu.scheduler.rebalancer import (
    Decision,
    RebalancerParams,
    rebalance_pool,
)

log = logging.getLogger(__name__)


@dataclass
class SchedulerConfig:
    match: MatchConfig = field(default_factory=MatchConfig)
    rebalancer: RebalancerParams = field(default_factory=RebalancerParams)
    max_runtime_check: bool = True
    # per-user-per-pool launch rate (token bucket); 0 = unlimited
    # (reference: create-per-user-per-pool-launch-rate-limiter, quota.clj:118)
    user_launch_rate_per_minute: float = 0.0
    user_launch_burst: float = 0.0
    # columnar host-side state: O(delta) rank-cycle encoding
    use_columnar_index: bool = True
    # host-encode cache (scheduler/encode_cache.py): incremental
    # encode_nodes + feasibility rows keyed by offer-set fingerprint,
    # store-event invalidated — an unchanged pool re-encodes O(delta)
    use_encode_cache: bool = True
    # pipelined multi-pool match pass (scheduler/pipeline.py): overlap
    # host encode/launch with the device solve; depth = max in-flight
    # solves (2 = double-buffered)
    pipeline_depth: int = 2
    # fan backend launches out on the per-cluster launch executors during
    # the pipelined pass (kills still exclude via the kill-lock)
    async_launch: bool = True
    # prediction-assisted speculative cycles (scheduler/prediction.py):
    # while cycle N's launches drain, cycle N+1's solve is pre-encoded
    # and pre-dispatched against the predicted offer set; it commits at
    # cycle N+1 start only if the stamped epoch is unchanged (a stale
    # speculation is dropped, never repaired).  Off by default.
    speculation: bool = False
    # how far ahead (store-clock ms) a running task's predicted finish
    # may sit and still be assumed complete by the speculative solve;
    # the simulator pins this to its cycle_ms
    speculation_horizon_ms: float = 30_000.0
    # runtime predictor (per-(user, command-fingerprint) rolling
    # quantiles; pluggable — ROADMAP item 5's learned model slots in)
    predictor_quantile: float = 0.75
    predictor_window: int = 64
    predictor_min_samples: int = 3
    # predicted-duration backfill (ops/dru.py): a bounded scoring term
    # added to each pending task's DRU before the global order sort, so
    # predicted-short jobs backfill ahead at near-equal fairness.  0
    # disables (rank order untouched — the default); quality-guarded by
    # the QualityMonitor like every approximate path.
    backfill_weight: float = 0.0
    # predicted duration that saturates the backfill term (fraction
    # clamps to 1 at/above this)
    backfill_norm_ms: float = 600_000.0
    # flight recorder: bounded ring of per-cycle decision records served
    # at GET /debug/cycles (flight_recorder.py); 0 disables
    flight_recorder_capacity: int = 512
    # device telemetry (cook_tpu/obs/): compile observatory, sampled CPU
    # shadow-solve quality monitor, solve-latency baselines, device-memory
    # gauges — the substrate of GET /debug/health.  False disables.
    device_telemetry: bool = True
    # shadow-solve every Nth solvable match cycle per pool (0 keeps the
    # telemetry but never shadow-solves)
    quality_sample_every: int = 25
    # recompile storm: >= threshold new XLA programs within the last
    # `window` solves of one op (padding-bucket churn signature); the
    # op's first `warmup` solves never feed the trigger (first-boot
    # compiles are expected — a page per deploy trains operators to
    # ignore the signal).  None = one full window.
    compile_storm_window: int = 32
    compile_storm_threshold: int = 4
    compile_storm_warmup: Optional[int] = None
    # device-oom-risk fires above this allocator fill fraction
    device_oom_threshold: float = 0.9
    # incident observatory (cook_tpu/obs/incident.py): every ok->degraded
    # health transition snapshots an evidence bundle (verdict, cycle
    # records, span-ring chrome trace, armed faults, contention when the
    # REST layer is attached) into a bounded ring served at
    # GET /debug/incidents; incident_dir persists bundles to disk
    incident_capacity: int = 32
    incident_cooldown_s: float = 30.0
    incident_dir: str = ""
    # automatic device-profile capture (obs/profiling.ProfileCapturer)
    # riding the incident capture for latency-shaped reasons; opt-in —
    # jax's profiler is process-global and a capture costs real overhead,
    # so only the service wiring (components.py) turns it on by default
    auto_profile: bool = False
    profile_dir: str = ""
    # elastic capacity plane (cook_tpu/elastic/): pool-to-pool capacity
    # loaning with durable ledger deltas and reclaim-before-preemption.
    # Disabled by default; enable via ElasticParams(enabled=True)
    elastic: ElasticParams = field(default_factory=ElasticParams)


class Scheduler:
    """One leader's scheduling brain.  Host-side orchestration; all the
    heavy per-cycle math runs in the JAX kernels."""

    def __init__(
        self,
        store: JobStore,
        clusters: Sequence[ComputeCluster],
        config: Optional[SchedulerConfig] = None,
        plugins=None,
        txn=None,
    ):
        from cook_tpu.scheduler.plugins import PluginRegistry

        self.store = store
        self.clusters = list(clusters)
        self.config = config or SchedulerConfig()
        self.plugins = plugins or PluginRegistry()
        self._launch_filter_cache: dict = {}
        self.launch_rate_limiter = None
        if self.config.user_launch_rate_per_minute > 0:
            from cook_tpu.scheduler.ratelimit import TokenBucketRateLimiter

            self.launch_rate_limiter = TokenBucketRateLimiter(
                tokens_replenished_per_minute=(
                    self.config.user_launch_rate_per_minute),
                bucket_size=(self.config.user_launch_burst
                             or self.config.user_launch_rate_per_minute),
                clock=store.clock,
            )
        self._task_seq = itertools.count()
        # effect gate: on a hot standby the store event feed carries
        # REPLICATED transactions (control/replication.py) — the leader
        # already executed their side effects (kill fan-out, completion
        # plugins) and the results arrive as further replicated events, so
        # a passive scheduler must only maintain indexes, never re-execute.
        # components.start_leader_duties flips this at promotion.
        self.active = True
        self.columnar = None
        if self.config.use_columnar_index:
            from cook_tpu.models.columnar import ColumnarJobIndex

            self.columnar = ColumnarJobIndex(store)
        self.encode_cache = None
        if self.config.use_encode_cache:
            from cook_tpu.scheduler.encode_cache import EncodeCache

            self.encode_cache = EncodeCache(store)
        # device-resident match state (scheduler/device_state.py):
        # per-pool encode tensors stay on device across cycles with
        # O(delta) donated-buffer updates; also hosts the quantization
        # parity guard, so it exists whenever either knob is on (the
        # observatory reference is patched in after telemetry below)
        self.device_state = None
        if self.config.match.device_residency or self.config.match.quantized:
            from cook_tpu.scheduler.device_state import DeviceResidentState

            self.device_state = DeviceResidentState(
                encode_cache=self.encode_cache,
                parity_floor=self.config.match.quantization_parity_floor)
        # runtime prediction + speculative cycles (prediction.py):
        # the predictor feeds from instance completions; the speculator
        # pre-dispatches cycle N+1's solve while cycle N drains
        self.predictor = None
        self.speculator = None
        if self.config.speculation or self.config.backfill_weight > 0:
            from cook_tpu.scheduler.prediction import (
                QuantileRuntimePredictor,
            )

            self.predictor = QuantileRuntimePredictor(
                quantile=self.config.predictor_quantile,
                window=self.config.predictor_window,
                min_samples=self.config.predictor_min_samples,
            ).attach(store)
        if self.config.speculation:
            from cook_tpu.scheduler.prediction import CycleSpeculator

            self.speculator = CycleSpeculator(
                store, self.clusters, self.predictor,
                horizon_ms=self.config.speculation_horizon_ms,
                encode_cache=self.encode_cache,
                device_state=self.device_state,
            )
        self.pool_queues: dict[str, RankedQueue] = {}
        self.pool_match_state: dict[str, PoolMatchState] = {}
        self.last_unmatched_offers: dict[str, dict[str, Resources]] = {}
        self.placement_failures: dict[str, str] = {}  # job uuid -> reason text
        # rebalancer host reservations: hostname -> reserving job uuid
        # (reserve-hosts!, rebalancer.clj:419)
        self.host_reservations: dict[str, str] = {}
        # accumulating hostname -> attributes cache: fully-occupied hosts
        # emit no offers, but their attrs are still needed to count running
        # group members for balanced-host placement (constraints.clj:600).
        # LRU-bounded: long-lived autoscaled clusters mint unique node
        # names forever
        from collections import OrderedDict

        self.host_attr_cache: OrderedDict[str, dict] = OrderedDict()
        self.host_attr_cache_max = 100_000
        self.metrics: dict[str, float] = {}
        # per-cycle flight recorder (GET /debug/cycles) + job-lifecycle
        # latency histograms (submit->matched, matched->running,
        # end-to-end), both the measurement substrate of docs/observability.md
        self.recorder = (
            FlightRecorder(capacity=self.config.flight_recorder_capacity)
            if self.config.flight_recorder_capacity > 0 else None)
        # device telemetry (cook_tpu/obs/): every rank/match/rebalance
        # solve reports its (op, padded shape, backend) here; /debug/health
        # folds it into the degradation verdict
        self.telemetry = None
        if self.config.device_telemetry:
            from cook_tpu.obs import DeviceTelemetry

            self.telemetry = DeviceTelemetry(
                storm_window=self.config.compile_storm_window,
                storm_threshold=self.config.compile_storm_threshold,
                storm_warmup=self.config.compile_storm_warmup,
                quality_sample_every=self.config.quality_sample_every,
                oom_threshold=self.config.device_oom_threshold,
            )
        if self.device_state is not None and self.telemetry is not None:
            # compile accounting for the update/gather programs, and the
            # quantization parity guard riding every shadow-solve sample
            # (one wiring site covers serial/batched/pipelined/spec)
            self.device_state.observatory = self.telemetry.observatory
            self.telemetry.quality.add_listener(
                self.device_state.note_quality)
        elif self.config.match.quantized:
            # the parity guard rides the QualityMonitor's shadow-solve
            # samples; without device telemetry no samples ever flow, so
            # bf16 drift would go undetected AND undemoted — say so
            # loudly instead of quietly running unguarded
            log.warning(
                "MatchConfig.quantized is on but device_telemetry is "
                "off: the QualityMonitor parity guard cannot run, so "
                "bf16 packing drift will never demote to f32 — enable "
                "device_telemetry or disable quantized")
        # incident observatory + profile capture (diagnosis layer,
        # cook_tpu/obs/incident.py): the scheduler contributes cycle
        # records, the span-ring chrome trace, and the armed fault
        # schedule as bundle evidence; the REST layer (rest/api.py) adds
        # its contention snapshot when it adopts this recorder
        from cook_tpu.obs.incident import (IncidentRecorder,
                                           add_default_collectors)
        from cook_tpu.obs.profiling import ProfileCapturer

        self.profiler = ProfileCapturer(
            base_dir=self.config.profile_dir or None)
        self.incidents = add_default_collectors(IncidentRecorder(
            capacity=self.config.incident_capacity,
            cooldown_s=self.config.incident_cooldown_s,
            dir=self.config.incident_dir or None,
            profiler=self.profiler,
            auto_profile=self.config.auto_profile))
        if self.recorder is not None:
            self.incidents.add_collector(
                "cycles", lambda: self.recorder.records_json(limit=50))
        if self.telemetry is not None:
            self.telemetry.health_observer = self.incidents.observe
        # fairness observatory (obs/fairness.py): per-user DRU
        # trajectories fed from rank_cycle, the preemption ledger fed
        # from rebalance_cycle, wasted-work rollups recovered from the
        # store's terminal instances after failover
        from cook_tpu.obs.fairness import FairnessObservatory

        self.fairness = FairnessObservatory(clock=store.clock)
        self.fairness.recover(store)
        self.incidents.add_collector("fairness", self.fairness.collector)
        self._last_rank_s: dict[str, float] = {}
        # elastic capacity plane: capacity deltas commit through the txn
        # pipeline (components.py wires the journal-backed log in; a bare
        # Scheduler gets an in-memory pipeline with the same op registry)
        self.elastic = None
        if self.config.elastic.enabled:
            from cook_tpu.elastic import CapacityPlanner
            from cook_tpu.txn import TransactionLog

            self.txn = txn or TransactionLog(store)
            self.elastic = CapacityPlanner(
                store, self.clusters, self.txn, self.config.elastic,
                telemetry=self.telemetry)
        # overload admission control (cook_tpu/faults/reactions.py):
        # while the control plane burns its commit-ack SLO budget (or the
        # store lock saturates), every pool's considerable window shrinks
        # x0.95 per cycle toward a floor, restoring as the burn clears —
        # Cook's head-of-queue scaleback, driven by overload.  Inert
        # until components.py (or a test/chaos harness) sets overload_fn.
        from cook_tpu.faults.reactions import AdmissionController

        self.admission = AdmissionController()
        from cook_tpu.scheduler.monitor import JobLifecycleTracker

        # effect-gated like _on_event: a standby applying replicated
        # events must not observe apply-time latencies into the SLO
        # histograms (a replayed backlog would inflate them by the outage)
        self.lifecycle = JobLifecycleTracker(store,
                                             enabled=lambda: self.active)
        store.add_watcher(self._on_event)
        for cluster in self.clusters:
            if hasattr(cluster, "status_callback"):
                cluster.status_callback = self.handle_status_update

    # ------------------------------------------------------------ plumbing

    def cluster_by_name(self, name: str) -> Optional[ComputeCluster]:
        for c in self.clusters:
            if c.name == name:
                return c
        return None

    def add_cluster(self, cluster: ComputeCluster) -> None:
        """Attach a dynamically-created compute cluster (reference: dynamic
        cluster config insertion, compute_cluster.clj:450-530)."""
        if self.cluster_by_name(cluster.name) is not None:
            raise ValueError(f"cluster {cluster.name} already exists")
        if hasattr(cluster, "status_callback"):
            cluster.status_callback = self.handle_status_update
        self.clusters.append(cluster)

    def _make_task_id(self, job: Job) -> str:
        return f"task-{job.uuid[:8]}-{next(self._task_seq)}"

    # ---------------------------------------------------- status + fan-out

    def handle_status_update(
        self, task_id: str, status: InstanceStatus, reason: Optional[str]
    ) -> None:
        """Backend callback -> store state machine (write-status-to-datomic,
        scheduler.clj:217)."""
        self.store.update_instance_state(task_id, status, reason)

    def _on_event(self, event: Event) -> None:
        """Store event feed consumer: kill-on-complete fan-out
        (monitor-tx-report-queue, scheduler.clj:378) and instance-completion
        plugin dispatch (plugins/definitions.clj:44)."""
        if not self.active:
            return
        if event.kind == "instance/status" and event.data["status"] in (
            "success", "failed"
        ):
            job = self.store.jobs.get(event.data["job"])
            inst = self.store.instances.get(event.data["task_id"])
            if job is not None and inst is not None:
                self.plugins.on_completion(job, inst)
                self._note_wasted_work(job, inst)
        if event.kind != "job/state" or event.data.get("state") != "completed":
            return
        job_uuid = event.data["uuid"]
        for inst in self.store.live_instances_of_job(job_uuid):
            cluster = self.cluster_by_name(inst.compute_cluster)
            if cluster is not None:
                cluster.safe_kill_task(inst.task_id)
                self.store.update_instance_state(
                    inst.task_id, InstanceStatus.FAILED, "killed-by-user"
                )

    def _note_wasted_work(self, job, inst) -> None:
        """Mea-culpa wasted-work accounting for NON-rebalancer kills
        (e.g. the backing cluster preempted the container, reason
        `container-preempted`).  Rebalancer preemptions are accounted at
        decision time by rebalance_cycle -> fairness.record_decisions,
        and their instance/status event lands here too — skip them or
        the wasted seconds double-count."""
        from cook_tpu.models.reasons import REASONS_BY_CODE

        if inst.status != InstanceStatus.FAILED or inst.reason_code is None:
            return
        reason = REASONS_BY_CODE.get(inst.reason_code)
        if (reason is None or not reason.mea_culpa
                or reason.name == "preempted-by-rebalancer"):
            return
        end_ms = inst.end_time_ms or self.store.clock()
        wasted_s = max(0.0, (end_ms - inst.start_time_ms) / 1000.0)
        self.fairness.note_kill(job.pool, job.user, inst.task_id,
                                wasted_s, reason=reason.name)

    # -------------------------------------------------------------- cycles

    def _pool_capacity_probe(self, pool: Pool):
        """(limits_active, max_mem, max_cpus, max_gpus) over the pool's
        work-accepting clusters — the offensive-job filter's input
        (scheduler.clj:2198-2257), shared by the rank cycle and the
        speculative dispatch (whose predicted rank must apply the SAME
        filter or the commit-time window-equality check can never pass).
        An autoscaling cluster can grow capacity, so nothing is offensive
        relative to its current nodes (limits inactive)."""
        from cook_tpu.cluster.base import safe_pool_offers

        max_mem = max_cpus = max_gpus = 0.0
        autoscales = False
        for cluster in self.clusters:
            if not cluster.accepts_work:
                continue
            autoscales = autoscales or cluster.autoscaling(pool.name)
            for offer in safe_pool_offers(cluster, pool.name) or ():
                max_mem = max(max_mem, offer.total_mem or offer.mem)
                max_cpus = max(max_cpus, offer.total_cpus or offer.cpus)
                max_gpus = max(max_gpus, offer.gpus)
        return max_mem > 0 and not autoscales, max_mem, max_cpus, max_gpus

    def _offensive_filter(self, pool: Pool):
        """The pool's current offensive-job filter (or None)."""
        from cook_tpu.scheduler.ranking import offensive_job_filter

        limits_active, max_mem, max_cpus, max_gpus = \
            self._pool_capacity_probe(pool)
        return (offensive_job_filter(max_mem, max_cpus, max_gpus)
                if limits_active else None)

    @property
    def _backfill_active(self) -> bool:
        return self.config.backfill_weight > 0 and self.predictor is not None

    def _pool_store(self, pool: Pool):
        """The store a per-pool cycle should read: a sharded (or mp
        shard-group) store exposes `store_for_pool`, pinning the cycle
        to the pool's own shard — one snapshot, no facade fan-out and
        no cross-shard lock traffic mid-cycle.  Plain JobStores return
        themselves."""
        pinned = getattr(self.store, "store_for_pool", None)
        if pinned is None:
            return self.store
        try:
            return pinned(pool.name)
        except Exception:  # noqa: BLE001 — a pool this process does
            # not serve (MisroutedKey): fall back to the facade, which
            # raises the precise error at the access site
            return self.store

    def rank_cycle(self, pool: Pool) -> RankedQueue:
        # offensive-job filter: quarantine jobs no host in the pool could
        # ever hold (scheduler.clj:2198-2257)
        import time as _time

        from cook_tpu.scheduler.ranking import offensive_job_filter

        t_rank = _time.perf_counter()

        limits_active, max_mem, max_cpus, max_gpus = \
            self._pool_capacity_probe(pool)
        # DRU-column residency rides the match knob: with residency on,
        # the rank cycle's task columns reuse their resident device
        # copies when content is unchanged (device_state.resident_array)
        dru_state = (self.device_state
                     if self.config.match.device_residency else None)
        if self.columnar is not None and not self._backfill_active:
            from cook_tpu.scheduler.ranking_columnar import rank_pool_columnar

            queue = rank_pool_columnar(
                self._pool_store(pool), self.columnar, pool,
                capacity_limits=((max_mem, max_cpus, max_gpus)
                                 if limits_active else None),
                device_state=dru_state,
            )
        else:
            # predicted-duration backfill routes through the full encoder
            # (the columnar fast path carries no duration column yet):
            # the bounded term is added to the DRU tensor in ops/dru.py
            filt = (offensive_job_filter(max_mem, max_cpus, max_gpus)
                    if limits_active else None)
            queue = rank_pool(
                self._pool_store(pool), pool, offensive_job_filter=filt,
                predictor=(self.predictor if self._backfill_active
                           else None),
                backfill_weight=self.config.backfill_weight,
                backfill_norm_ms=self.config.backfill_norm_ms,
                device_state=dru_state)
        for uuid in queue.quarantined:
            self.placement_failures[uuid] = (
                "The job's resource demands exceed every host in the pool."
            )
        self.pool_queues[pool.name] = queue
        self.metrics[f"rank.{pool.name}.queue_len"] = len(queue.jobs)
        global_registry.gauge(
            "rank.queue_len", "ranked queue length per pool").set(
            len(queue.jobs), {"pool": pool.name})
        # fairness trajectory sample: the rank cycle is the one moment
        # the per-user fair-share picture (queue DRU + running usage) is
        # coherent in one place
        self.fairness.observe_rank(pool.name, queue, self._pool_store(pool))
        # stash the duration so the NEXT match cycle's flight record can
        # claim its rank phase even when ranking is driven separately
        # (components.py rank trigger, the simulator's explicit rank step)
        rank_s = _time.perf_counter() - t_rank
        self._last_rank_s[pool.name] = rank_s
        if self.telemetry is not None and queue.solve_shape is not None:
            # compile accounting for the DRU kernel: its padded task
            # bucket is the shape axis that churns as the queue grows.
            # No seconds: rank_s is the whole rank cycle's wall (offer
            # scans + host-side queue assembly), not device solve time —
            # feeding it would corrupt the obs.solve.seconds histogram
            self.telemetry.record_solve("rank", queue.solve_shape, "xla")
        return queue

    def _begin_cycle(self, pool_name: str):
        from cook_tpu.scheduler.flight_recorder import NULL_CYCLE

        if self.recorder is None:
            return NULL_CYCLE
        flight = self.recorder.begin(pool_name, self.store.clock())
        # per-pool capacity snapshot at cycle start + the capacity plan
        # the cycle ran under, so elastic deltas correlate with match
        # outcomes straight off the record (docs/elastic.md)
        flight.record.pool_capacity = self._pool_capacity_snapshot(pool_name)
        if self.elastic is not None:
            flight.record.elastic_plan = self.elastic.recorder.last_plan_id()
        return flight

    def _pool_capacity_snapshot(self, pool_name: str) -> dict:
        """Host count + total/spare capacity the pool holds right now
        (one extra offer scan per recorded cycle — the record's claim is
        capacity AT CYCLE START, which the post-match spare cache can't
        provide; totals for gpus are not carried by offers, so only
        spare is reported there)."""
        from cook_tpu.cluster.base import scan_pool_offers

        hosts = 0
        mem = cpus = 0.0
        spare = {"mem": 0.0, "cpus": 0.0, "gpus": 0.0}
        for _cluster, offer in scan_pool_offers(self.clusters, pool_name):
            hosts += 1
            mem += offer.total_mem or offer.mem
            cpus += offer.total_cpus or offer.cpus
            spare["mem"] += max(offer.mem, 0.0)
            spare["cpus"] += max(offer.cpus, 0.0)
            spare["gpus"] += max(offer.gpus, 0.0)
        return {"hosts": hosts, "mem": mem, "cpus": cpus,
                "spare_mem": spare["mem"], "spare_cpus": spare["cpus"],
                "spare_gpus": spare["gpus"]}

    def _commit_cycle(self, flight) -> None:
        if self.recorder is not None and flight.record is not None:
            self.recorder.commit(flight)

    def _credit_rank_and_quarantine(self, flight, pool_name: str,
                                    queue) -> None:
        """Shared cycle-record prologue for both match paths: claim the
        most recent rank cycle's duration, and record the jobs the rank
        cycle's offensive-job filter quarantined (the matcher never sees
        them)."""
        from cook_tpu.scheduler.flight_recorder import EXCEEDS_POOL_CAPACITY

        rank_s = self._last_rank_s.pop(pool_name, None)
        if rank_s is not None:
            flight.add_phase("rank", rank_s)
        for uuid in getattr(queue, "quarantined", ()):
            flight.note_skip(uuid, EXCEEDS_POOL_CAPACITY)

    def match_cycle(self, pool: Pool) -> MatchOutcome:
        flight = self._begin_cycle(pool.name)
        queue = self.pool_queues.get(pool.name)
        if queue is None:
            queue = self.rank_cycle(pool)
        self._credit_rank_and_quarantine(flight, pool.name, queue)
        state = self.pool_match_state.setdefault(
            pool.name,
            PoolMatchState(num_considerable=self.config.match.max_jobs_considered),
        )
        self.admission.clamp(pool.name, state,
                             self.config.match.max_jobs_considered)
        from cook_tpu.obs import data_plane

        # the cycle's data-plane scope covers the speculation commit
        # too: a hit's only transfer is the speculative assignment's
        # fetch (its tensor build ran during the PREVIOUS cycle's drain,
        # scope-less), so hit cycles report near-zero H2D — the
        # device-residency behavior item 2(a) generalizes
        with data_plane.activate(flight.dp):
            outcome = self._try_speculative_cycle(pool, queue, state,
                                                  flight)
        if outcome is None:
            outcome = match_pool(
                self._pool_store(pool),
                pool,
                queue,
                self.clusters,
                self.config.match,
                state,
                make_task_id=self._make_task_id,
                launch_filter=self._make_launch_filter(),
                record_placement_failure=self._record_placement_failure,
                host_reservations=self.host_reservations,
                host_attrs=self.host_attr_cache,
                flight=flight,
                telemetry=self.telemetry,
                encode_cache=self.encode_cache,
                predictor=self.predictor,
                device_state=self.device_state,
            )
        # charge launches against the per-user rate limiter (spend-through)
        if self.launch_rate_limiter is not None:
            for job, _ in outcome.matched:
                self.launch_rate_limiter.spend((job.user, job.pool))
        # cache spare resources for the rebalancer (view-incubating-offers,
        # scheduler.clj:1537): offers minus what this cycle just placed
        matched_uuids = {j.uuid for j, _ in outcome.matched}
        # launched jobs release their host reservations; a placed gang
        # releases its group-wide gang:<group> reservations
        matched_tags = matched_uuids | {
            "gang:" + j.group_uuid
            for j, _ in outcome.matched if j.group_uuid}
        if self.host_reservations:
            self.host_reservations = {
                host: tag for host, tag in self.host_reservations.items()
                if tag not in matched_tags
            }
        queue.jobs = [j for j in queue.jobs if j.uuid not in matched_uuids]
        self._cache_spare(pool)
        self.metrics[f"match.{pool.name}.matched"] = len(outcome.matched)
        self.metrics[f"match.{pool.name}.offers"] = outcome.offers_total
        global_registry.counter(
            "match.matched", "jobs matched to hosts per pool").inc(
            len(outcome.matched), {"pool": pool.name})
        global_registry.gauge(
            "match.offers", "offers seen by the last match cycle").set(
            outcome.offers_total, {"pool": pool.name})
        # per-cycle summary line (handle-match-cycle-metrics,
        # scheduler.clj:1210)
        from cook_tpu.utils.logging import log_info

        log_info(
            "match cycle",
            component="matcher",
            pool=pool.name,
            matched=len(outcome.matched),
            unmatched=len(outcome.unmatched),
            offers=outcome.offers_total,
            head_matched=outcome.head_matched,
            considerable_window=state.num_considerable,
        )
        if flight.record is not None:
            flight.record.head_matched = outcome.head_matched
        self._commit_cycle(flight)
        # speculate cycle N+1 while this cycle's work drains (launches
        # in the serial path are synchronous, so every event this cycle
        # produced has already landed — the guard token opens clean)
        self._dispatch_speculation([pool])
        return outcome

    # ------------------------------------------------ speculative cycles

    def _speculation_commit(self, pool, queue, state, flight):
        """One pool's speculation commit attempt (prediction.py commit
        rule), recorded on the cycle record.  On a hit the cycle-record
        bookkeeping a fresh prepare would have done (counts, rank
        context, not-considered index, solve identity, quality sample)
        runs here.  Returns the CommitResult, or None when no speculator
        is attached."""
        if self.speculator is None:
            return None
        from cook_tpu.obs.compile_observatory import shape_signature
        from cook_tpu.scheduler.matcher import (
            problem_shape,
            record_considered,
            solve_backend,
        )

        with flight.phase("speculation_commit"):
            result = self.speculator.try_commit(
                pool, queue, state, self.config.match,
                launch_filter=self._make_launch_filter())
        flight.note_speculation(result.status, result.reason)
        if result.ok:
            prepared = result.prepared
            record_considered(flight, queue, prepared.considerable,
                              len(prepared.cluster_offers))
            # the backend label marks the cycle as speculative-served;
            # no telemetry latency sample — the solve's wall spanned the
            # previous cycle's drain, not this cycle's critical path
            flight.note_solve(
                shape_signature(problem_shape(prepared.problem)),
                f"spec-{solve_backend(self.config.match)}", False)
            if self.telemetry is not None:
                self.telemetry.quality.observe_cycle(
                    prepared, result.assignment, pool.name)
        return result

    def _try_speculative_cycle(self, pool, queue, state, flight):
        """Serve the cycle from a committed speculation; None = solve
        fresh (nothing in flight, or the speculation was dropped)."""
        result = self._speculation_commit(pool, queue, state, flight)
        if result is None or not result.ok:
            return None
        from cook_tpu.scheduler.matcher import finalize_pool_match

        with flight.phase("launch"):
            return finalize_pool_match(
                self.store, result.prepared, result.assignment,
                self.config.match, state, self.clusters,
                make_task_id=self._make_task_id,
                record_placement_failure=self._record_placement_failure,
                flight=flight)

    def _dispatch_speculation(self, pools) -> None:
        """End-of-cycle speculative dispatch (prediction.py): predict the
        completions the next cycle will see, pre-encode its problem and
        start its solve — the device works through the drain and the
        inter-cycle idle.  Must run AFTER the cycle's launches and their
        store events have landed, or the guard would mark the fresh
        speculation stale against our own events."""
        if self.speculator is None:
            return
        for pool in pools:
            state = self.pool_match_state.get(pool.name)
            if state is None:
                continue
            self.speculator.dispatch(
                pool, self.config.match, state,
                launch_filter=self._make_launch_filter(),
                host_reservations=self.host_reservations,
                host_attrs=self.host_attr_cache,
                offensive_job_filter=self._offensive_filter(pool),
                predictor_for_rank=(self.predictor
                                    if self._backfill_active else None),
                backfill_weight=self.config.backfill_weight,
                backfill_norm_ms=self.config.backfill_norm_ms)

    def match_cycle_all_pools(self, mesh=None) -> dict[str, MatchOutcome]:
        """Batched multi-pool match: every active pool's problem solved in
        one device call, optionally sharded over `mesh` (the config-5
        path; see matcher.match_pools_batched)."""
        from cook_tpu.scheduler.matcher import match_pools_batched

        pools, flights = self._begin_multi_pool_cycle()
        outcomes = match_pools_batched(
            self.store, pools, self.pool_queues, self.clusters,
            self.config.match, self.pool_match_state,
            make_task_id=self._make_task_id,
            launch_filter=self._make_launch_filter(),
            record_placement_failure=self._record_placement_failure,
            host_reservations=self.host_reservations,
            host_attrs=self.host_attr_cache,
            mesh=mesh,
            flights=flights,
            telemetry=self.telemetry,
            encode_cache=self.encode_cache,
            predictor=self.predictor,
            device_state=self.device_state,
        )
        self._finish_multi_pool_cycle(pools, outcomes, flights)
        return outcomes

    def match_cycle_pipelined(self) -> dict[str, MatchOutcome]:
        """Pipelined multi-pool match pass (scheduler/pipeline.py): pool
        k's device solve overlaps pool k+1's host encode and pool k-1's
        finalize/launch; transactions still commit in pool order and
        launches fan out on the per-cluster executors."""
        from cook_tpu.scheduler.pipeline import (
            PipelineParams,
            match_pools_pipelined,
        )

        pools, flights = self._begin_multi_pool_cycle()
        # commit-or-drop each pool's in-flight speculation up front;
        # committed pools enter the pipelined pass pre-solved (their
        # solve ran while the PREVIOUS pass's launches drained)
        speculative = {}
        if self.speculator is not None:
            from cook_tpu.obs import data_plane

            for pool in pools:
                # per-pool scope: the commit's assignment fetch (a hit's
                # only transfer) attributes to its own cycle record
                with data_plane.activate(flights[pool.name].dp):
                    result = self._speculation_commit(
                        pool, self.pool_queues[pool.name],
                        self.pool_match_state[pool.name],
                        flights[pool.name])
                if result is not None and result.ok:
                    speculative[pool.name] = result
        outcomes = match_pools_pipelined(
            self.store, pools, self.pool_queues, self.clusters,
            self.config.match, self.pool_match_state,
            make_task_id=self._make_task_id,
            launch_filter=self._make_launch_filter(),
            record_placement_failure=self._record_placement_failure,
            host_reservations=self.host_reservations,
            host_attrs=self.host_attr_cache,
            flights=flights,
            telemetry=self.telemetry,
            encode_cache=self.encode_cache,
            recorder=self.recorder,
            params=PipelineParams(depth=self.config.pipeline_depth,
                                  async_launch=self.config.async_launch),
            predictor=self.predictor,
            speculative=speculative,
            device_state=self.device_state,
        )
        self._finish_multi_pool_cycle(pools, outcomes, flights)
        # the pass drained its async launches above (drain_launches
        # default), so every launch event has landed: speculate the next
        # pass's solves into the inter-cycle idle
        self._dispatch_speculation(pools)
        return outcomes

    def drain_launches(self, timeout: Optional[float] = None) -> bool:
        """Wait for every cluster's in-flight async launch batches."""
        from cook_tpu.cluster.base import wait_all_launches

        return not wait_all_launches(self.clusters, timeout=timeout)

    def _begin_multi_pool_cycle(self):
        """Shared prologue of the batched and pipelined multi-pool
        passes: flight builders, rank-if-missing, rank/quarantine
        credit, per-pool match state."""
        pools = [p for p in self.store.pools.values() if p.schedules_jobs]
        flights = {pool.name: self._begin_cycle(pool.name) for pool in pools}
        for pool in pools:
            if pool.name not in self.pool_queues:
                self.rank_cycle(pool)
            self._credit_rank_and_quarantine(
                flights[pool.name], pool.name, self.pool_queues[pool.name])
            state = self.pool_match_state.setdefault(
                pool.name,
                PoolMatchState(
                    num_considerable=self.config.match.max_jobs_considered),
            )
            self.admission.clamp(pool.name, state,
                                 self.config.match.max_jobs_considered)
        return pools, flights

    def _finish_multi_pool_cycle(self, pools, outcomes, flights) -> None:
        """Shared epilogue of the batched and pipelined multi-pool
        passes: per-user rate-limiter spend-through, per-pool
        queue/reservation upkeep, spare cache, record commit."""
        for pool in pools:
            outcome = outcomes[pool.name]
            # charge launches against the per-user rate limiter exactly
            # like the serial path — without the spend-through the bucket
            # refills to full burst every cycle and the configured
            # sustained rate is never enforced
            if self.launch_rate_limiter is not None:
                for job, _ in outcome.matched:
                    self.launch_rate_limiter.spend((job.user, job.pool))
            matched_uuids = {j.uuid for j, _ in outcome.matched}
            queue = self.pool_queues[pool.name]
            queue.jobs = [j for j in queue.jobs if j.uuid not in matched_uuids]
            matched_tags = matched_uuids | {
                "gang:" + j.group_uuid
                for j, _ in outcome.matched if j.group_uuid}
            if self.host_reservations:
                self.host_reservations = {
                    host: tag
                    for host, tag in self.host_reservations.items()
                    if tag not in matched_tags
                }
            self._cache_spare(pool)
            flight = flights[pool.name]
            if flight.record is not None:
                flight.record.head_matched = outcome.head_matched
            self._commit_cycle(flight)

    def _cache_spare(self, pool: Pool) -> None:
        from cook_tpu.cluster.base import scan_pool_offers

        spare: dict[str, Resources] = {}
        host_info: dict[str, tuple[dict, str]] = {}  # host -> (attrs, location)
        for cluster, offer in scan_pool_offers(self.clusters, pool.name):
            spare[offer.hostname] = Resources(
                mem=offer.mem, cpus=offer.cpus, gpus=offer.gpus,
                disk=offer.disk,
            )
            host_info[offer.hostname] = (dict(offer.attributes),
                                         cluster.location)
            self.host_attr_cache[offer.hostname] = dict(offer.attributes)
            self.host_attr_cache.move_to_end(offer.hostname)
        while len(self.host_attr_cache) > self.host_attr_cache_max:
            self.host_attr_cache.popitem(last=False)
        self.last_unmatched_offers[pool.name] = spare
        self.last_host_info = getattr(self, "last_host_info", {})
        self.last_host_info[pool.name] = host_info

    def _rebalancer_params(self) -> RebalancerParams:
        """Config-file defaults overridden by runtime-mutable dynamic
        config (reference: Datomic-resident `:rebalancer/config`,
        rebalancer.clj:535-557 — tuning preemption must not need a
        restart).  `POST /incremental-config {"rebalancer": {...}}`."""
        overrides = self.store.dynamic_config.get("rebalancer")
        base = self.config.rebalancer
        if not isinstance(overrides, dict):
            return base
        return RebalancerParams(
            safe_dru_threshold=float(overrides.get(
                "safe_dru_threshold", base.safe_dru_threshold)),
            min_dru_diff=float(overrides.get(
                "min_dru_diff", base.min_dru_diff)),
            max_preemption=int(overrides.get(
                "max_preemption", base.max_preemption)),
            fast_cycle=bool(overrides.get(
                "fast_cycle", base.fast_cycle)),
            gang_enabled=bool(overrides.get(
                "gang_enabled", base.gang_enabled)),
            gang_max_admissions=int(overrides.get(
                "gang_max_admissions", base.gang_max_admissions)),
            gang_drain_max_wait_ms=float(overrides.get(
                "gang_drain_max_wait_ms", base.gang_drain_max_wait_ms)),
            gang_drain_wasted_factor=float(overrides.get(
                "gang_drain_wasted_factor", base.gang_drain_wasted_factor)),
            resident=bool(overrides.get("resident", base.resident)),
        )

    def _rebalance_mirror(self, pool: Pool):
        """Per-pool ResidentRows mirror for the rebalancer's victim
        tensors — owned HERE so it outlives every cycle (warm reuse is
        the point; a cycle-scoped mirror would always rebuild cold)."""
        mirrors = getattr(self, "_rebalance_mirrors", None)
        if mirrors is None:
            mirrors = self._rebalance_mirrors = {}
        mirror = mirrors.get(pool.name)
        if mirror is None:
            from cook_tpu.obs import data_plane
            from cook_tpu.scheduler.device_state import ResidentRows

            mirror = ResidentRows(
                f"rebalance:{pool.name}",
                observatory=(self.telemetry.observatory
                             if self.telemetry is not None else None),
                family=data_plane.FAM_REBALANCE)
            mirrors[pool.name] = mirror
        return mirror

    def rebalance_cycle(self, pool: Pool) -> list[Decision]:
        import time as _time

        queue = self.pool_queues.get(pool.name) or self.rank_cycle(pool)
        # timer starts AFTER the queue lookup: a rank triggered here is
        # stashed in _last_rank_s and credited to the next match cycle's
        # rank phase — counting it here too would double-book the wall
        t0 = _time.perf_counter()
        spare = self.last_unmatched_offers.get(pool.name, {})
        params = self._rebalancer_params()
        decisions = rebalance_pool(
            self.store, pool, queue.jobs, spare, params,
            host_info=getattr(self, "last_host_info", {}).get(pool.name),
            telemetry=self.telemetry,
            # reclaim-before-preemption: loaned-out capacity comes home
            # (non-disruptively) before any victim search considers a kill
            reclaimer=(self.elastic.reclaim_for
                       if self.elastic is not None else None),
            resident=(self._rebalance_mirror(pool)
                      if params.resident else None),
        )
        # fairness ledger: per-victim wasted-work seconds must be read
        # BEFORE _transact_preemption flips the instances terminal (the
        # runtime destroyed is clock() - start at the kill)
        now_ms = self.store.clock()
        block_of = self._host_block_map(pool, spare)
        ledger_entries = []
        for d in decisions:
            if not d.task_ids:
                continue
            victims = []
            for v in d.victims:
                inst = self.store.instances.get(v["task_id"])
                wasted_s = 0.0
                # start_time_ms is always clock-stamped at create; 0 is
                # a REAL start under the simulator's virtual clock
                if inst is not None and not inst.status.terminal:
                    wasted_s = max(
                        0.0, (now_ms - inst.start_time_ms) / 1000.0)
                victims.append(dict(v, wasted_s=round(wasted_s, 3)))
            ledger_entries.append({
                "t_ms": now_ms,
                "preemptor_job": d.job.uuid,
                "preemptor_user": d.job.user,
                "hostname": d.hostname,
                # topology block of the freed host: the fairness
                # observatory's block-aware fragmentation groups freed
                # capacity by block (obs/fairness.py _fragmentation)
                "block": block_of.get(d.hostname, -1),
                "min_preempted_dru": d.min_preempted_dru,
                "victims": victims,
                "wasted_s": round(sum(v["wasted_s"] for v in victims), 3),
                "freed": {"mem": sum(v["mem"] for v in victims),
                          "cpus": sum(v["cpus"] for v in victims),
                          "gpus": sum(v["gpus"] for v in victims)},
            })
        fairness_rollup = self.fairness.record_decisions(
            pool.name, ledger_entries)
        if self.recorder is not None:
            by_job = {e["preemptor_job"]: e for e in ledger_entries}
            self.recorder.annotate_preemptions(
                pool.name,
                [PreemptionRecord(
                    job_uuid=d.job.uuid, hostname=d.hostname,
                    task_ids=list(d.task_ids),
                    min_preempted_dru=d.min_preempted_dru,
                    preemptor_user=d.job.user,
                    victims=by_job.get(d.job.uuid, {}).get("victims", []),
                    wasted_s=by_job.get(d.job.uuid, {}).get("wasted_s", 0.0))
                 for d in decisions if d.task_ids],
                _time.perf_counter() - t0,
                fairness=fairness_rollup if ledger_entries else None,
            )
        for decision in decisions:
            self._transact_preemption(decision)
            if len(decision.task_ids) > 1:
                # multi-task preemptions reserve the host for the job they
                # made room for, so the next match sends it there
                self.host_reservations[decision.hostname] = decision.job.uuid
        n_preempted = sum(len(d.task_ids) for d in decisions)
        self.metrics[f"rebalance.{pool.name}.preempted"] = n_preempted
        global_registry.counter(
            "rebalance.preempted",
            "tasks preempted by the rebalancer per pool").inc(
            n_preempted, {"pool": pool.name})
        self._gang_admission_cycle(pool, queue, spare)
        return decisions

    def _host_block_map(self, pool: Pool, spare: dict) -> dict[str, int]:
        """hostname -> topology block index, on the planner's reading of
        the fleet (sorted hosts chunked by the match config's block
        width) — shared by the fairness ledger stamps and gang
        admission."""
        from cook_tpu.scheduler.matcher import topology_block_width

        hostnames = sorted(
            set(spare)
            | {i.hostname for i in self.store.running_instances(pool.name)
               if i.hostname})
        npb = topology_block_width(self.config.match,
                                   max(len(hostnames), 1))
        if npb <= 0:
            npb = max(len(hostnames), 1)
        return {h: i // npb for i, h in enumerate(hostnames)}

    def _gang_admission_cycle(self, pool: Pool, queue, spare) -> list:
        """Topology-aware gang admission (scheduler/gang.py): whole-gang
        drain-vs-kill decisions riding the rebalance cycle.  Preempt-less
        admissions only reserve hosts (the block drains into the
        reservation); preempt admissions transact contiguous in-block
        victim sets like any rebalancer kill."""
        from cook_tpu.scheduler.gang import (
            GANG_RESERVATION_PREFIX,
            gang_reservation_tag,
            plan_gang_admissions,
        )
        from cook_tpu.scheduler.matcher import topology_block_width

        params = self._rebalancer_params()
        if not (params.gang_enabled and self.config.match.gang_enabled):
            return []
        waiting_groups = {
            gang_reservation_tag(j.group_uuid) for j in queue.jobs
            if j.gang_size >= 2 and j.group_uuid}
        # stale gang reservations (gang canceled / placed via another
        # pool) must not squat on hosts
        self.host_reservations = {
            host: tag for host, tag in self.host_reservations.items()
            if not tag.startswith(GANG_RESERVATION_PREFIX)
            or tag in waiting_groups}
        if not waiting_groups:
            return []
        admissions = plan_gang_admissions(
            self.store, pool, queue.jobs, spare,
            nodes_per_block=topology_block_width(
                self.config.match, max(len(spare), 1)),
            predictor=self.predictor,
            params=params,
            now_ms=self.store.clock(),
            reserved=set(self.host_reservations),
        )
        now_ms = self.store.clock()
        gang_entries = []
        for adm in admissions:
            tag = gang_reservation_tag(adm.group_uuid)
            for host in adm.hosts:
                self.host_reservations[host] = tag
            victims = []
            for task_id in adm.victims:
                inst = self.store.instances.get(task_id)
                if inst is None or inst.status.terminal:
                    continue
                job = self.store.jobs.get(inst.job_uuid)
                victims.append({
                    "task_id": task_id,
                    "user": job.user if job is not None else "",
                    "dru": 0.0,
                    "mem": job.resources.mem if job is not None else 0.0,
                    "cpus": job.resources.cpus if job is not None else 0.0,
                    "gpus": job.resources.gpus if job is not None else 0.0,
                    "wasted_s": round(max(
                        0.0, (now_ms - inst.start_time_ms) / 1000.0), 3),
                })
                self.store.update_instance_state(
                    task_id, InstanceStatus.FAILED,
                    "preempted-by-rebalancer")
                cluster = self.cluster_by_name(inst.compute_cluster)
                if cluster is not None:
                    cluster.safe_kill_task(task_id)
            if victims:
                # gang kills join the fairness ledger like any rebalancer
                # decision — block-stamped, so the block-aware
                # fragmentation stat sees the contiguous freed capacity
                gang_entries.append({
                    "t_ms": now_ms,
                    "preemptor_job": adm.leader_uuid,
                    "preemptor_user": "",
                    "hostname": ",".join(adm.hosts),
                    "block": adm.block,
                    "min_preempted_dru": 0.0,
                    "victims": victims,
                    "wasted_s": round(
                        sum(v["wasted_s"] for v in victims), 3),
                    "freed": {
                        "mem": sum(v["mem"] for v in victims),
                        "cpus": sum(v["cpus"] for v in victims),
                        "gpus": sum(v["gpus"] for v in victims)},
                })
            global_registry.counter(
                "gang.admissions",
                "gang admission decisions by the rebalance cycle per "
                "pool and mode (drain = preempt-less)").inc(
                1, {"pool": pool.name, "mode": adm.mode})
        if gang_entries:
            self.fairness.record_decisions(pool.name, gang_entries)
        self.metrics[f"rebalance.{pool.name}.gang_admissions"] = len(
            admissions)
        self.last_gang_admissions = [a.to_json() for a in admissions]
        return admissions

    def elastic_cycle(self):
        """One capacity-plane planning interval (cook_tpu/elastic/):
        rank queues feed the demand tensors, the plan commits durable
        pool-capacity deltas and converges cluster capacity.  Driven by
        a trigger loop in components.py (and the simulator); returns
        the PlanRecord (None when elastic is disabled or single-pool)."""
        if self.elastic is None:
            return None
        for pool in [p for p in self.store.pools.values()
                     if p.schedules_jobs]:
            if pool.name not in self.pool_queues:
                self.rank_cycle(pool)
        record = self.elastic.plan_cycle(self.pool_queues)
        if record is not None:
            self.metrics["elastic.last_plan"] = record.plan_id
        return record

    def _transact_preemption(self, decision: Decision) -> None:
        """transact-preemption! + safe-kill-task (rebalancer.clj:482-533)."""
        for task_id in decision.task_ids:
            inst = self.store.instances.get(task_id)
            if inst is None or inst.status.terminal:
                continue
            self.store.update_instance_state(
                task_id, InstanceStatus.FAILED, "preempted-by-rebalancer"
            )
            cluster = self.cluster_by_name(inst.compute_cluster)
            if cluster is not None:
                cluster.safe_kill_task(task_id)

    def _record_placement_failure(self, job: Job, reason: str) -> None:
        self.placement_failures[job.uuid] = reason

    def _make_launch_filter(self):
        """Considerable-job filters: per-user launch rate limit
        (pending-jobs->considerable-jobs, scheduler.clj:729) + the
        JobLaunchFilter plugins with TTL cache (plugins/launch.clj).
        Returns a per-cycle closure: the rate budget is snapshotted at
        cycle start and debited as jobs are selected, so one cycle can't
        select more launches than the bucket holds."""
        budget: dict = {}

        def launch_filter(job: Job) -> bool:
            if self.launch_rate_limiter is not None:
                key = (job.user, job.pool)
                remaining = budget.get(key)
                if remaining is None:
                    bucket = self.launch_rate_limiter._refill(key)
                    remaining = bucket.tokens
                if remaining < 1.0:
                    budget[key] = remaining
                    return False
                budget[key] = remaining - 1.0
            if not self.plugins.launch_filters:
                return True
            return self.plugins.check_launch(
                job, self.store.clock(), self._launch_filter_cache
            )

        return launch_filter

    # ------------------------------------------------------------ monitors

    def kill_lingering_tasks(self, now_ms: int) -> list[str]:
        """Max-runtime enforcement (lingering-task-killer,
        scheduler.clj:1941-1974)."""
        killed = []
        for pool_name in list(self.store.pools):
            for inst in self.store.running_instances(pool_name):
                job = self.store.jobs[inst.job_uuid]
                if job.max_runtime_ms and inst.start_time_ms + job.max_runtime_ms <= now_ms:
                    self.store.update_instance_state(
                        inst.task_id, InstanceStatus.FAILED,
                        "max-runtime-exceeded",
                    )
                    cluster = self.cluster_by_name(inst.compute_cluster)
                    if cluster is not None:
                        cluster.safe_kill_task(inst.task_id)
                    killed.append(inst.task_id)
        return killed

    def kill_stragglers(self, now_ms: int) -> list[str]:
        """Group straggler handling (straggler-handler, scheduler.clj:1976;
        docs/groups.md quantile-deviation): if a group's running task has
        run longer than `multiplier` x the `quantile` runtime of its
        completed siblings, kill it mea-culpa."""
        killed = []
        for group in self.store.groups.values():
            sh = group.straggler_handling
            if sh.type != "quantile-deviation":
                continue
            completed_ms = []
            running: list = []
            for member in group.job_uuids:
                for inst in self.store.job_instances(member):
                    if inst.status == InstanceStatus.SUCCESS:
                        completed_ms.append(inst.end_time_ms - inst.start_time_ms)
                    elif inst.status == InstanceStatus.RUNNING:
                        running.append(inst)
            if len(completed_ms) < 2:
                continue
            quantiles = statistics.quantiles(completed_ms, n=100)
            threshold = quantiles[int(sh.quantile * 100) - 1] * sh.multiplier
            for inst in running:
                if now_ms - inst.start_time_ms > threshold:
                    self.store.update_instance_state(
                        inst.task_id, InstanceStatus.FAILED, "straggler"
                    )
                    cluster = self.cluster_by_name(inst.compute_cluster)
                    if cluster is not None:
                        cluster.safe_kill_task(inst.task_id)
                    killed.append(inst.task_id)
        return killed

    def kill_cancelled_tasks(self) -> list[str]:
        """cancelled-task-killer (scheduler.clj:2000)."""
        killed = []
        for inst in list(self.store.instances.values()):
            if inst.cancelled and not inst.status.terminal:
                self.store.update_instance_state(
                    inst.task_id, InstanceStatus.FAILED, "killed-by-user"
                )
                cluster = self.cluster_by_name(inst.compute_cluster)
                if cluster is not None:
                    cluster.safe_kill_task(inst.task_id)
                killed.append(inst.task_id)
        return killed

    def reconcile(self) -> list[str]:
        """Resync store vs backends (reconcile-tasks, scheduler.clj:1828):
        store-live tasks unknown to their backend are failed mea-culpa."""
        fixed = []
        backend_known: set[str] = set()
        for cluster in self.clusters:
            running = getattr(cluster, "running", None)
            if running is not None:
                backend_known.update(running.keys())
        for inst in list(self.store.instances.values()):
            if inst.status.terminal:
                continue
            if inst.task_id not in backend_known:
                self.store.update_instance_state(
                    inst.task_id, InstanceStatus.FAILED, "task-unknown"
                )
                fixed.append(inst.task_id)
        return fixed
