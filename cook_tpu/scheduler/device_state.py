"""Device-resident match state: persistent encode tensors, O(delta) updates.

ROADMAP item 2(a): the PR-11 data-plane observatory proved that an
unchanged cached pool reports `rebuild_fraction` 0.0 on the host yet
re-transfers 100% of its node-encode and job-feasibility bytes every
cycle.  This module removes that waste: per-pool demand and feasibility
tensors live ON DEVICE across cycles, and a cycle uploads only

  * the delta rows (new jobs, invalidated feasibility rows), scattered
    into the resident buffers by donated-buffer jitted updaters
    (`ops/device_update.py` — one XLA program per padded update bucket,
    CompileObservatory-pinned);
  * the per-cycle small tensors that genuinely change every cycle
    (avail/totals/node_valid — spare amounts churn with every launch —
    plus the [J] schedule-order permutation and job_valid).

**Validity.**  A mirror is keyed by the host `EncodeCache`'s own
currency: the offer-structure fingerprint, the encode-cache epoch, and
the per-row `RowServe` report the cache emits each cycle.  A resident
row is reused ONLY when the host cache served that job's row as a HIT
at the epoch the mirror stamped on upload — so mirror correctness never
depends on observing every invalidation: a lost notification costs one
re-upload, not a stale solve.  The cache's subscriber callback
(row-dropped / epoch-bumped) frees slots and forces rebuilds eagerly.

**Rebuilds.**  Epoch bumps (quota/share/config/pool mutations), offer
structure changes, job-axis bucket growth, and dtype flips (quantized
demotion) fall back to a clean full rebuild — the classic full-upload
path, amortized away the next cycle.

**Schedule order.**  The ranked queue reorders every cycle, so resident
rows are stored in SLOT order and gathered into schedule order on
device (`gather_rows`): the permutation is the only per-cycle job-axis
upload.  The gather also produces FRESH problem tensors — the resident
buffers are private, because the next delta cycle donates them, and a
donated buffer must never alias a problem a background reader (quality
audit, speculation) may still hold.

**Quantization.**  `MatchConfig.quantized` stores the cost tensors
(demands/avail/totals) as bfloat16 — half the resident bytes and half
the delta traffic; feasibility stays bool (already minimal).  The
QualityMonitor parity guard rides the existing shadow-solve samples: a
pool whose packing-efficiency ratio drops below
`quantization_parity_floor` is demoted to f32 (mirror rebuilds at the
wider dtype) and stays demoted for the process lifetime — quantization
is an optimization, never worth re-probing into a known drift.

**DRU columns.**  The rank cycle's task columns ride the same store via
`resident_array`: content-fingerprinted whole-column reuse (an
unchanged queue re-uploads nothing; any change re-uploads that column).
"""
from __future__ import annotations

import hashlib
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

import numpy as np

from cook_tpu.obs import data_plane
from cook_tpu.scheduler.flight_recorder import NULL_CYCLE
from cook_tpu.utils.metrics import global_registry

# resident_array cache bound: (pool, column-name) keys — a handful per
# pool; the bound only matters when pools churn
MAX_RESIDENT_ARRAYS = 256


def quantized_dtype() -> np.dtype:
    """The quantized cost-tensor dtype (bfloat16 via ml_dtypes, the
    registration jax itself depends on)."""
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


class _Mirror:
    """One pool's resident buffers + slot map."""

    __slots__ = ("nodes_fp", "n_real", "n_pad", "cap", "dtype",
                 "cache_epoch", "demands", "feas", "slots", "free", "last")

    def __init__(self):
        self.nodes_fp = None
        self.n_real = 0          # UNPADDED node count: fingerprint-
        self.n_pad = 0           # collision guard (a colliding fp with a
        self.cap = 0             # different node count must rebuild)
        self.dtype = None
        self.cache_epoch = -1
        self.demands = None      # device [cap, R]
        self.feas = None         # device [cap, n_pad] bool
        # job uuid -> (row, epoch-at-upload); LRU order for eviction
        self.slots: OrderedDict[str, tuple[int, int]] = OrderedDict()
        self.free: list[int] = []
        self.last: dict = {}

    @property
    def resident_bytes(self) -> int:
        total = 0
        for buf in (self.demands, self.feas):
            if buf is not None:
                total += int(buf.nbytes)
        return total


# every live DeviceResidentState, for the /debug/device section
_REGISTRY: "weakref.WeakSet[DeviceResidentState]" = weakref.WeakSet()

# every live ResidentRows mirror (rebalancer victim tensors, elastic
# demand/capacity tensors), same debug surface
_ROW_REGISTRY: "weakref.WeakSet[ResidentRows]" = weakref.WeakSet()


def snapshot_all() -> dict:
    """The `/debug/device` device_state section: every live resident
    state's pools + guard status (normally exactly one per process),
    plus the keyed-row mirrors (`ResidentRows`: rebalancer + elastic
    tensor families)."""
    states = [state.debug_json() for state in list(_REGISTRY)]
    rows = sorted((m.debug_json() for m in list(_ROW_REGISTRY)),
                  key=lambda d: d["name"])
    return {"enabled": bool(states) or bool(rows), "states": states,
            "row_mirrors": rows}


class DeviceResidentState:
    """Per-pool device mirror of the encode cache + quantization guard.

    Thread-safety: builds run on the scheduler's driving thread; the
    encode-cache subscriber delivers invalidations from store-event
    threads — every mutation takes the state lock.
    """

    def __init__(self, encode_cache=None, observatory=None, *,
                 parity_floor: float = 0.98):
        self.encode_cache = encode_cache
        self.observatory = observatory
        self.parity_floor = parity_floor
        self._lock = threading.RLock()
        self._mirrors: dict[str, _Mirror] = {}
        # resident whole-array cache (DRU columns): (pool, name) ->
        # (content fingerprint, device array)
        self._arrays: OrderedDict[tuple, tuple] = OrderedDict()
        # resident-state epoch: bumped on cache epoch bumps and explicit
        # invalidation — the speculation guard stamps it at dispatch so
        # a commit never finalizes a problem built from dropped state
        self._epoch = 0
        # quantization guard: pools demoted to f32 after a parity breach
        self._demoted: set[str] = set()
        self._quant_armed: set[str] = set()
        if encode_cache is not None:
            encode_cache.subscribe(self._on_cache_event)
        self._resident_gauge = global_registry.gauge(
            "device_state.resident_bytes",
            "bytes of match-state tensors resident on device, per pool")
        self._delta_counter = global_registry.counter(
            "device_state.delta_rows",
            "resident-state rows updated via donated-buffer scatter, "
            "per pool")
        self._update_counter = global_registry.counter(
            "device_state.updates",
            "match cycles served by O(delta) resident-state updates, "
            "per pool")
        self._rebuild_counter = global_registry.counter(
            "device_state.rebuilds",
            "resident-state full rebuilds, per pool/reason (cold / "
            "offers-changed / epoch-bumped / bucket-growth / "
            "dtype-changed)")
        self._update_hist = global_registry.histogram(
            "device_state.update_seconds",
            "wall seconds of the per-cycle resident-state update "
            "(delta upload + scatter, or full rebuild upload)")
        self._array_counter = global_registry.counter(
            "device_state.array_reuse",
            "resident whole-array (DRU column) requests, by result")
        self._demotion_counter = global_registry.counter(
            "device_state.quant_demotions",
            "pools demoted from quantized (bf16) to f32 cost tensors by "
            "the QualityMonitor parity guard")
        _REGISTRY.add(self)

    # ---------------------------------------------------------- invalidation

    def _on_cache_event(self, kind: str, **info) -> None:
        """EncodeCache subscriber: free mirror slots / force rebuilds as
        invalidations land (correctness does not depend on this — the
        RowServe rule already refuses stale rows — but eager slot drops
        keep resident memory honest and rebuilds prompt)."""
        with self._lock:
            if kind == "epoch-bumped":
                self._epoch += 1
                for mirror in self._mirrors.values():
                    mirror.cache_epoch = -1  # next build rebuilds clean
            elif kind == "row-dropped":
                uuid = info.get("job_uuid")
                for mirror in self._mirrors.values():
                    slot = mirror.slots.pop(uuid, None)
                    if slot is not None:
                        mirror.free.append(slot[0])

    def invalidate(self) -> None:
        """Drop every mirror and resident array (tests, resync)."""
        with self._lock:
            self._epoch += 1
            self._mirrors.clear()
            self._arrays.clear()

    @property
    def epoch(self) -> int:
        """Resident-state generation, stamped into speculative dispatches
        (scheduler/prediction.py): a bump between dispatch and commit
        drops the speculation."""
        with self._lock:
            return self._epoch

    # --------------------------------------------------------- quantization

    def quantized_for(self, config, pool: str) -> bool:
        """Whether this pool's cost tensors build as bf16 this cycle;
        arms the parity guard (a pool never observed quantized must not
        be demotable by an unrelated quality dip)."""
        if not getattr(config, "quantized", False):
            return False
        with self._lock:
            if pool in self._demoted:
                return False
            self._quant_armed.add(pool)
            return True

    def note_quality(self, pool: str, ratio: float) -> None:
        """QualityMonitor sample listener: demote a quantized pool whose
        packing-efficiency parity broke the floor.  The next build
        rebuilds the mirror at f32 (dtype change)."""
        with self._lock:
            if pool not in self._quant_armed or pool in self._demoted:
                return
            if ratio >= self.parity_floor:
                return
            self._demoted.add(pool)
        self._demotion_counter.inc(1, {"pool": pool})
        import logging

        logging.getLogger(__name__).warning(
            "pool %s: quantized cost tensors broke the parity floor "
            "(%.4f < %.2f); demoting to f32", pool, ratio,
            self.parity_floor)

    def demoted_pools(self) -> list[str]:
        with self._lock:
            return sorted(self._demoted)

    # -------------------------------------------------------------- build

    def build_problem(self, pool: str, jobs, nodes, feasible: np.ndarray,
                      nodes_fp: int, served: dict, config,
                      flight=NULL_CYCLE):
        """Build the pool's padded MatchProblem from the resident mirror
        plus this cycle's delta.  `served` is the EncodeCache's RowServe
        report for the cycle (cacheable jobs only); `feasible` the fully
        assembled host mask (reservation-free — callers bypass the
        mirror when reservations mutate rows)."""
        from cook_tpu.ops.common import bucket_size
        from cook_tpu.scheduler.matcher import (
            encode_problem_arrays,
            padded_job_axis,
        )

        t0 = time.perf_counter()
        j, n = len(jobs), nodes.n
        pad_j = padded_job_axis(j, config.chunk)
        pad_n = bucket_size(max(n, 1))
        quantized = self.quantized_for(config, pool)
        dtype = quantized_dtype() if quantized else np.dtype(np.float32)
        cache_epoch = (self.encode_cache.epoch
                       if self.encode_cache is not None else 0)
        demands, avail, totals = encode_problem_arrays(jobs, nodes.offers,
                                                       config)
        with self._lock:
            try:
                return self._build_locked(
                    pool, jobs, nodes, feasible, nodes_fp, served, config,
                    flight, demands, avail, totals, j, n, pad_j, pad_n,
                    quantized, dtype, cache_epoch, t0)
            except Exception:
                # a half-applied update (e.g. the second scatter raising
                # after the first donated) must never survive: slots
                # could claim rows whose content never landed.  Drop the
                # mirror — the next cycle rebuilds cold
                self._mirrors.pop(pool, None)
                raise

    def _build_locked(self, pool, jobs, nodes, feasible, nodes_fp, served,
                      config, flight, demands, avail, totals, j, n, pad_j,
                      pad_n, quantized, dtype, cache_epoch, t0):
        """The guarded body of build_problem; the caller holds the state
        lock (re-entrant — re-taken here so the lock scope reads locally)
        and drops the pool's mirror on ANY raise."""
        from cook_tpu.ops.common import pad_to
        from cook_tpu.ops.device_update import gather_rows
        from cook_tpu.ops.match import MatchProblem

        with self._lock:
            mirror = self._mirrors.get(pool)
            rebuild = None
            if mirror is None or mirror.demands is None:
                rebuild = "cold"
            elif mirror.nodes_fp != nodes_fp:
                rebuild = "offers-changed"
            elif mirror.n_real != n or mirror.n_pad != pad_n:
                # fingerprint collision guard: a matching fp with a
                # differing node count must never serve resident rows
                rebuild = "offers-changed"
            elif mirror.cache_epoch != cache_epoch:
                rebuild = "epoch-bumped"
            elif mirror.cap < pad_j:
                rebuild = "bucket-growth"
            elif mirror.dtype != dtype:
                rebuild = "dtype-changed"

            if rebuild is None:
                stats = self._delta_update(
                    mirror, pool, jobs, demands, feasible, served,
                    cache_epoch, n, pad_n, dtype)
                if stats is None:
                    rebuild = "bucket-growth"  # slot allocation failed
            if rebuild is not None:
                mirror, stats = self._rebuild(
                    pool, jobs, demands, feasible, served, nodes_fp,
                    cache_epoch, n, pad_j, pad_n, dtype)
                stats["reason"] = rebuild
                self._rebuild_counter.inc(1, {"pool": pool,
                                              "reason": rebuild})
            else:
                self._update_counter.inc(1, {"pool": pool})
                if stats["delta_rows"]:
                    self._delta_counter.inc(stats["delta_rows"],
                                            {"pool": pool})

            # schedule-order permutation: the one per-cycle job-axis
            # upload a warm cycle pays (rows live in slot order).
            # Padded entries point at the dedicated all-zero pad row
            # (index cap), so the gathered problem is CONTENT-identical
            # to the classic build — zero demands, all-False feasibility
            # — not merely job_valid-masked
            perm = np.full(pad_j, mirror.cap, dtype=np.int32)
            perm[:j] = stats.pop("_rows")
            transient = stats.pop("_transient", ())
            mirror.free.extend(transient)
            resident_bytes = mirror.resident_bytes

        fam = data_plane.FAM_NODE_ENCODE
        perm_dev = data_plane.h2d(perm, family=fam)
        data_plane.note_padding("match", (pad_j, pad_n),
                                valid_cells=j * n,
                                padded_cells=pad_j * pad_n)
        problem = MatchProblem(
            demands=gather_rows(mirror.demands, perm_dev,
                                observatory=self.observatory),
            job_valid=data_plane.h2d(
                pad_to(np.ones(j, dtype=bool), pad_j, fill=False),
                family=fam),
            avail=data_plane.h2d(pad_to(avail.astype(dtype), pad_n),
                                 family=fam),
            totals=data_plane.h2d(pad_to(totals.astype(dtype), pad_n),
                                  family=fam),
            node_valid=data_plane.h2d(
                pad_to(np.ones(n, dtype=bool), pad_n, fill=False),
                family=fam),
            feasible=gather_rows(mirror.feas, perm_dev,
                                 observatory=self.observatory),
        )
        update_s = time.perf_counter() - t0
        stats.update(resident_bytes=resident_bytes, update_s=update_s,
                     quantized=quantized, jobs=j,
                     resident_rows=j - stats["delta_rows"])
        self._resident_gauge.set(resident_bytes, {"pool": pool})
        self._update_hist.observe(update_s)
        with self._lock:
            mirror.last = dict(stats)
        flight.note_device_state(stats)
        return problem

    def _rebuild(self, pool: str, jobs, demands, feasible, served,
                 nodes_fp: int, cache_epoch: int, n: int, pad_j: int,
                 pad_n: int, dtype) -> _Mirror:
        """Clean full rebuild: fresh buffers, every row uploaded (the
        classic full-transfer cycle — amortized away from the next cycle
        on).  Caller holds the lock."""
        from cook_tpu.ops.common import pad_to

        j = len(jobs)
        cap = max(pad_j, 1)
        mirror = _Mirror()
        mirror.nodes_fp = nodes_fp
        mirror.n_real = n
        mirror.n_pad = pad_n
        mirror.cap = cap
        mirror.dtype = dtype
        mirror.cache_epoch = cache_epoch
        # cap + 1 rows: the LAST row is the dedicated all-zero pad row
        # padded perm entries gather (never allocated, never scattered),
        # so padded problem rows read zero demands / all-False rows
        # exactly like the classic build's
        feas_buf = np.zeros((cap + 1, pad_n), dtype=bool)
        feas_buf[:j, :n] = feasible[:j, :n]
        mirror.demands = data_plane.h2d(
            pad_to(demands.astype(dtype), cap + 1),
            family=data_plane.FAM_NODE_ENCODE)
        mirror.feas = data_plane.h2d(feas_buf,
                                     family=data_plane.FAM_FEASIBILITY)
        rows = []
        for ji, job in enumerate(jobs):
            serve = served.get(job.uuid) if served is not None else None
            if serve is not None and serve.cached:
                mirror.slots[job.uuid] = (ji, serve.epoch)
            rows.append(ji)
        occupied = {row for row, _ in mirror.slots.values()}
        mirror.free = [row for row in range(cap) if row not in occupied]
        self._mirrors[pool] = mirror
        return mirror, {"rebuild": True, "delta_rows": j, "_rows": rows,
                        "_transient": []}

    def _delta_update(self, mirror: _Mirror, pool: str, jobs, demands,
                      feasible, served, cache_epoch: int, n: int,
                      pad_n: int, dtype) -> Optional[dict]:
        """Apply this cycle's O(delta) row updates to a valid mirror.
        Returns the build stats (with the schedule-order row list), or
        None when slot allocation is impossible (forces a rebuild).
        Caller holds the lock."""
        from cook_tpu.ops.device_update import scatter_rows

        j = len(jobs)
        window = {job.uuid for job in jobs}
        rows = [0] * j
        delta_ji: list[int] = []
        delta_rows: list[int] = []
        transient: list[int] = []

        def allocate() -> Optional[int]:
            if mirror.free:
                return mirror.free.pop()
            for uuid in mirror.slots:  # oldest first (LRU order)
                if uuid not in window:
                    row, _ = mirror.slots.pop(uuid)
                    return row
            return None

        for ji, job in enumerate(jobs):
            serve = served.get(job.uuid) if served is not None else None
            slot = mirror.slots.get(job.uuid)
            if (serve is not None and not serve.fresh and slot is not None
                    and slot[1] == serve.epoch):
                # resident hit: the host cache served this row unchanged
                # at the epoch we uploaded it — zero bytes move
                rows[ji] = slot[0]
                mirror.slots.move_to_end(job.uuid)
                continue
            if slot is not None:
                row = slot[0]
            else:
                row = allocate()
                if row is None:
                    return None
            rows[ji] = row
            delta_ji.append(ji)
            delta_rows.append(row)
            if serve is not None and serve.cached:
                mirror.slots[job.uuid] = (row, serve.epoch)
                mirror.slots.move_to_end(job.uuid)
            else:
                # transient row (group job, uncacheable serve): freed
                # after the gather — its content is this cycle's only
                mirror.slots.pop(job.uuid, None)
                transient.append(row)

        if delta_ji:
            idx = np.asarray(delta_rows, dtype=np.int32)
            dem_rows = demands[delta_ji].astype(dtype)
            feas_rows = np.zeros((len(delta_ji), pad_n), dtype=bool)
            feas_rows[:, :n] = feasible[delta_ji][:, :n]
            mirror.demands = scatter_rows(
                mirror.demands, idx, dem_rows,
                family=data_plane.FAM_NODE_ENCODE,
                observatory=self.observatory)
            mirror.feas = scatter_rows(
                mirror.feas, idx, feas_rows,
                family=data_plane.FAM_FEASIBILITY,
                observatory=self.observatory)
        return {"rebuild": False, "reason": "",
                "delta_rows": len(delta_ji), "_rows": rows,
                "_transient": transient}

    # ----------------------------------------------------- resident arrays

    def resident_array(self, pool: str, name: str, host_array: np.ndarray,
                       family: Optional[str] = None):
        """Content-fingerprinted whole-array residency (DRU columns):
        returns the resident device copy when the host content is
        byte-identical to the last upload, else uploads and replaces.
        The returned array is shared across cycles — callers must treat
        it as immutable kernel INPUT (never donate it)."""
        arr = np.ascontiguousarray(host_array)
        fp = (arr.shape, str(arr.dtype),
              hashlib.blake2b(arr.tobytes(), digest_size=16).digest())
        key = (pool, name)
        with self._lock:
            entry = self._arrays.get(key)
            if entry is not None and entry[0] == fp:
                self._arrays.move_to_end(key)
                dev = entry[1]
            else:
                dev = None
        if dev is not None:
            self._array_counter.inc(1, {"result": "hit"})
            return dev
        dev = data_plane.h2d(arr, family=family or data_plane.FAM_DRU)
        with self._lock:
            self._arrays[key] = (fp, dev)
            self._arrays.move_to_end(key)
            while len(self._arrays) > MAX_RESIDENT_ARRAYS:
                self._arrays.popitem(last=False)
        self._array_counter.inc(1, {"result": "miss"})
        return dev

    # -------------------------------------------------------------- debug

    def debug_json(self) -> dict:
        with self._lock:
            pools = {}
            for name, mirror in self._mirrors.items():
                pools[name] = {
                    "resident_bytes": mirror.resident_bytes,
                    "cap": mirror.cap,
                    "n_pad": mirror.n_pad,
                    "slots": len(mirror.slots),
                    "dtype": str(mirror.dtype) if mirror.dtype else "",
                    "cache_epoch": mirror.cache_epoch,
                    "last": dict(mirror.last),
                }
            arrays = {}
            for (pool, name), (fp, dev) in self._arrays.items():
                arrays.setdefault(pool, {})[name] = int(dev.nbytes)
            return {
                "epoch": self._epoch,
                "quantized_demoted": sorted(self._demoted),
                "pools": pools,
                "resident_arrays": arrays,
            }


class ResidentRows:
    """Content-addressed keyed-row device mirror for cycle-built tensor
    families — the rebalancer's victim tensors and the elastic planner's
    demand rows, which PR 11's ledger showed rebuilding from host state
    on every dispatch.

    The match mirror above keys row validity on the host EncodeCache's
    RowServe report; these families have no host cache, so content
    addressing IS the serve report: each key's row is fingerprinted over
    the concatenated column bytes, and a row whose fingerprint matches
    the resident copy moves ZERO bytes (the RowServe hit-rule analog —
    a stale fingerprint can only cost a re-upload, never a stale solve).
    Deltas ride the same donated-buffer bucket-padded scatters
    (`ops/device_update.scatter_rows`), and the per-cycle row order is a
    device gather through a FINGERPRINT-CACHED permutation — an
    unchanged layout re-uploads neither rows nor the perm, so a warm
    dispatch's encode H2D is ~0 against the cold rebuild's 1.0.

    Rebuild-reason ladder (stamped like the match mirror's):
    `cold` (no buffers), `width-changed` (column set / trailing shape /
    dtype differs — the offers-changed/dtype-changed analog; e.g. the
    elastic queue bucket growing), `bucket-growth` (key count outgrew
    the row bucket, or slot allocation failed).

    Like `_Mirror`, buffers carry cap + 1 rows with a dedicated all-zero
    pad row at index cap: out-of-window output rows gather zeros, so
    integer columns that need a -1 pad encode value+1 and subtract on
    device after the gather (the rebalancer's task->host column).
    """

    def __init__(self, name: str, observatory=None,
                 family: Optional[str] = None):
        self.name = name
        self.observatory = observatory
        self.family = family or data_plane.FAM_OTHER
        self._lock = threading.RLock()
        self._names: tuple = ()
        self._widths: dict = {}
        self._buffers: Optional[dict] = None   # name -> device [cap+1,...]
        self._cap = 0
        # key -> (slot row, content fingerprint); LRU order for eviction
        self._slots: OrderedDict = OrderedDict()
        self._free: list[int] = []
        # (perm-bytes fp) -> device perm: the gather permutation is the
        # warm cycle's only other job-axis upload, and it is ~stable —
        # uncached it would be a double-digit share of the cold bytes
        self._perm_cache: Optional[tuple] = None
        self._arrays: OrderedDict = OrderedDict()  # whole-array cache
        self.last: dict = {}
        # the match mirror's metric families, pool-labelled by mirror
        # name (the registry is idempotent on names)
        self._resident_gauge = global_registry.gauge(
            "device_state.resident_bytes")
        self._delta_counter = global_registry.counter(
            "device_state.delta_rows")
        self._update_counter = global_registry.counter(
            "device_state.updates")
        self._rebuild_counter = global_registry.counter(
            "device_state.rebuilds")
        self._update_hist = global_registry.histogram(
            "device_state.update_seconds")
        self._array_counter = global_registry.counter(
            "device_state.array_reuse")
        _ROW_REGISTRY.add(self)

    # ------------------------------------------------------------- build

    @staticmethod
    def _row_fp(columns: dict, names: tuple, i: int) -> bytes:
        h = hashlib.blake2b(digest_size=16)
        for name in names:
            h.update(columns[name][i].tobytes())
        return h.digest()

    def build(self, keys, columns: dict, out_len: int,
              flight=NULL_CYCLE) -> tuple[dict, dict]:
        """Serve this cycle's tensors from the mirror plus the delta.

        `keys`: one hashable identity per row (task id, pool name), in
        this cycle's row order.  `columns`: {name: host [K, ...] array},
        all sharing the row axis.  `out_len`: padded output row count —
        rows beyond len(keys) gather the all-zero pad row.

        Returns ({name: FRESH device [out_len, ...] array}, stats) with
        the match-mirror stats schema (rebuild/reason/delta_rows/...).
        """
        from cook_tpu.ops.common import bucket_size
        from cook_tpu.ops.device_update import gather_rows

        t0 = time.perf_counter()
        k = len(keys)
        names = tuple(sorted(columns))
        cols = {name: np.ascontiguousarray(columns[name])
                for name in names}
        widths = {name: (cols[name].shape[1:], str(cols[name].dtype))
                  for name in names}
        pad_k = bucket_size(max(k, 1))
        fps = [self._row_fp(cols, names, i) for i in range(k)]
        with self._lock:
            rebuild = None
            if self._buffers is None:
                rebuild = "cold"
            elif self._names != names or self._widths != widths:
                rebuild = "width-changed"
            elif self._cap < pad_k:
                rebuild = "bucket-growth"
            if rebuild is None:
                stats = self._delta_locked(keys, fps, cols, names)
                if stats is None:
                    rebuild = "bucket-growth"
            if rebuild is not None:
                stats = self._rebuild_locked(keys, fps, cols, names,
                                             widths, pad_k)
                stats["reason"] = rebuild
                self._rebuild_counter.inc(1, {"pool": self.name,
                                              "reason": rebuild})
            else:
                self._update_counter.inc(1, {"pool": self.name})
                if stats["delta_rows"]:
                    self._delta_counter.inc(stats["delta_rows"],
                                            {"pool": self.name})
            perm = np.full(out_len, self._cap, dtype=np.int32)
            perm[:k] = stats.pop("_rows")
            resident_bytes = sum(int(b.nbytes)
                                 for b in self._buffers.values())

            perm_fp = hashlib.blake2b(perm.tobytes(),
                                      digest_size=16).digest()
            cached = self._perm_cache
            if cached is not None and cached[0] == perm_fp:
                perm_dev = cached[1]
            else:
                perm_dev = data_plane.h2d(perm, family=self.family)
                self._perm_cache = (perm_fp, perm_dev)
            out = {
                name: gather_rows(self._buffers[name], perm_dev,
                                  observatory=self.observatory,
                                  op=f"{self.name}_gather")
                for name in names
            }
        update_s = time.perf_counter() - t0
        stats.update(resident_bytes=resident_bytes, update_s=update_s,
                     quantized=False, jobs=k,
                     resident_rows=k - stats["delta_rows"])
        self._resident_gauge.set(resident_bytes, {"pool": self.name})
        self._update_hist.observe(update_s)
        with self._lock:
            self.last = dict(stats)
        flight.note_device_state(stats)
        return out, stats

    def _rebuild_locked(self, keys, fps, cols, names, widths,
                        pad_k: int) -> dict:
        from cook_tpu.ops.common import pad_to

        k = len(keys)
        cap = max(pad_k, 1)
        self._names = names
        self._widths = widths
        self._cap = cap
        self._slots = OrderedDict()
        # cap + 1 rows, all-zero pad row at index cap (see class doc)
        self._buffers = {
            name: data_plane.h2d(pad_to(cols[name], cap + 1),
                                 family=self.family)
            for name in names
        }
        rows = list(range(k))
        for i, key in enumerate(keys):
            self._slots[key] = (i, fps[i])
        self._free = list(range(k, cap))
        return {"rebuild": True, "delta_rows": k, "_rows": rows}

    def _delta_locked(self, keys, fps, cols, names) -> Optional[dict]:
        from cook_tpu.ops.device_update import scatter_rows

        window = set(keys)
        rows = [0] * len(keys)
        delta_i: list[int] = []
        delta_rows: list[int] = []

        def allocate():
            if self._free:
                return self._free.pop()
            for key in self._slots:  # oldest first (LRU order)
                if key not in window:
                    row, _ = self._slots.pop(key)
                    return row
            return None

        for i, key in enumerate(keys):
            slot = self._slots.get(key)
            if slot is not None and slot[1] == fps[i]:
                # content hit: the resident row is byte-identical
                rows[i] = slot[0]
                self._slots.move_to_end(key)
                continue
            if slot is not None:
                row = slot[0]
            else:
                row = allocate()
                if row is None:
                    return None
            rows[i] = row
            self._slots[key] = (row, fps[i])
            self._slots.move_to_end(key)
            delta_i.append(i)
            delta_rows.append(row)

        if delta_i:
            idx = np.asarray(delta_rows, dtype=np.int32)
            for name in names:
                self._buffers[name] = scatter_rows(
                    self._buffers[name], idx, cols[name][delta_i],
                    family=self.family, observatory=self.observatory,
                    op=f"{self.name}_update")
        return {"rebuild": False, "reason": "",
                "delta_rows": len(delta_i), "_rows": rows}

    # ----------------------------------------------------- whole arrays

    def whole_array(self, name: str, host_array: np.ndarray):
        """Content-fingerprinted whole-array residency for the tensors
        with no row identity (the rebalancer's spare/host_ok, the
        elastic supply/outstanding/pool_valid): byte-identical content
        re-uploads nothing.  Returned arrays are shared across cycles —
        kernel INPUT only, never donate them."""
        arr = np.ascontiguousarray(host_array)
        fp = (arr.shape, str(arr.dtype),
              hashlib.blake2b(arr.tobytes(), digest_size=16).digest())
        key = (self.name, name)
        with self._lock:
            entry = self._arrays.get(key)
            if entry is not None and entry[0] == fp:
                self._arrays.move_to_end(key)
                dev = entry[1]
            else:
                dev = None
        if dev is not None:
            self._array_counter.inc(1, {"result": "hit"})
            return dev
        dev = data_plane.h2d(arr, family=self.family)
        with self._lock:
            self._arrays[key] = (fp, dev)
            self._arrays.move_to_end(key)
            while len(self._arrays) > MAX_RESIDENT_ARRAYS:
                self._arrays.popitem(last=False)
        self._array_counter.inc(1, {"result": "miss"})
        return dev

    def invalidate(self) -> None:
        """Drop the mirror (tests, resync): next build rebuilds cold."""
        with self._lock:
            self._buffers = None
            self._slots = OrderedDict()
            self._free = []
            self._perm_cache = None
            self._arrays.clear()

    # -------------------------------------------------------------- debug

    def debug_json(self) -> dict:
        with self._lock:
            resident_bytes = (sum(int(b.nbytes)
                                  for b in self._buffers.values())
                              if self._buffers else 0)
            return {
                "name": self.name,
                "family": self.family,
                "resident_bytes": resident_bytes,
                "cap": self._cap,
                "columns": {name: {"shape": list(shape),
                                   "dtype": dtype}
                            for name, (shape, dtype)
                            in self._widths.items()},
                "slots": len(self._slots),
                "arrays": {name: int(dev.nbytes)
                           for (_, name), (fp, dev)
                           in self._arrays.items()},
                "last": dict(self.last),
            }
