"""The match cycle: ranked queue + offers -> kernel solve -> launches.

Reference: `handle-fenzo-pool` / `handle-resource-offers!` / `launch-matched-
tasks!` (/root/reference/scheduler/src/cook/scheduler/scheduler.clj:617-1651)
with the Fenzo solve replaced by the `ops.match` kernels, plus:

  * considerable-job selection with per-cycle cap and quota filtering
    (`pending-jobs->considerable-jobs`, scheduler.clj:729);
  * head-of-queue fairness backoff — if the queue head fails to match, the
    next cycle considers 5% fewer jobs, floored; a matched head resets the
    cap (scheduler.clj:1613-1651);
  * launch transactions with the allowed-to-start precondition, then backend
    launch under the cluster's kill-lock read side (scheduler.clj:962-1048);
  * placement-failure bookkeeping for /unscheduled_jobs
    (fenzo_utils.clj/record-placement-failures!).
"""
from __future__ import annotations

import logging
import math
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from cook_tpu import faults
from cook_tpu.cluster.base import ComputeCluster, Offer, TaskSpec
from cook_tpu.models.entities import (
    GroupPlacementType,
    InstanceStatus,
    Job,
    JobState,
    Pool,
)
from cook_tpu.models.store import JobStore, TransactionVetoed
from cook_tpu.obs import data_plane
from cook_tpu.obs.compile_observatory import shape_signature
from cook_tpu.ops.common import (
    PendingResult,
    bucket_size,
    fetch_result,
    pad_to,
)
from cook_tpu.ops.gang import (
    np_block_free_hosts,
    np_gang_filter,
    np_gang_repair,
)
from cook_tpu.ops.match import (
    MatchProblem,
    backend_flags,
    chunked_match,
    greedy_match,
    vmap_safe_backend,
)
from cook_tpu.scheduler.constraints import (
    MISSING_ATTR,
    EncodedNodes,
    balanced_group_topup,
    encode_nodes,
    feasibility_mask,
    validate_group_assignments,
)
from cook_tpu.scheduler import flight_recorder as flight_codes
from cook_tpu.scheduler.flight_recorder import NULL_CYCLE
from cook_tpu.scheduler.ranking import QuotaWalk, RankedQueue
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)


@dataclass
class MatchConfig:
    """Fenzo-knob equivalents (reference config.clj:108-116)."""

    max_jobs_considered: int = 1000
    scaleback: float = 0.95
    floor_iterations_before_reset: int = 1000000
    chunk: int = 0           # 0 = exact sequential greedy kernel
    # chunked-matcher knobs; defaults are the r2 TPU sweep's best config
    # with packing efficiency >= 1.0 vs sequential greedy (tpu_sweep_r2:
    # 552 ms @ 100k x 10k, eff 1.0044 — see docs/status.md)
    chunk_rounds: int = 3
    chunk_passes: int = 2    # candidate recomputes per chunk
    chunk_kc: int = 128      # candidate-list width per job
    # "xla" (approx_max_k candidate lists), "pallas" (fused
    # feasibility+fitness+argmax kernel, ops/pallas_match.py), or
    # "bucketed" (class-shared candidate lists + exact cleanup pass)
    backend: str = "xla"
    # every Nth chunked solve is replayed through the exact sequential-
    # greedy kernel and the packing ratio gauged (match.quality_audit) —
    # the runtime guard that tuned approximate configs keep >= 0.99
    # packing parity on the REAL workload, not just the sweep shape.
    # 0 disables; irrelevant when chunk=0 (the exact kernel is in use).
    quality_audit_every: int = 50
    # estimated-completion constraint (constraints.clj:385 +
    # estimated-completion-config): 0 multiplier or lifetime = disabled
    completion_multiplier: float = 0.0
    host_lifetime_mins: float = 0.0
    agent_start_grace_mins: float = 10.0
    # extra memory a checkpointing job consumes for its tooling, applied
    # at MATCH time (demands + TaskSpec) so placement and the launched
    # pod agree — padding only in the backend would direct-bind pods the
    # kubelet must reject (calculate-effective-resources, api.clj:1152)
    checkpoint_memory_overhead_mb: float = 0.0
    # device-solve fallback (docs/resilience.md): when a pool's solve
    # raises — or its latency regresses past device_latency_guard x the
    # rolling baseline — the pool degrades to the host-side
    # ops/cpu_reference.np_greedy_match for this many cycles (health
    # reason `device-degraded`), then probes the device again.  The
    # failing cycle itself is re-solved on CPU, so no cycle is lost to a
    # sick device.  0 disables the reaction (a solve error propagates
    # as before).
    device_fallback_cycles: int = 8
    # latency guard ratio over the rolling median baseline (0 = latency
    # never triggers fallback; solve errors still do)
    device_latency_guard: float = 0.0
    # hierarchical two-level matcher (ops/hierarchical.py): when a pool's
    # padded jobs x nodes product reaches this threshold, the solve
    # decomposes into topology blocks — coarse jobs x blocks assignment,
    # then every block's fine problem batched over the block axis (the
    # axis parallel/mesh.py shards), plus bounded refinement.  0 disables
    # (the flat kernels remain the only path).  Reached via
    # SchedulerConfig.match.hierarchical_threshold.
    hierarchical_threshold: int = 0
    # block geometry overrides; 0 = auto from the tuned buckets
    # (ops/hierarchical.NODE_BLOCK_BUCKETS / block_slack)
    hierarchical_nodes_per_block: int = 0
    hierarchical_jobs_per_block: int = 0
    hierarchical_refine_rounds: int = 2
    # superblock (DCN-domain) layer above the topology blocks: nodes per
    # superblock (rounded up to a power-of-two number of blocks).  0
    # disables; engages only when the pool spans >= 2 superblocks.  The
    # coarse level then splits into super-coarse jobs x superblocks plus
    # per-superblock jobs x blocks batched on the mesh axis — the
    # mega-scale (1M x 100k) decomposition.  Config key:
    # `hier_superblock_nodes`.
    hierarchical_superblock_nodes: int = 0
    # coarse block-scoring backend: "xla" (masked chunked kernel) or
    # "pallas" (fused ops/pallas_match.best_block; quality-guarded)
    hierarchical_coarse_backend: str = "xla"
    # shard the fine batch's block axis over the device mesh when the
    # process holds more than one device
    hierarchical_use_mesh: bool = True
    # fine-solve backend: "xla" (vmapped chunked kernel, mesh-shardable)
    # or "pallas" (ops/pallas_match.best_node_batched — the fused
    # fit+fitness+argmax scorer owning the block axis natively, so the
    # hierarchical inner loop stops depending on XLA fusion luck;
    # single-process only — the fused kernel is not mesh-sharded)
    hierarchical_fine_backend: str = "xla"
    # device-resident match state (scheduler/device_state.py): per-pool
    # demand/feasibility tensors stay on device across cycles; unchanged
    # rows move ZERO bytes, deltas apply via donated-buffer scatters.
    # Off by default — enable per deployment after reading
    # docs/operations.md "Reading rebuild_fraction and resident bytes"
    device_residency: bool = False
    # quantized cost tensors: demands/avail/totals cross (and stay
    # resident) as bfloat16 — half the bytes; feasibility is already
    # bool.  Guarded by the QualityMonitor parity floor below: a pool
    # whose packing efficiency drifts under it demotes to f32
    quantized: bool = False
    quantization_parity_floor: float = 0.98
    # gang scheduling (ops/gang.py + scheduler/gang.py): jobs submitted
    # with gang_size=k place all-or-nothing — k distinct hosts inside
    # ONE topology block on the hierarchical path (the fine pass's
    # group-sum filter), whole-pool all-or-nothing on the flat paths
    # (which have no block structure; np_gang_filter in
    # finalize_pool_match is the single enforcement chokepoint either
    # way).  Disabling treats gang members as independent jobs.
    gang_enabled: bool = True
    # topology distance term: additive per-node score bonus
    # (MatchProblem.node_bonus) proportional to the node's block
    # utilization, so placements co-locate into already-warm topology
    # blocks even for non-gang jobs — keeping whole blocks free for
    # gangs.  0 disables (the pre-gang XLA programs stay byte-identical);
    # binpack fitness is ~[0, 1], so weights ~0.1-0.5 bias without
    # drowning the packing signal.
    topology_weight: float = 0.0
    # block width (hosts) for the distance term; 0 = the hierarchical
    # decomposition's tuned bucket (ops/hierarchical.NODE_BLOCK_BUCKETS)
    topology_block_hosts: int = 0

    def __post_init__(self):
        backend_flags(self.backend)  # raises on unknown names
        if self.hierarchical_coarse_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown hierarchical coarse backend "
                f"{self.hierarchical_coarse_backend!r} "
                "(expected xla | pallas)")
        if self.hierarchical_fine_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown hierarchical fine backend "
                f"{self.hierarchical_fine_backend!r} "
                "(expected xla | pallas)")
        if self.backend == "bucketed" and 0 < self.chunk and \
                self.chunk_passes < 2:
            # the solve-time guard in ops/match.py would only fire on the
            # first real match cycle; fail at config-parse time instead
            raise ValueError(
                "backend 'bucketed' requires chunk_passes >= 2 (the final "
                "pass is the exact per-job cleanup)")


@dataclass
class PoolMatchState:
    """Mutable per-pool matcher state (head-of-queue backoff + device
    fallback)."""

    num_considerable: int
    iterations_at_floor: int = 0
    chunked_solves: int = 0  # drives the periodic quality audit
    # device-solve fallback: cycles left on the CPU reference solver
    # before the next device probe; reason kept until a probe succeeds
    fallback_cycles_left: int = 0
    fallback_reason: str = ""


@dataclass
class MatchOutcome:
    matched: list[tuple[Job, Offer]] = field(default_factory=list)
    launched_task_ids: list[str] = field(default_factory=list)
    unmatched: list[Job] = field(default_factory=list)
    offers_total: int = 0
    head_matched: bool = True


def select_considerable(
    store: JobStore,
    pool: Pool,
    queue: RankedQueue,
    limit: int,
    *,
    launch_filter: Optional[Callable[[Job], bool]] = None,
) -> list[Job]:
    """Head of the ranked queue, re-filtered against LIVE per-user quota
    and usage, then plugin-filtered, capped at `limit` (scheduler.clj:729
    `pending-jobs->considerable-jobs` + tools.clj:961
    `filter-pending-jobs-for-quota`).

    The rank cycle already quota-capped the queue, but that snapshot is
    up to one rank interval old — launches, completions, and quota
    changes since then must be honored here or a user can exceed quota by
    a rank interval's worth of matches.  Filter order mirrors the
    reference: quota admission consumes the user's budget even for jobs a
    later filter rejects (the reference threads usage state through the
    whole stream before its other filters)."""
    walk = QuotaWalk(store, pool.name)
    out = []
    for job in queue.jobs:
        # stale-queue liveness: a job killed/launched since the rank tick
        # must neither be matched nor consume the user's quota budget
        live = store.jobs.get(job.uuid)
        if live is None or live.state is not JobState.WAITING:
            continue
        if not walk.admit(job):
            continue
        if launch_filter is not None and not launch_filter(job):
            continue
        out.append(job)
        if len(out) >= limit:
            break
    return out


def job_mem_with_overhead(job: Job, config: "MatchConfig") -> float:
    """Effective memory demand: checkpointing jobs carry the tooling
    overhead from match time onward."""
    mem = job.resources.mem
    if job.checkpoint is not None and job.checkpoint.mode:
        mem += config.checkpoint_memory_overhead_mb
    return mem


def gang_context(
    considerable: Sequence[Job], config: "MatchConfig",
) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """(gang_id [J] int32, gang_need [J] int32) for this cycle's
    considerable window, or (None, None) when no gang rows are present
    (the gang-free fast path — no extra arrays, no extra XLA programs).
    gang_id is a dense per-cycle index over the distinct gang groups;
    members outside the window simply don't appear, so an under-
    represented gang (quota cap, queue cap) strips at the chokepoint
    with a members-missing detail instead of partially placing."""
    if not config.gang_enabled:
        return None, None
    ids: dict[str, int] = {}
    gang_id = np.full(len(considerable), -1, dtype=np.int32)
    gang_need = np.zeros(len(considerable), dtype=np.int32)
    for ji, job in enumerate(considerable):
        if job.gang_size >= 2 and job.group_uuid:
            gang_id[ji] = ids.setdefault(job.group_uuid, len(ids))
            gang_need[ji] = job.gang_size
    if not ids:
        return None, None
    return gang_id, gang_need


def topology_block_width(config: "MatchConfig", n_nodes: int) -> int:
    """Block width (hosts) the topology distance term uses: the explicit
    override, else the hierarchical decomposition's tuned bucket — so
    the distance term and the gang block rule agree on what "one block"
    means when both are active."""
    if config.topology_block_hosts:
        return config.topology_block_hosts
    from cook_tpu.ops.hierarchical import choose_nodes_per_block

    return choose_nodes_per_block(max(n_nodes, 1))


def topology_bonus(nodes: EncodedNodes,
                   config: "MatchConfig") -> Optional[np.ndarray]:
    """Per-node additive score bonus [N] float32 (None when disabled):
    topology_weight x the node's block mem utilization.  Warmer blocks
    attract placements, so scalar jobs pack into partially-used blocks
    and whole blocks stay free for gangs — the node-topology distance
    term of the cost tensor (fitness is within-node utilization; this
    adds the across-block dimension)."""
    if config.topology_weight <= 0 or nodes.n == 0:
        return None
    npb = topology_block_width(config, nodes.n)
    avail_mem = np.array([o.mem for o in nodes.offers], dtype=np.float32)
    total_mem = np.array([max(o.total_mem or o.mem, 1e-9)
                          for o in nodes.offers], dtype=np.float32)
    util = np.clip(1.0 - avail_mem / total_mem, 0.0, 1.0)
    bonus = np.empty(nodes.n, dtype=np.float32)
    for start in range(0, nodes.n, npb):
        seg = slice(start, min(start + npb, nodes.n))
        bonus[seg] = util[seg].mean()
    return (config.topology_weight * bonus).astype(np.float32)


def encode_problem_arrays(
    jobs: Sequence[Job],
    offers: Sequence,
    config: Optional["MatchConfig"] = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(demands[j,4], avail[n,4], totals[n,2]) float32 rows — the one
    resource encoding shared by the device problem build and the
    host-side fallback solve (their parity claim starts here)."""
    demands = np.zeros((len(jobs), 4), dtype=np.float32)
    for i, job in enumerate(jobs):
        r = job.resources
        mem = (job_mem_with_overhead(job, config)
               if config is not None else r.mem)
        demands[i] = (mem, r.cpus, r.gpus, r.disk)
    avail = np.zeros((len(offers), 4), dtype=np.float32)
    totals = np.zeros((len(offers), 2), dtype=np.float32)
    for i, o in enumerate(offers):
        avail[i] = (o.mem, o.cpus, o.gpus, o.disk)
        totals[i] = (o.total_mem or o.mem, o.total_cpus or o.cpus)
    return demands, avail, totals


def padded_job_axis(j: int, chunk: int = 0) -> int:
    """Padded job-axis size of a match problem: the power-of-two bucket,
    rounded up to a chunk multiple when the chunked matcher is in use.
    ONE definition shared by the classic tensor build and the device-
    resident mirror (their problems must land on identical shapes)."""
    pad_j = bucket_size(max(j, 1))
    if chunk:
        pad_j = max(pad_j, chunk)
        pad_j += (-pad_j) % chunk
    return pad_j


def build_match_problem(
    jobs: Sequence[Job],
    nodes: EncodedNodes,
    feasible: np.ndarray,
    *,
    chunk: int = 0,
    config: Optional["MatchConfig"] = None,
    quantized: bool = False,
) -> MatchProblem:
    j, n = len(jobs), nodes.n
    pad_j = padded_job_axis(j, chunk)
    pad_n = bucket_size(max(n, 1))
    demands, avail, totals = encode_problem_arrays(jobs, nodes.offers,
                                                   config)
    if quantized:
        # bf16 cost tensors (MatchConfig.quantized): half the transfer
        # bytes; parity guarded by the QualityMonitor demotion ladder
        from cook_tpu.scheduler.device_state import quantized_dtype

        dtype = quantized_dtype()
        demands = demands.astype(dtype)
        avail = avail.astype(dtype)
        totals = totals.astype(dtype)
    feas = np.zeros((pad_j, pad_n), dtype=bool)
    feas[:j, :n] = feasible
    # data-plane accounting: the padded host arrays are what cross to
    # the device (data_plane.h2d = jnp.asarray + ledger note), split by
    # tensor family; the padded-vs-valid cell ratio is the bucket waste
    h2d = data_plane.h2d
    data_plane.note_padding("match", (pad_j, pad_n),
                            valid_cells=j * n,
                            padded_cells=pad_j * pad_n)
    return MatchProblem(
        demands=h2d(pad_to(demands, pad_j),
                    family=data_plane.FAM_NODE_ENCODE),
        job_valid=h2d(pad_to(np.ones(j, dtype=bool), pad_j, fill=False),
                      family=data_plane.FAM_NODE_ENCODE),
        avail=h2d(pad_to(avail, pad_n), family=data_plane.FAM_NODE_ENCODE),
        totals=h2d(pad_to(totals, pad_n),
                   family=data_plane.FAM_NODE_ENCODE),
        node_valid=h2d(pad_to(np.ones(n, dtype=bool), pad_n, fill=False),
                       family=data_plane.FAM_NODE_ENCODE),
        feasible=h2d(feas, family=data_plane.FAM_FEASIBILITY),
    )


def problem_shape(problem: MatchProblem) -> tuple[int, int]:
    """(padded jobs, padded nodes) — the solve's XLA-visible shape."""
    return (int(problem.demands.shape[0]), int(problem.avail.shape[0]))


def solve_backend(config: "MatchConfig") -> str:
    """The backend label telemetry/records report for a solve under this
    config: the candidate-pass backend for the chunked matcher, "exact"
    for the chunk=0 sequential-greedy kernel (a distinct XLA program)."""
    return config.backend if config.chunk else "exact"


def hierarchical_enabled(config: "MatchConfig",
                         problem: MatchProblem) -> bool:
    """Automatic two-level path: padded jobs x nodes at/over the
    configured threshold (0 = never)."""
    if config.hierarchical_threshold <= 0:
        return False
    j, n = problem_shape(problem)
    return j * n >= config.hierarchical_threshold


def hier_params_from_config(config: "MatchConfig"):
    """MatchConfig -> ops/hierarchical.HierParams (the chunked-matcher
    knobs carry over so the fine solve uses the pool's tuned config)."""
    from cook_tpu.ops.hierarchical import HierParams

    return HierParams(
        nodes_per_block=config.hierarchical_nodes_per_block,
        jobs_per_block=config.hierarchical_jobs_per_block,
        refine_rounds=config.hierarchical_refine_rounds,
        superblock_nodes=config.hierarchical_superblock_nodes,
        chunk=config.chunk or 1024,
        rounds=config.chunk_rounds,
        passes=config.chunk_passes,
        kc=config.chunk_kc,
        backend=vmap_safe_backend(config.backend),
        coarse_backend=config.hierarchical_coarse_backend,
        fine_backend=config.hierarchical_fine_backend,
    )


_HIER_MESH = None
_HIER_MESH_READY = False


def hier_mesh():
    """Process-cached device mesh for the hierarchical fine batch (None
    on single-device hosts: plain vmap is the right schedule there)."""
    global _HIER_MESH, _HIER_MESH_READY
    if not _HIER_MESH_READY:
        import jax

        from cook_tpu.parallel.mesh import make_mesh

        _HIER_MESH = make_mesh() if len(jax.devices()) > 1 else None
        _HIER_MESH_READY = True
    return _HIER_MESH


class HierarchicalPending:
    """PendingResult stand-in for a pool solved by the two-level matcher:
    the coarse/scatter/fine/refine pipeline needs host round-trips, so
    the whole solve runs at `fetch()` (JAX still dispatches each device
    pass asynchronously inside).  Stats land on `prepared.hier_stats`
    for record_solve_outcome to fold into the CycleRecord."""

    __slots__ = ("prepared", "config", "telemetry")

    def __init__(self, prepared: "PreparedPool", config: "MatchConfig",
                 telemetry=None):
        self.prepared = prepared
        self.config = config
        self.telemetry = telemetry

    def fetch(self) -> np.ndarray:
        from cook_tpu.ops.hierarchical import hierarchical_match

        observatory = (self.telemetry.observatory
                       if self.telemetry is not None else None)
        mesh = (hier_mesh() if self.config.hierarchical_use_mesh else None)
        result, stats = hierarchical_match(
            self.prepared.problem,
            params=hier_params_from_config(self.config),
            mesh=mesh, observatory=observatory,
            pool=self.prepared.pool.name,
            gang_id=self.prepared.gang_id,
            gang_need=self.prepared.gang_need)
        self.prepared.hier_stats = stats
        return np.asarray(
            result.assignment[: len(self.prepared.considerable)])


def dispatch_pool_solve(prepared: "PreparedPool", config: "MatchConfig",
                        telemetry=None) -> PendingResult:
    """Dispatch the pool's match kernel WITHOUT observing completion.

    JAX's async dispatch returns device buffers immediately; the returned
    PendingResult's `fetch()` is the one completion observation (same
    semantics as `fetch_result`, including deferred-error surfacing).
    The serial path fetches inline; the pipelined engine
    (scheduler/pipeline.py) interleaves other pools' host phases between
    dispatch and fetch.  Pools at/over `hierarchical_threshold` route to
    the two-level matcher (ops/hierarchical.py) behind the same pending
    interface — a raising hierarchical solve rides the identical
    device-fallback ladder."""
    fault_schedule = faults.ACTIVE  # snapshot: a concurrent disarm must
    if fault_schedule is not None:  # not None out the global mid-site
        # `device.solve` fault point: error = kernel raising at dispatch
        # (surfaces at fetch in the pipelined engine, inline here);
        # delay = a latency spike feeding the regression guard
        fault_schedule.hit(faults.DEVICE_SOLVE, pool=prepared.pool.name)
    if hierarchical_enabled(config, prepared.problem):
        return HierarchicalPending(prepared, config, telemetry)
    if config.chunk:
        result = chunked_match(prepared.problem, chunk=config.chunk,
                               rounds=config.chunk_rounds,
                               passes=config.chunk_passes,
                               kc=config.chunk_kc,
                               **backend_flags(config.backend))
    else:
        result = greedy_match(prepared.problem)
    return PendingResult(result.assignment[: len(prepared.considerable)])


def record_solve_outcome(prepared: "PreparedPool", assignment: np.ndarray,
                         config: "MatchConfig", state: "PoolMatchState",
                         pool_name: str, solve_s: float, flight,
                         telemetry, *, overlapped: bool = False) -> None:
    """The post-solve protocol shared by the serial and pipelined paths:
    compile/latency telemetry, quality sampling, the cycle record's
    solve identity, and the periodic exact-kernel quality audit.
    `overlapped=True` for walls measured under overlap (they span
    neighbor pools' host work and must not feed any latency surface —
    see DeviceTelemetry.record_match_solve)."""
    shape = problem_shape(prepared.problem)
    backend = solve_backend(config)
    hier = prepared.hier_stats
    if hier is not None:
        # two-level solve: the record's backend names the decomposition
        # so a slow cycle is attributable to the hierarchical path from
        # the record alone (coarse/fine wall split rides in hier_phases)
        backend = f"hier-{hier['backend']}"
    compiled = False
    if telemetry is not None:
        compiled = telemetry.record_match_solve(
            pool_name, shape, backend, solve_s, overlapped=overlapped)
        telemetry.quality.observe_cycle(prepared, assignment, pool_name)
    flight.note_solve(shape_signature(shape), backend, compiled)
    if hier is not None:
        flight.note_hierarchical(hier)
        # NO exact-kernel audit for hierarchical cycles: the audit
        # replays the FULL flat problem through the sequential-greedy
        # scan — the very solve the decomposition exists to avoid (at
        # the XL sizes that trigger this path it would peg a core for
        # minutes under the single-flight audit lock).  Parity is
        # guarded by the QualityMonitor shadow solves (bounded by
        # max_shadow_jobs) and the pinned tests instead.
        return
    if telemetry is not None:
        _maybe_probe_roofline(prepared, config, shape, backend, telemetry)
    if config.chunk:
        state.chunked_solves += 1
        if (config.quality_audit_every
                and state.chunked_solves % config.quality_audit_every == 0):
            start_quality_audit(prepared, assignment, pool_name)


def _maybe_probe_roofline(prepared: "PreparedPool", config: "MatchConfig",
                          shape: tuple, backend: str, telemetry) -> None:
    """Schedule a background cost_analysis() probe for the cycle's flat
    match program (obs/data_plane.probe_roofline: single-flight, cached
    in the CompileObservatory).  Size-capped: re-lowering a giant
    program costs a full compile, so programs past the cap simply carry
    no roofline row (raise COOK_ROOFLINE_MAX_CELLS to probe them —
    pools that big route through the hierarchical path anyway, whose
    coarse/fine programs sit under the cap)."""
    if shape[0] * shape[1] > data_plane.ROOFLINE_MAX_CELLS:
        return
    observatory = telemetry.observatory
    if config.chunk:
        data_plane.probe_roofline(
            observatory, "match", shape, backend, chunked_match,
            prepared.problem, chunk=config.chunk,
            rounds=config.chunk_rounds, passes=config.chunk_passes,
            kc=config.chunk_kc, **backend_flags(config.backend))
    else:
        data_plane.probe_roofline(observatory, "match", shape, backend,
                                  greedy_match, prepared.problem)


# ------------------------------------------------------ device fallback
#
# Reaction (c) of docs/resilience.md: a sick device must not cost match
# cycles.  When a pool's solve raises (or its latency regresses past the
# guard) the pool degrades to the host-side reference solver — identical
# decision semantics to the chunk=0 exact kernel (the quality monitor's
# parity claim) — for `device_fallback_cycles` cycles, then probes the
# device again.  Health surfaces the episode as `device-degraded`.

FALLBACK_BACKEND = "cpu-fallback"

_fallback_counter = None


def _note_fallback_metrics(pool_name: str, reason: str) -> None:
    global _fallback_counter
    if _fallback_counter is None:
        _fallback_counter = global_registry.counter(
            "matcher.device_fallback_cycles",
            "match cycles solved on the CPU reference because the pool's "
            "device solve is degraded, per pool/reason")
    _fallback_counter.inc(1, {"pool": pool_name, "reason": reason})


_gang_metrics = None


def _note_gang_metrics(pool_name: str, considered: int, placed: int,
                       reasons: dict) -> None:
    """Per-cycle gang placement counters (the `gang.*` metric family):
    considered/placed per pool, blocked per pool+reason so dashboards can
    split members-missing from no-block-capacity from transact-failed."""
    global _gang_metrics
    if _gang_metrics is None:
        _gang_metrics = {
            "considered": global_registry.counter(
                "gang.considered",
                "gangs seen by a pool's match cycle, per pool"),
            "placed": global_registry.counter(
                "gang.placed",
                "gangs whose every member placed and transacted whole "
                "(one topology block, distinct hosts), per pool"),
            "blocked": global_registry.counter(
                "gang.blocked",
                "gangs held back whole (gang-incomplete), per pool and "
                "blocking reason"),
        }
    if considered:
        _gang_metrics["considered"].inc(considered, {"pool": pool_name})
    if placed:
        _gang_metrics["placed"].inc(placed, {"pool": pool_name})
    for reason, n in (reasons or {}).items():
        if n:
            _gang_metrics["blocked"].inc(n, {"pool": pool_name,
                                             "reason": reason})


def enter_device_fallback(state: PoolMatchState, config: MatchConfig,
                          pool_name: str, reason: str) -> None:
    state.fallback_cycles_left = config.device_fallback_cycles
    state.fallback_reason = reason
    log.warning("pool %s: degrading to %s for %d cycles (%s)", pool_name,
                FALLBACK_BACKEND, state.fallback_cycles_left, reason)


def check_device_fallback(config: MatchConfig, state: PoolMatchState,
                          telemetry, pool_name: str) -> tuple[bool, str]:
    """(use_cpu, reason) for this cycle; consumes one cycle of the
    fallback budget.  A pool whose budget just ran out returns False —
    that cycle IS the device probe; `exit_device_fallback` (on probe
    success) or `enter_device_fallback` (on probe failure) closes the
    episode."""
    if config.device_fallback_cycles <= 0:
        return False, ""
    if state.fallback_cycles_left > 0:
        state.fallback_cycles_left -= 1
        return True, state.fallback_reason
    if config.device_latency_guard > 0 and telemetry is not None \
            and not state.fallback_reason:
        anomaly = telemetry.latency_regressions().get(pool_name)
        if anomaly and anomaly.get("baseline", 0) > 0 and \
                anomaly["recent"] >= config.device_latency_guard \
                * anomaly["baseline"]:
            enter_device_fallback(state, config, pool_name,
                                  "latency-regression")
            state.fallback_cycles_left -= 1
            return True, state.fallback_reason
    return False, ""


def exit_device_fallback(state: PoolMatchState, telemetry,
                         pool_name: str) -> None:
    """A device solve succeeded with no fallback budget pending: the
    probe passed, clear the episode (and the health reason)."""
    if state.fallback_reason:
        log.info("pool %s: device probe succeeded; leaving %s mode",
                 pool_name, FALLBACK_BACKEND)
        state.fallback_reason = ""
        if telemetry is not None:
            telemetry.clear_device_fallback(pool_name)


def cpu_fallback_solve(prepared: "PreparedPool",
                       config: MatchConfig) -> np.ndarray:
    """Solve the prepared pool problem entirely host-side with the
    reference numpy greedy — no device buffer is touched, so this works
    even when the accelerator is wedged outright."""
    from cook_tpu.ops import cpu_reference as ref

    jobs = prepared.considerable
    demands, avail, totals = encode_problem_arrays(
        jobs, prepared.nodes.offers, config)
    assignment = ref.np_greedy_match(
        demands, avail, totals,
        feasible_mask=np.asarray(prepared.feasible)[:len(jobs)])
    return assignment.astype(np.int32)


def record_fallback_outcome(prepared: "PreparedPool", pool_name: str,
                            state: PoolMatchState, flight,
                            telemetry, reason: str) -> None:
    """The fallback cycle's counterpart of record_solve_outcome: cycle
    record + health surface, but NO latency-baseline feeding — a CPU
    solve's wall must not pollute the device baseline the probe will be
    judged against (and the quality monitor's CPU-vs-CPU ratio carries
    no signal)."""
    flight.note_solve(shape_signature(problem_shape(prepared.problem)),
                      FALLBACK_BACKEND, False)
    _note_fallback_metrics(pool_name, reason or "unknown")
    if telemetry is not None:
        telemetry.note_device_fallback(
            pool_name, reason or "unknown",
            cycles_left=state.fallback_cycles_left)


def degrade_to_solve_failed(prepared: "PreparedPool", config: "MatchConfig",
                            state: "PoolMatchState", flight,
                            record_placement_failure) -> "MatchOutcome":
    """There is no further tier to degrade to (the CPU reference itself
    raised): the pool's considerable jobs wait a cycle with solve-failed
    recorded — shared by the serial and batched paths (the pipelined
    engine reaches the same semantics through its fetch)."""
    outcome = prepared.outcome
    outcome.unmatched = list(prepared.considerable)
    outcome.head_matched = False
    for job in prepared.considerable:
        flight.note_skip(job.uuid, flight_codes.SOLVE_FAILED)
        if record_placement_failure is not None:
            record_placement_failure(
                job, flight_codes.REASON_TEXT[flight_codes.SOLVE_FAILED])
    _apply_backoff(config, state, False)
    return outcome


class CpuFallbackPending:
    """PendingResult stand-in for a pool in fallback mode: `fetch()` runs
    the host-side reference solve (the pipelined engine treats it like
    any other pending solve; there is simply no device work behind
    it)."""

    __slots__ = ("prepared", "config")

    def __init__(self, prepared: "PreparedPool", config: MatchConfig):
        self.prepared = prepared
        self.config = config

    def fetch(self) -> np.ndarray:
        return cpu_fallback_solve(self.prepared, self.config)


def fail_launched_specs(store: JobStore, specs: Sequence[TaskSpec],
                        exc: BaseException,
                        note_reason: Optional[Callable[[str, str], None]]
                        = None) -> None:
    """Launch-failure flow-back: a backend launch RPC that raised must
    not leave already-transacted tasks dangling in the store — each spec's
    instance transitions to failed with the mea-culpa `launch-failed`
    reason (the job re-queues without consuming its retry budget).
    `note_reason(job_uuid, detail)` lets callers thread the outcome into
    the flight recorder's per-job index."""
    detail = f"{type(exc).__name__}: {exc}"
    for spec in specs:
        try:
            store.update_instance_state(spec.task_id, InstanceStatus.FAILED,
                                        "launch-failed")
        except Exception:  # noqa: BLE001 — one bad transition must not
            # strand the rest of the batch in limbo
            log.exception("launch-failed transition for %s did not apply",
                          spec.task_id)
        if note_reason is not None:
            note_reason(spec.job_uuid, detail)


def gather_group_context(
    store: JobStore,
    jobs: Sequence[Job],
    host_attrs: Optional[dict[str, dict]] = None,
):
    """Hostnames/attr-values pinned by running group members.

    `host_attrs` maps hostname -> attribute dict for every host the
    scheduler has ever seen an offer from — running members may sit on
    hosts absent from this cycle's offers (full hosts emit no offer), and
    the reference's balanced-host constraint counts ALL running members
    (constraints.clj:600), not just those on currently-offered hosts."""
    group_used_hosts: dict[str, set[str]] = {}
    group_attr_value: dict[str, tuple[str, str]] = {}
    group_balance_counts: dict[str, dict[str, int]] = {}
    groups = {}
    for job in jobs:
        if not job.group_uuid or job.group_uuid in groups:
            continue
        group = store.groups.get(job.group_uuid)
        if group is None:
            continue
        groups[group.uuid] = group
        ptype = group.host_placement.type
        count_attr = (group.host_placement.attribute
                      if host_attrs and ptype in (
                          GroupPlacementType.BALANCED,
                          GroupPlacementType.ATTRIBUTE_EQUALS)
                      else None)
        hosts: set[str] = set()
        # counts are per running TASK, not per distinct host — the
        # reference takes frequencies over cohost attr maps, one per cotask
        # (constraints.clj:600), and a balanced group may co-locate members
        counts: dict[str, int] = {}
        for member_uuid in group.job_uuids:
            for inst in store.job_instances(member_uuid):
                if inst.status.terminal or not inst.hostname:
                    continue
                hosts.add(inst.hostname)
                if count_attr is not None:
                    value = host_attrs.get(inst.hostname, {}).get(count_attr)
                    if value is None and ptype == GroupPlacementType.BALANCED:
                        value = MISSING_ATTR  # nil counts as a value
                    if value is not None:
                        counts[value] = counts.get(value, 0) + 1
        group_used_hosts[group.uuid] = hosts
        if counts:
            if ptype == GroupPlacementType.BALANCED:
                group_balance_counts[group.uuid] = counts
            elif group.uuid not in group_attr_value:
                # running members pin the attribute value for the group
                group_attr_value[group.uuid] = (
                    count_attr, max(counts, key=counts.get))
    return groups, group_used_hosts, group_attr_value, group_balance_counts


def _agent_removed_codes() -> frozenset:
    from cook_tpu.models.reasons import REASONS_BY_NAME

    return frozenset(
        REASONS_BY_NAME[name].code
        for name in ("node-removed", "could-not-reconstruct-state")
        if name in REASONS_BY_NAME
    )


AGENT_REMOVED_CODES = _agent_removed_codes()


def estimated_end_times(store: JobStore, jobs: Sequence[Job],
                        config: MatchConfig,
                        predictor=None) -> Optional[np.ndarray]:
    """Per-job estimated completion time in epoch ms, -1 = no estimate
    (build-estimated-completion-constraint, constraints.clj:410-432):
    max of scaled expected runtime and the runtimes of instances that
    died with the host (agent-removed analogs), capped at
    host-lifetime - grace so a full-lifetime job can still start on a
    fresh host.  `predictor` (scheduler/prediction.py) supplies an
    observed-runtime estimate for jobs that declared no
    expected_runtime_ms — the predicted-duration column threaded into
    the match feasibility tensor."""
    if not (config.completion_multiplier > 0
            and config.host_lifetime_mins > 0):
        return None
    now_ms = store.clock()
    cap_ms = (config.host_lifetime_mins
              - config.agent_start_grace_mins) * 60_000.0
    out = np.full(len(jobs), -1.0)
    for ji, job in enumerate(jobs):
        runtime = job.expected_runtime_ms
        if not runtime and predictor is not None:
            runtime = predictor.predict_runtime_ms(job.user,
                                                   job.command) or 0.0
        expected = (runtime * config.completion_multiplier
                    if runtime else 0.0)
        for inst in store.job_instances(job.uuid):
            if (inst.status.terminal
                    and inst.reason_code in AGENT_REMOVED_CODES
                    and inst.end_time_ms > inst.start_time_ms):
                expected = max(expected,
                               inst.end_time_ms - inst.start_time_ms)
        if expected > 0:
            out[ji] = now_ms + min(expected, cap_ms)
    return out


def assign_ports(offer, used: set, count: int) -> Optional[tuple]:
    """Pick `count` concrete ports from the offer's free ranges, skipping
    ports already taken this cycle (mesos/task.clj port assignment)."""
    if count <= 0:
        return ()
    picked = []
    for begin, end in offer.ports:
        for port in range(begin, end + 1):
            if port in used:
                continue
            picked.append(port)
            if len(picked) == count:
                return tuple(picked)
    return None


def previous_failed_hosts(store: JobStore, jobs: Sequence[Job]) -> dict[str, set[str]]:
    """novel-host constraint input: hosts each job already failed on."""
    out: dict[str, set[str]] = {}
    for job in jobs:
        hosts = {
            inst.hostname
            for inst in store.job_instances(job.uuid)
            if inst.status.terminal and inst.hostname
        }
        if hosts:
            out[job.uuid] = hosts
    return out


def record_considered(flight, queue, considerable, offers_count: int) -> None:
    """Cycle-record bookkeeping for a selected considerable window —
    shared by the fresh prepare and the speculative-commit path (a cycle
    served from speculation must report the same counts, rank context,
    and not-considered index a fresh prepare would).

    The rank context is attached by reference (rank_cycle replaces,
    never mutates); the not-considered indexing is skipped entirely when
    no recorder is attached — it is O(queue) work on the latency-
    critical match path."""
    flight.set_counts(offers=offers_count, queue_len=len(queue.jobs),
                      considered=len(considerable))
    flight.set_rank_context(queue.jobs, queue.dru)
    if flight is not NULL_CYCLE and len(considerable) < len(queue.jobs):
        # jobs in the ranked queue but outside this cycle's considerable
        # window (cap, quota, launch filter, dead-in-queue): indexed so
        # /unscheduled_jobs answers with the CURRENT reason, not a stale
        # decision from the last cycle that did consider them
        selected = {j.uuid for j in considerable}
        for job in queue.jobs:
            if job.uuid not in selected:
                flight.note_not_considered(job.uuid)


@dataclass
class PreparedPool:
    """Host-side encoding of one pool's match problem, ready to solve."""

    pool: Pool
    outcome: MatchOutcome
    considerable: list = field(default_factory=list)
    cluster_offers: list = field(default_factory=list)
    nodes: Optional[EncodedNodes] = None
    groups: dict = field(default_factory=dict)
    group_used_hosts: dict = field(default_factory=dict)
    group_attr_value: dict = field(default_factory=dict)
    group_balance_counts: dict = field(default_factory=dict)
    balanced_pre_rows: dict = field(default_factory=dict)
    feasible: Optional[np.ndarray] = None
    problem: Optional[MatchProblem] = None
    # two-level solve accounting (ops/hierarchical.py stats), set by
    # HierarchicalPending.fetch and folded into the CycleRecord by
    # record_solve_outcome
    hier_stats: Optional[dict] = None
    # gang rows of the considerable window (gang_context): dense per-
    # cycle gang index / member count, None when the cycle has no gangs.
    # The hierarchical solve consumes them for block routing; the
    # finalize chokepoint enforces all-or-nothing on EVERY path with them
    gang_id: Optional[np.ndarray] = None
    gang_need: Optional[np.ndarray] = None
    # clusters withheld from this cycle because their circuit breaker is
    # open (cook_tpu/faults/breaker.py): offer-less pools report
    # `cluster-circuit-open` instead of a misleading `no-offers`
    circuit_open: list = field(default_factory=list)

    @property
    def solvable(self) -> bool:
        return self.problem is not None


def prepare_pool_problem(
    store: JobStore,
    pool: Pool,
    queue: RankedQueue,
    clusters: Sequence[ComputeCluster],
    config: MatchConfig,
    state: PoolMatchState,
    *,
    launch_filter: Optional[Callable[[Job], bool]] = None,
    host_reservations: Optional[dict[str, str]] = None,
    host_attrs: Optional[dict[str, dict]] = None,
    flight=NULL_CYCLE,
    encode_cache=None,
    predictor=None,
    device_state=None,
) -> PreparedPool:
    """Gather offers + considerable jobs and encode the tensor problem.

    With `encode_cache` (scheduler/encode_cache.py) the node encoding and
    per-job feasibility rows are incremental: an unchanged pool re-encodes
    O(delta) rows instead of O(J×N).  The cache is bypassed while the
    estimated-completion constraint is active (rows become clock-
    dependent).

    With `device_state` (scheduler/device_state.py) AND
    `config.device_residency`, the padded problem tensors additionally
    stay device-resident across cycles: unchanged rows transfer zero
    bytes, deltas apply via donated-buffer scatters.  The mirror is
    bypassed alongside the host cache (completion constraint), and on
    reservation cycles (host reservations mutate rows after assembly)."""
    prepared = PreparedPool(pool=pool, outcome=MatchOutcome())

    # offers from every running cluster (scheduler.clj:1574-1585); an
    # offer RPC raising skips that cluster for this scan — with NO
    # breaker accounting (its window watches launch/kill RPCs only) —
    # instead of killing the cycle; cluster/base.safe_pool_offers
    from cook_tpu.cluster.base import safe_pool_offers
    from cook_tpu.cluster.base import ClusterState as _CS
    from cook_tpu.faults.breaker import BreakerState as _BS

    for cluster in clusters:
        if not cluster.accepts_work:
            # classify via the non-mutating state read: a second
            # allows_work() here could consume the open->half-open
            # transition (and the probe slot) outside any launch flow
            if cluster.state == _CS.RUNNING \
                    and cluster.breaker.state is not _BS.CLOSED:
                prepared.circuit_open.append(cluster.name)
            continue
        offers = safe_pool_offers(cluster, pool.name)
        if offers is None:
            continue
        for offer in offers:
            prepared.cluster_offers.append((cluster, offer))
    prepared.outcome.offers_total = len(prepared.cluster_offers)

    prepared.considerable = select_considerable(
        store, pool, queue, state.num_considerable, launch_filter=launch_filter
    )
    considerable = prepared.considerable
    record_considered(flight, queue, considerable,
                      len(prepared.cluster_offers))
    prepared.gang_id, prepared.gang_need = gang_context(considerable,
                                                        config)
    if not considerable or not prepared.cluster_offers:
        return prepared

    est_end_ms = estimated_end_times(store, considerable, config,
                                     predictor=predictor)
    use_cache = encode_cache is not None and est_end_ms is None
    if use_cache:
        nodes, nodes_fp = encode_cache.encoded_nodes(
            pool.name, prepared.cluster_offers)
    else:
        nodes = encode_nodes([o for _, o in prepared.cluster_offers])
    prepared.nodes = nodes
    # every host in this cycle's offers contributes attrs, written back
    # into the caller's accumulated cache HERE (pre-match) — a host whose
    # first offer is fully consumed this cycle would otherwise never be
    # cached and its running group members would count as attribute-less
    if host_attrs is not None:
        for o in nodes.offers:
            host_attrs[o.hostname] = dict(o.attributes)
        merged_attrs: dict = host_attrs
    else:
        merged_attrs = {o.hostname: dict(o.attributes) for o in nodes.offers}
    (prepared.groups, prepared.group_used_hosts,
     prepared.group_attr_value,
     prepared.group_balance_counts) = gather_group_context(
        store, considerable, host_attrs=merged_attrs)
    offer_locations = [c.location for c, _ in prepared.cluster_offers]
    use_mirror = (use_cache and device_state is not None
                  and config.device_residency and not host_reservations)
    served: Optional[dict] = {} if use_mirror else None
    if use_cache:
        def compute_rows(subset, pre_rows):
            return feasibility_mask(
                subset,
                nodes,
                previous_hosts=previous_failed_hosts(store, subset),
                group_used_hosts=prepared.group_used_hosts,
                group_attr_value=prepared.group_attr_value,
                group_balance_counts=prepared.group_balance_counts,
                groups=prepared.groups,
                offer_locations=offer_locations,
                host_lifetime_mins=config.host_lifetime_mins,
                balanced_pre_rows=pre_rows,
            )

        feasible = encode_cache.feasibility(
            pool.name, considerable, nodes.n, nodes_fp, compute_rows,
            balanced_pre_rows=prepared.balanced_pre_rows,
            served=served,
        )
    else:
        feasible = feasibility_mask(
            considerable,
            nodes,
            previous_hosts=previous_failed_hosts(store, considerable),
            group_used_hosts=prepared.group_used_hosts,
            group_attr_value=prepared.group_attr_value,
            group_balance_counts=prepared.group_balance_counts,
            groups=prepared.groups,
            offer_locations=offer_locations,
            job_est_end_ms=est_end_ms,
            host_lifetime_mins=config.host_lifetime_mins,
            balanced_pre_rows=prepared.balanced_pre_rows,
        )
        # cache bypassed (disabled, or the estimated-completion
        # constraint made rows clock-dependent): every encode row was
        # freshly computed, so the residency ledger reports a full
        # rebuild (the cache path's notes come from EncodeCache itself)
        data_plane.note_residency(len(considerable) * nodes.n, 0)
        data_plane.note_residency(data_plane.NODE_ROW_BYTES * nodes.n, 0,
                                  kind="nodes")
    if host_reservations:
        # rebalancer reservations (constraints.clj:242 + reserve-hosts!,
        # rebalancer.clj:419): a reserved host only accepts its reserving job
        reserved_for = np.array(
            [host_reservations.get(o.hostname, "") for o in nodes.offers]
        )
        has_reservation = reserved_for != ""
        for ji, job in enumerate(considerable):
            allowed = ~has_reservation | (reserved_for == job.uuid)
            if job.group_uuid:
                # gang admission reserves hosts under a group-wide tag any
                # member may claim (scheduler/gang.py)
                allowed |= reserved_for == ("gang:" + job.group_uuid)
            feasible[ji] &= allowed
            # the saved pre-closure rows must honor reservations too, or
            # the balanced top-up could steal a reserved host
            if ji in prepared.balanced_pre_rows:
                prepared.balanced_pre_rows[ji] &= allowed
    prepared.feasible = feasible
    if use_mirror:
        # device-resident path: unchanged rows move zero bytes; the
        # mirror's problem is shape- and content-identical to the
        # classic build below (padded_job_axis is shared)
        prepared.problem = device_state.build_problem(
            pool.name, considerable, nodes, feasible, nodes_fp, served,
            config, flight=flight)
    else:
        quantized = (device_state.quantized_for(config, pool.name)
                     if device_state is not None else config.quantized)
        prepared.problem = build_match_problem(considerable, nodes,
                                               feasible,
                                               chunk=config.chunk,
                                               config=config,
                                               quantized=quantized)
    bonus = topology_bonus(nodes, config)
    if bonus is not None:
        # the topology distance term rides every build path (classic,
        # quantized, device-resident) as a post-assembly field: [N]
        # floats are negligible next to the [J, N] mask, so residency
        # doesn't mirror them
        pad_n = int(prepared.problem.avail.shape[0])
        prepared.problem = prepared.problem._replace(
            node_bonus=data_plane.h2d(pad_to(bonus, pad_n),
                                      family=data_plane.FAM_NODE_ENCODE))
    return prepared


def finalize_pool_match(
    store: JobStore,
    prepared: PreparedPool,
    assignment: np.ndarray,
    config: MatchConfig,
    state: PoolMatchState,
    clusters: Sequence[ComputeCluster],
    *,
    make_task_id: Callable[[Job], str],
    record_placement_failure: Optional[Callable[[Job, str], None]] = None,
    flight=NULL_CYCLE,
    async_launch: bool = False,
    launch_failure_cb: Optional[Callable] = None,
) -> MatchOutcome:
    """Apply a solved assignment: group validation, launch transactions,
    backend launches, autoscaling, head-of-queue backoff.

    `async_launch` moves each cluster's backend launch onto that
    cluster's bounded launch executor (ComputeCluster.launch_tasks_async)
    so RPC latency leaves the cycle's critical path; failures flow
    through `launch_failure_cb(specs, exc)` (default: the same
    fail_launched_specs flow-back the synchronous path uses)."""
    outcome = prepared.outcome
    considerable = prepared.considerable
    pool = prepared.pool
    if not prepared.solvable:
        outcome.unmatched = considerable
        outcome.head_matched = not considerable
        if not prepared.cluster_offers:
            # distinguish "no capacity" from "capacity exists but its
            # clusters are circuit-open": the latter is a transient the
            # breaker will probe out of, and operators must see it
            code = (flight_codes.CLUSTER_CIRCUIT_OPEN
                    if prepared.circuit_open else flight_codes.NO_OFFERS)
        else:
            code = flight_codes.CONSTRAINTS_FILTERED
        for job in considerable:
            flight.note_skip(job.uuid, code)
        if prepared.gang_id is not None:
            n_gangs = int(np.unique(
                prepared.gang_id[prepared.gang_id >= 0]).size)
            flight.note_gang(considered=n_gangs, placed=0, blocked=n_gangs,
                            reasons={code: n_gangs})
        _apply_backoff(config, state, outcome.head_matched)
        return outcome
    nodes = prepared.nodes
    cluster_offers = prepared.cluster_offers
    feasible = prepared.feasible
    live_balance_counts: dict = {}
    assignment = validate_group_assignments(
        considerable, assignment, nodes, prepared.groups,
        prepared.group_used_hosts, prepared.group_attr_value,
        prepared.group_balance_counts,
        out_balance_counts=live_balance_counts,
    )
    if any(assignment[ji] < 0 for ji in prepared.balanced_pre_rows):
        # retry balanced-group jobs the stale pre-mask closed out, against
        # post-cycle counts (intra-cycle leveling re-opens values); the
        # demand/avail tensors were already built for the kernel — slice
        # the unpadded rows back instead of rebuilding (three full padded
        # tensors cross back: D2H-accounted like every other crossing)
        data_plane.note_d2h(
            int(prepared.problem.demands.nbytes)
            + int(prepared.problem.avail.nbytes)
            + int(prepared.problem.totals.nbytes),
            family=data_plane.FAM_NODE_ENCODE)
        # float32 casts: under MatchConfig.quantized the device tensors
        # are bf16, whose numpy ufunc coverage (subtract.at) is partial
        demands = np.asarray(prepared.problem.demands).astype(
            np.float32)[:len(considerable)]
        remaining = np.asarray(prepared.problem.avail).astype(
            np.float32)[:nodes.n].copy()
        placed_mask = assignment >= 0
        np.subtract.at(remaining, assignment[placed_mask],
                       demands[placed_mask])
        assignment = balanced_group_topup(
            considerable, assignment, nodes, prepared.groups,
            live_balance_counts, prepared.balanced_pre_rows,
            remaining, demands,
            totals=np.asarray(prepared.problem.totals).astype(
                np.float32)[:nodes.n],
        )

    # gang all-or-nothing chokepoint (ops/gang.np_gang_filter): EVERY
    # solve path — serial, batched, pipelined, speculative, CPU-fallback,
    # hierarchical — funnels its assignment through here, so a gang can
    # never partially place no matter which kernel produced it.  The
    # hierarchical path already filtered on-device (and retried through
    # refine); this host twin re-checks after group validation/topup may
    # have stripped members.  Flat solves carry no block structure, so
    # they enforce whole-pool all-or-nothing + distinct hosts
    # (nodes_per_block=0); the one-block rule binds where topology
    # exists.
    gang_details: dict[int, str] = {}
    if prepared.gang_id is not None:
        gid, gneed = prepared.gang_id, prepared.gang_need
        npb_eff = int((prepared.hier_stats or {}).get("nodes_per_block", 0))
        if npb_eff == 0 and config.topology_block_hosts:
            # flat solve but the operator declared the topology: the
            # explicit block width binds the one-block rule here too
            npb_eff = int(config.topology_block_hosts)
        demands_np, avail_np, _tot = encode_problem_arrays(
            considerable, nodes.offers, config)
        # repair before judging: the flat kernels best-fit gang members
        # onto one host (UNIQUE validation just stripped the duplicates);
        # give each broken gang one whole-gang retry on distinct feasible
        # hosts inside a single block before all-or-nothing decides
        assignment = np_gang_repair(assignment, gid, gneed, demands_np,
                                    avail_np, feasible, npb_eff)
        assignment, _ = np_gang_filter(assignment, gid, gneed, npb_eff)
        # capacity left after the strip — what the repair pass actually
        # saw, so skip details report the real blocker, and the scalar
        # top-up below reuses hosts a stripped gang freed
        remaining_np = avail_np.copy()
        placed_rows = np.flatnonzero(assignment >= 0)
        np.subtract.at(remaining_np, assignment[placed_rows],
                       demands_np[placed_rows])
        block_reasons: dict[str, int] = {}
        placed_gangs = 0
        gang_ids = np.unique(gid[gid >= 0])
        for g in gang_ids:
            rows = np.flatnonzero(gid == g)
            if bool((assignment[rows] >= 0).all()):
                placed_gangs += 1
                continue
            k = int(gneed[rows].max())
            if len(rows) < k:
                gang_details[int(g)] = (
                    f"only {len(rows)}/{k} members in this cycle's "
                    "considerable window")
                reason = "members-missing"
            else:
                member_demand = demands_np[rows].max(axis=0)
                free = np_block_free_hosts(
                    remaining_np, feasible[rows].all(axis=0),
                    member_demand, npb_eff if npb_eff > 0 else nodes.n)
                best = int(free.max(initial=0))
                gang_details[int(g)] = (
                    f"best block had {min(best, k)}/{k} hosts free")
                reason = "no-block-capacity"
            block_reasons[reason] = block_reasons.get(reason, 0) + 1
        # scalar top-up: a stripped gang hands its hosts straight back
        # to waiting UNGROUPED rows (greedy first-fit in schedule
        # order) instead of idling them for a cycle — grouped jobs sit
        # out, their placement rules already ran upstream
        for ji in np.flatnonzero(assignment < 0):
            ji = int(ji)
            if gid[ji] >= 0 or considerable[ji].group_uuid:
                continue
            fits = feasible[ji] & (
                remaining_np >= demands_np[ji]).all(axis=1)
            cands = np.flatnonzero(fits)
            if cands.size:
                node = int(cands[0])
                assignment[ji] = node
                remaining_np[node] -= demands_np[ji]
        # emitted AFTER the transact loop: a gang that solves whole can
        # still abort during transact, and the cycle record must say so
        gang_note = (int(gang_ids.size), placed_gangs, block_reasons)
    else:
        gang_note = None

    # transact + launch (scheduler.clj:790-1048)
    launches_per_cluster: dict[str, list[TaskSpec]] = {}
    cluster_by_name = {}
    # per-cluster launch budgets this cycle (max-launchable +
    # filter-matches-for-ratelimit, scheduler.clj:887)
    cluster_budget: dict[str, int] = {}
    # ports handed out this cycle, per node (the mask guaranteed counts
    # against the offer; concrete picks must not collide intra-cycle)
    ports_used: dict[int, set] = {}

    # gang-atomic transact: a gang's specs and launch bookkeeping defer
    # into gang_txn until the LAST member transacts; a member failing any
    # transact step (launch cap, ports, veto) rolls already-transacted
    # siblings back (mea-culpa launch-failed, budget and ports refunded)
    # so the all-or-nothing property survives the host-side launch
    # pipeline, not just the solve
    gang_txn: dict[int, dict] = {}
    failed_gangs: set[int] = set()

    def gang_of(ji: int) -> int:
        return (int(prepared.gang_id[ji])
                if prepared.gang_id is not None else -1)

    def abort_gang(g: int, cause: str) -> None:
        failed_gangs.add(g)
        txn = gang_txn.pop(g, None)
        if txn is None:
            return
        for task_id in txn["task_ids"]:
            try:
                store.update_instance_state(
                    task_id, InstanceStatus.FAILED, "launch-failed")
            except Exception:  # noqa: BLE001 — one stuck rollback must
                # not strand the rest of the gang's members
                log.exception("gang rollback transition for %s did not "
                              "apply", task_id)
        for cname, cnt in txn["budget"].items():
            if cname in cluster_budget:
                cluster_budget[cname] += cnt
        for node_i, tports in txn["ports"]:
            ports_used.get(node_i, set()).difference_update(tports)
        detail = f"gang member failed to transact ({cause})"
        for member, _offer, _tid in txn["jobs"]:
            outcome.unmatched.append(member)
            flight.note_skip(member.uuid, flight_codes.GANG_INCOMPLETE,
                             detail)
            if record_placement_failure is not None:
                record_placement_failure(
                    member,
                    flight_codes.REASON_TEXT[flight_codes.GANG_INCOMPLETE]
                    + f" ({detail})")

    for ji, job in enumerate(considerable):
        node_idx = int(assignment[ji])
        g = gang_of(ji)
        if g >= 0 and g in failed_gangs:
            # a sibling already failed this cycle's transact: hold this
            # member back too (all-or-nothing)
            outcome.unmatched.append(job)
            flight.note_skip(job.uuid, flight_codes.GANG_INCOMPLETE,
                             gang_details.get(g, ""))
            if record_placement_failure is not None:
                record_placement_failure(
                    job,
                    flight_codes.REASON_TEXT[flight_codes.GANG_INCOMPLETE])
            continue
        if node_idx < 0:
            outcome.unmatched.append(job)
            if g >= 0:
                detail = gang_details.get(g, "")
                flight.note_skip(job.uuid, flight_codes.GANG_INCOMPLETE,
                                 detail)
                if record_placement_failure is not None:
                    text = flight_codes.REASON_TEXT[
                        flight_codes.GANG_INCOMPLETE]
                    record_placement_failure(
                        job, text + (f" ({detail})" if detail else ""))
                continue
            code = _failure_reason(job, nodes, feasible[ji])
            flight.note_skip(job.uuid, code)
            if record_placement_failure is not None:
                record_placement_failure(job, flight_codes.REASON_TEXT[code])
            continue
        cluster, offer = cluster_offers[node_idx]
        budget = cluster_budget.get(cluster.name)
        if budget is None:
            budget = cluster.max_launchable()
            # per-cluster launch rate limiter (rate_limit.clj:44): this
            # cycle may launch at most the bucket's current balance here
            limiter = getattr(cluster, "launch_rate_limiter", None)
            tokens_available = getattr(limiter, "tokens_available", None)
            if tokens_available is not None:
                tokens = tokens_available(cluster.name)
                # inf = unenforced bucket / unlimited null object
                if math.isfinite(tokens):
                    budget = min(budget, int(tokens))
        if budget <= 0:
            # over the cluster's launch cap: reject BEFORE assigning
            # ports, or rate-capped jobs would consume phantom ports and
            # later jobs would report the wrong failure reason.  Cache the
            # zero so later jobs skip the limiter lookup — and so a bucket
            # refilling mid-cycle cannot admit lower-ranked jobs after
            # higher-ranked ones were rejected
            cluster_budget[cluster.name] = 0
            outcome.unmatched.append(job)
            flight.note_skip(job.uuid, flight_codes.LAUNCH_CAP)
            if record_placement_failure is not None:
                record_placement_failure(
                    job, flight_codes.REASON_TEXT[flight_codes.LAUNCH_CAP])
            if g >= 0:
                abort_gang(g, flight_codes.LAUNCH_CAP)
            continue
        task_ports = assign_ports(offer, ports_used.setdefault(node_idx, set()),
                                  job.resources.ports)
        if task_ports is None:
            # earlier matches this cycle exhausted the offer's ports
            outcome.unmatched.append(job)
            flight.note_skip(job.uuid, flight_codes.PORTS_EXHAUSTED)
            if record_placement_failure is not None:
                record_placement_failure(
                    job,
                    flight_codes.REASON_TEXT[flight_codes.PORTS_EXHAUSTED])
            if g >= 0:
                abort_gang(g, flight_codes.PORTS_EXHAUSTED)
            continue
        ports_used[node_idx].update(task_ports)
        cluster_budget[cluster.name] = budget - 1
        task_id = make_task_id(job)
        try:
            store.create_instance(
                job.uuid,
                task_id,
                hostname=offer.hostname,
                node_id=offer.node_id,
                compute_cluster=cluster.name,
            )
        except TransactionVetoed:
            # job completed/launched concurrently; drop the match
            flight.note_skip(job.uuid, flight_codes.LAUNCH_VETOED)
            if g >= 0:
                abort_gang(g, flight_codes.LAUNCH_VETOED)
            continue
        # checkpoint context rides in the task env uniformly for every
        # backend (mode/period for the tooling, preserve paths for the
        # restore — checkpoint->volume-mounts, api.clj:1194)
        checkpoint_env: tuple = ()
        if job.checkpoint is not None and job.checkpoint.mode:
            checkpoint_env = (
                ("COOK_CHECKPOINT_MODE", job.checkpoint.mode),
                ("COOK_CHECKPOINT_PERIOD_SEC",
                 str(job.checkpoint.periodic_sec)),
            )
            if job.checkpoint.preserve_paths:
                checkpoint_env += (
                    ("COOK_CHECKPOINT_PRESERVE_PATHS",
                     ":".join(job.checkpoint.preserve_paths)),
                )
        spec = TaskSpec(
            task_id=task_id,
            job_uuid=job.uuid,
            user=job.user,
            command=job.command,
            mem=job_mem_with_overhead(job, config),
            cpus=job.resources.cpus,
            gpus=job.resources.gpus,
            node_id=offer.node_id,
            hostname=offer.hostname,
            disk=job.resources.disk,
            env=job.user_provided_env + checkpoint_env + tuple(
                (f"PORT{i}", str(p)) for i, p in enumerate(task_ports)),
            container_image=(job.container.image if job.container else ""),
            expected_runtime_ms=job.expected_runtime_ms,
            ports=task_ports,
            checkpoint_mode=(job.checkpoint.mode if job.checkpoint else ""),
            checkpoint_periodic_sec=(job.checkpoint.periodic_sec
                                     if job.checkpoint else 0),
            checkpoint_preserve_paths=(tuple(job.checkpoint.preserve_paths)
                                       if job.checkpoint else ()),
        )
        cluster_by_name[cluster.name] = cluster
        if g >= 0:
            # defer the member: its spec only joins the launch batch once
            # every sibling has transacted too
            txn = gang_txn.setdefault(
                g, {"specs": [], "jobs": [], "task_ids": [],
                    "budget": {}, "ports": []})
            txn["specs"].append((cluster.name, spec))
            txn["jobs"].append((job, offer, task_id))
            txn["task_ids"].append(task_id)
            txn["budget"][cluster.name] = (
                txn["budget"].get(cluster.name, 0) + 1)
            txn["ports"].append((node_idx, set(task_ports)))
            continue
        launches_per_cluster.setdefault(cluster.name, []).append(spec)
        outcome.matched.append((job, offer))
        outcome.launched_task_ids.append(task_id)
        flight.note_match(job.uuid, offer.hostname, task_id)

    # flush gangs whose every member transacted — their specs join the
    # launch batches only now, so a late member's transact failure could
    # not have left siblings half-launched.  (Launch-RPC failures AFTER
    # this point are not rolled back gang-wide: those members re-queue
    # mea-culpa through fail_launched_specs like any other job.)
    for g in sorted(gang_txn):
        txn = gang_txn[g]
        for (cname, spec), (job, offer, task_id) in zip(txn["specs"],
                                                        txn["jobs"]):
            launches_per_cluster.setdefault(cname, []).append(spec)
            outcome.matched.append((job, offer))
            outcome.launched_task_ids.append(task_id)
            flight.note_match(job.uuid, offer.hostname, task_id)

    if gang_note is not None:
        considered_n, placed_gangs, block_reasons = gang_note
        if failed_gangs:
            placed_gangs -= len(failed_gangs)
            block_reasons["transact-failed"] = len(failed_gangs)
        flight.note_gang(considered=considered_n, placed=placed_gangs,
                         blocked=considered_n - placed_gangs,
                         reasons=block_reasons)
        _note_gang_metrics(pool.name, considered_n, placed_gangs,
                           block_reasons)

    if launch_failure_cb is None:
        # the synchronous default may write the builder (same thread);
        # an async default must not — the callback runs on the cluster's
        # launch-worker thread, and CycleBuilder is single-threaded by
        # construction (the pipelined engine supplies a recorder-locked
        # callback instead)
        sync_note = (None if async_launch
                     else lambda uuid, detail: flight.note_skip(
                         uuid, flight_codes.LAUNCH_FAILED, detail))

        def launch_failure_cb(specs, exc):
            fail_launched_specs(store, specs, exc, note_reason=sync_note)

    for cname, specs in launches_per_cluster.items():
        cluster = cluster_by_name[cname]
        limiter = getattr(cluster, "launch_rate_limiter", None)
        if limiter is not None:
            # spend-through: charge the work that is about to happen
            limiter.spend(cname, float(len(specs)))
        if async_launch:
            # the worker holds the kill-lock read side itself; failures
            # arrive on the worker thread via the callback
            cluster.launch_tasks_async(
                pool.name, specs,
                done_cb=lambda sp, exc, _cb=launch_failure_cb:
                    _cb(sp, exc) if exc is not None else None)
            continue
        try:
            # read side of the kill-lock: kills can't interleave
            # mid-launch; run_launch adds the cluster.launch fault point
            # and circuit-breaker accounting around the backend RPC
            with cluster.kill_lock.read():
                cluster.run_launch(pool.name, specs)
        except Exception as exc:  # noqa: BLE001 — one cluster's RPC
            # failure must not abort the remaining clusters' launches
            log.exception("launch_tasks failed (cluster %s, pool %s, "
                          "%d specs); failing its specs and continuing",
                          cname, pool.name, len(specs))
            launch_failure_cb(specs, exc)

    # 4. autoscaling: surface unmatched demand to autoscaling clusters
    # (trigger-autoscaling!, scheduler.clj:1178,1509)
    if outcome.unmatched:
        demand = [
            TaskSpec(
                task_id=f"pending-{job.uuid}",
                job_uuid=job.uuid,
                user=job.user,
                command=job.command,
                mem=job.resources.mem,
                cpus=job.resources.cpus,
                gpus=job.resources.gpus,
                node_id="",
                hostname="",
                disk=job.resources.disk,
            )
            for job in outcome.unmatched
        ]
        for cluster in clusters:
            if cluster.accepts_work and cluster.autoscaling(pool.name):
                cluster.autoscale(pool.name, demand)

    # 5. head-of-queue backoff
    head = considerable[0]
    outcome.head_matched = any(j.uuid == head.uuid for j, _ in outcome.matched)
    _apply_backoff(config, state, outcome.head_matched)
    return outcome


_audit_lock = threading.Lock()
last_audit_thread: Optional[threading.Thread] = None  # tests join this


def start_quality_audit(prepared: "PreparedPool", assignment: np.ndarray,
                        pool_name: str) -> None:
    """Kick off audit_match_quality on a daemon thread.

    The exact solve (plus its first-use XLA compile) can take seconds at
    large considerable counts, so it must not stall the match cycle's
    launches.  Single-flight: while one audit runs, due samples are
    skipped rather than queued — the guard needs a periodic signal, not
    every sample."""
    global last_audit_thread
    if not _audit_lock.acquire(blocking=False):
        return
    def run():
        try:
            audit_match_quality(prepared, assignment, pool_name)
        except Exception:  # noqa: BLE001 — an audit failure must never
            # take down the scheduler; it is purely observability
            log.exception("match quality audit failed (pool %s)", pool_name)
        finally:
            _audit_lock.release()
    try:
        t = threading.Thread(target=run, name=f"match-audit-{pool_name}",
                             daemon=True)
        last_audit_thread = t
        t.start()
    except Exception:  # noqa: BLE001 — if the thread never starts, run()
        # never runs, so ITS finally can't release the lock; releasing
        # here keeps the audit alive for future cycles
        _audit_lock.release()
        raise


def audit_match_quality(prepared: "PreparedPool", assignment: np.ndarray,
                        pool_name: str) -> float:
    """Replay a chunked solve's problem through the exact sequential-
    greedy kernel and gauge the packing-parity ratio (placed demand
    weight, approximate / exact).

    This is the runtime guard behind `MatchConfig.quality_audit_every`:
    sweep-promoted configs are only certified at the sweep's shape, and
    the sweep showed quality collapse at some (chunk, kc) corners — so
    the deployed config is continuously re-checked on the live workload.
    The cost is one exact solve of the (<= max_jobs_considered)-job
    problem every N cycles, run via start_quality_audit on a background
    thread (the cycle's assignment is already final; the audit only
    reads it).  The exact solve runs on the host CPU backend: XLA
    serializes execution per device, so running it on the accelerator
    would queue the NEXT match cycle's solve behind a multi-second
    audit — the stall the background thread exists to avoid."""
    import jax

    n_consider = len(prepared.considerable)
    problem = prepared.problem
    try:
        cpu = jax.devices("cpu")[0]
        # bucketed under the distinct `fallback` tensor family: this put
        # re-stages the whole problem onto the HOST platform for the
        # reference replay — folding it into the device families would
        # inflate the very transfer numbers item 2(a) is judged by
        problem = data_plane.device_put(problem, cpu,
                                        family=data_plane.FAM_FALLBACK)
    except RuntimeError:
        pass  # no host platform registered; accept device contention
    exact = np.asarray(greedy_match(problem).assignment[:n_consider])
    data_plane.note_d2h(exact.nbytes, family=data_plane.FAM_FALLBACK)
    demands = np.asarray(prepared.problem.demands[:n_consider])
    data_plane.note_d2h(demands.nbytes, family=data_plane.FAM_FALLBACK)
    # weight = mem + cpus + gpus, each normalized by the problem's mean
    # demand so no resource dominates (same spirit as bench packing_eff);
    # gpus included so a collapse confined to gpu jobs still registers
    scale = np.maximum(demands.mean(axis=0), 1e-9)
    weights = (demands[:, :3] / scale[:3]).sum(axis=-1)
    approx_w = float(weights[assignment >= 0].sum())
    exact_w = float(weights[exact >= 0].sum())
    if exact_w <= 0:
        # the exact kernel placed nothing: a degenerate problem (no
        # feasible pairs), not evidence of parity — setting the gauge to
        # 1.0 would read "healthy" on a pathological cycle, so skip it
        log.info("match quality audit: pool %s exact kernel placed zero "
                 "weight; skipping gauge update", pool_name)
        return 1.0
    ratio = approx_w / exact_w
    global_registry.gauge(
        "match.quality_audit",
        "packing parity of the chunked solve vs the exact kernel",
    ).set(ratio, labels={"pool": pool_name})
    if ratio < 0.99:
        log.warning(
            "match quality audit: pool %s chunked solve placed %.4f of "
            "the exact kernel's demand weight (< 0.99) — the tuned "
            "matcher config is underperforming on this workload; "
            "consider re-running tools/tpu_sweep.py or lowering chunk",
            pool_name, ratio)
    return ratio


def match_pool(
    store: JobStore,
    pool: Pool,
    queue: RankedQueue,
    clusters: Sequence[ComputeCluster],
    config: MatchConfig,
    state: PoolMatchState,
    *,
    make_task_id: Callable[[Job], str],
    launch_filter: Optional[Callable[[Job], bool]] = None,
    record_placement_failure: Optional[Callable[[Job, str], None]] = None,
    host_reservations: Optional[dict[str, str]] = None,
    host_attrs: Optional[dict[str, dict]] = None,
    flight=NULL_CYCLE,
    telemetry=None,
    encode_cache=None,
    predictor=None,
    device_state=None,
) -> MatchOutcome:
    """One pool's match cycle end to end (prepare -> solve -> finalize)."""
    import time as _time

    # the cycle's data-plane scope wraps every transfer-bearing section
    # (tensor build H2D, solve-fetch D2H) so byte counts attribute to
    # THIS (pool, cycle) record; the CPU-fallback solve is pure numpy
    # and deliberately outside — it moves no device bytes
    with data_plane.activate(flight.dp), flight.phase("tensor_build"):
        prepared = prepare_pool_problem(
            store, pool, queue, clusters, config, state,
            launch_filter=launch_filter, host_reservations=host_reservations,
            host_attrs=host_attrs, flight=flight, encode_cache=encode_cache,
            predictor=predictor, device_state=device_state,
        )
    assignment = np.empty(0, dtype=np.int32)
    if prepared.solvable:
        use_cpu, fb_reason = check_device_fallback(config, state, telemetry,
                                                   pool.name)
        if not use_cpu:
            # the solve is the cycle's device section: the inline fetch
            # blocks until the kernel's result is materialized, so this
            # phase's wall time covers dispatch + device execution +
            # transfer (the pipelined engine splits these two calls
            # across pools instead)
            t_solve = _time.perf_counter()
            try:
                with data_plane.activate(flight.dp), \
                        data_plane.family(data_plane.FAM_SOLVE), \
                        flight.phase("solve", device=True):
                    assignment = dispatch_pool_solve(
                        prepared, config, telemetry=telemetry).fetch()
            except Exception:  # noqa: BLE001 — classified below
                if config.device_fallback_cycles <= 0:
                    raise
                # reaction (c): the failing cycle is re-solved host-side
                # NOW — no cycle is lost to a sick device — and the pool
                # stays on the CPU reference until the next probe
                log.exception("pool %s device solve failed; falling back "
                              "to %s", pool.name, FALLBACK_BACKEND)
                enter_device_fallback(state, config, pool.name,
                                      "solve-error")
                use_cpu, fb_reason = True, "solve-error"
            else:
                record_solve_outcome(prepared, assignment, config, state,
                                     pool.name,
                                     _time.perf_counter() - t_solve,
                                     flight, telemetry)
                exit_device_fallback(state, telemetry, pool.name)
        if use_cpu:
            try:
                with flight.phase("solve", device=False):
                    assignment = cpu_fallback_solve(prepared, config)
            except Exception:  # noqa: BLE001 — the fallback solver
                # failing too must not escape the cycle
                log.exception("cpu fallback solve failed (pool %s)",
                              pool.name)
                return degrade_to_solve_failed(prepared, config, state,
                                               flight,
                                               record_placement_failure)
            record_fallback_outcome(prepared, pool.name, state, flight,
                                    telemetry, fb_reason)
    # launch is scope-activated too: the balanced-group topup's D2H
    # slice-back happens in finalize and belongs to this cycle
    with data_plane.activate(flight.dp), flight.phase("launch"):
        return finalize_pool_match(
            store, prepared, assignment, config, state, clusters,
            make_task_id=make_task_id,
            record_placement_failure=record_placement_failure,
            flight=flight,
        )


def match_pools_batched(
    store: JobStore,
    pools: Sequence[Pool],
    queues: dict[str, RankedQueue],
    clusters: Sequence[ComputeCluster],
    config: MatchConfig,
    states: dict[str, PoolMatchState],
    *,
    make_task_id: Callable[[Job], str],
    launch_filter: Optional[Callable[[Job], bool]] = None,
    record_placement_failure: Optional[Callable[[Job, str], None]] = None,
    host_reservations: Optional[dict[str, str]] = None,
    host_attrs: Optional[dict[str, dict]] = None,
    mesh=None,
    flights: Optional[dict] = None,
    telemetry=None,
    encode_cache=None,
    predictor=None,
    device_state=None,
) -> dict[str, MatchOutcome]:
    """Solve EVERY pool's match problem in one batched device call.

    This is the BASELINE config-5 path (SURVEY §2.4): pools become the
    leading batch axis of a single pjit'd solve, sharded across the mesh so
    each device handles a slice of pools concurrently — where the reference
    round-robins pools on one thread (scheduler.clj:2508-2517).  All pools'
    problems are padded to shared (J, N) buckets; per-pool transactions and
    launches then run host-side exactly as in the per-pool path.
    """
    import jax
    import jax.numpy as jnp

    from cook_tpu.parallel.mesh import pool_sharded_match, shard_pools

    flights = flights or {}
    for f in flights.values():
        if f.record is not None:
            f.record.batched = True

    def pool_flight(pool_name: str):
        return flights.get(pool_name, NULL_CYCLE)

    prepared_list = []
    for pool in pools:
        flight = pool_flight(pool.name)
        # per-pool scope around the build: each pool's H2D attributes to
        # its own record (the SHARED batch solve below runs scope-less —
        # its fetch lands in the ledger totals once, never per-pool, so
        # nothing double-counts)
        with data_plane.activate(flight.dp), flight.phase("tensor_build"):
            prepared_list.append(prepare_pool_problem(
                store, pool, queues[pool.name], clusters, config,
                states[pool.name], launch_filter=launch_filter,
                host_reservations=host_reservations, host_attrs=host_attrs,
                flight=flight, encode_cache=encode_cache,
                predictor=predictor, device_state=device_state,
            ))
    # reaction (c) parity with the per-pool paths: pools already in
    # fallback mode solve host-side this cycle; the rest join the batch
    # (a pool whose budget just ran out rejoins — the batch solve IS its
    # device probe)
    cpu_solving: dict[str, str] = {}  # pool -> fallback reason
    solvable = []
    hier_pools = []
    for p in prepared_list:
        if not p.solvable:
            continue
        use_cpu, fb_reason = check_device_fallback(
            config, states[p.pool.name], telemetry, p.pool.name)
        if use_cpu:
            cpu_solving[p.pool.name] = fb_reason
        elif hierarchical_enabled(config, p.problem):
            # a pool at/over the hierarchical threshold must not ride
            # the flat batched kernel (the intractable [J, N] wall the
            # decomposition exists to avoid): it solves through the
            # two-level path individually, with the same fault point
            # and fallback ladder as the serial/pipelined routes
            hier_pools.append(p)
        else:
            solvable.append(p)
    batch_assignments: dict[str, np.ndarray] = {}
    hier_solved: set = set()
    if hier_pools:
        import time as _time

        fault_schedule = faults.ACTIVE  # snapshot (see flat branch)
        for p in hier_pools:
            name = p.pool.name
            flight = pool_flight(name)
            t_solve = _time.perf_counter()
            try:
                if fault_schedule is not None:
                    fault_schedule.hit(faults.DEVICE_SOLVE, pool=name)
                with data_plane.activate(flight.dp), \
                        flight.phase("solve", device=True):
                    assignment = HierarchicalPending(p, config,
                                                     telemetry).fetch()
            except Exception:  # noqa: BLE001 — classified below
                if config.device_fallback_cycles <= 0:
                    raise
                # reaction (c): this pool re-solves host-side below; the
                # OTHER pools' batch proceeds untouched
                log.exception("hierarchical solve failed (pool %s); "
                              "falling back to %s", name, FALLBACK_BACKEND)
                enter_device_fallback(states[name], config, name,
                                      "solve-error")
                cpu_solving[name] = "solve-error"
                continue
            record_solve_outcome(p, assignment, config, states[name],
                                 name, _time.perf_counter() - t_solve,
                                 flight, telemetry)
            exit_device_fallback(states[name], telemetry, name)
            batch_assignments[name] = assignment
            hier_solved.add(name)
    if solvable:
        import time as _time

        try:
            fault_schedule = faults.ACTIVE  # snapshot: a concurrent
            if fault_schedule is not None:  # disarm must not None out
                # the global mid-site.  `device.solve` fault point,
                # batched flavor: rules match per participating pool; one
                # injected error fails the SHARED solve (a sick device
                # takes the whole batch down, so the whole batch degrades)
                for p in solvable:
                    fault_schedule.hit(faults.DEVICE_SOLVE,
                                       pool=p.pool.name)
            t_stack = _time.perf_counter()
            # pad every pool's problem to shared buckets and stack
            max_j = max(p.problem.demands.shape[0] for p in solvable)
            max_n = max(p.problem.avail.shape[0] for p in solvable)

            # the stack below needs one pytree structure across pools: if
            # ANY pool carries a topology node_bonus, every lane gets one
            # (zeros = no preference, decision-identical to absent)
            any_bonus = any(p.problem.node_bonus is not None
                            for p in solvable)

            def pad_problem(problem: MatchProblem) -> MatchProblem:
                j, n = problem.demands.shape[0], problem.avail.shape[0]
                bonus = None
                if any_bonus:
                    raw = (problem.node_bonus
                           if problem.node_bonus is not None
                           else jnp.zeros(n, problem.avail.dtype))
                    bonus = jnp.pad(raw, (0, max_n - n))
                return MatchProblem(
                    demands=jnp.pad(problem.demands,
                                    ((0, max_j - j), (0, 0))),
                    job_valid=jnp.pad(problem.job_valid, (0, max_j - j)),
                    avail=jnp.pad(problem.avail, ((0, max_n - n), (0, 0))),
                    totals=jnp.pad(problem.totals, ((0, max_n - n), (0, 0))),
                    node_valid=jnp.pad(problem.node_valid, (0, max_n - n)),
                    feasible=jnp.pad(problem.feasible,
                                     ((0, max_j - j), (0, max_n - n))),
                    node_bonus=bonus,
                )

            padded_problems = [pad_problem(p.problem) for p in solvable]
            if mesh is not None:
                # pool-axis padding: the sharded path previously only
                # engaged when the pool count happened to divide the mesh
                # size; pad with all-invalid problems (job_valid/
                # node_valid False — the kernels place nothing there) so
                # it engages for ANY count, and the padded batch shape
                # stays one XLA program per (ceil-multiple, J, N) bucket
                # instead of one per pool count
                from cook_tpu.parallel.mesh import invalid_match_problem

                n_pad = (-len(solvable)) % mesh.devices.size
                if n_pad:
                    pad_p = invalid_match_problem(
                        max_j, max_n,
                        n_res=int(solvable[0].problem.demands.shape[-1]),
                        dtype=solvable[0].problem.demands.dtype)
                    if any_bonus:
                        pad_p = pad_p._replace(node_bonus=jnp.zeros(
                            max_n, solvable[0].problem.avail.dtype))
                    padded_problems.extend([pad_p] * n_pad)
            stacked = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *padded_problems,
            )
            # the shared pad/stack is host work, not solve time — credit
            # it as tensor_build so device_s stays an honest accelerator
            # figure
            stack_s = _time.perf_counter() - t_stack
            for p in solvable:
                pool_flight(p.pool.name).add_phase("tensor_build", stack_s)
            t_solve = _time.perf_counter()
            if mesh is not None:
                stacked = shard_pools(mesh, stacked)
                result = pool_sharded_match(mesh, stacked,
                                            chunk=config.chunk or 0,
                                            rounds=config.chunk_rounds,
                                            passes=config.chunk_passes,
                                            kc=config.chunk_kc,
                                            backend=config.backend)
            elif config.chunk:
                result = jax.vmap(
                    lambda p: chunked_match(
                        p, chunk=config.chunk,
                        rounds=config.chunk_rounds,
                        passes=config.chunk_passes,
                        kc=config.chunk_kc,
                        **backend_flags(vmap_safe_backend(config.backend)))
                )(stacked)
            else:
                result = jax.vmap(greedy_match)(stacked)
            with data_plane.family(data_plane.FAM_SOLVE):
                assignments = fetch_result(result.assignment)
        except Exception:  # noqa: BLE001 — classified below
            if config.device_fallback_cycles <= 0:
                raise
            # reaction (c), batched: the failing batch is re-solved
            # host-side pool by pool NOW — no cycle is lost to a sick
            # device — and every participating pool stays on the CPU
            # reference until its next probe
            log.exception("batched device solve failed (%d pools); "
                          "falling back to %s", len(solvable),
                          FALLBACK_BACKEND)
            for p in solvable:
                enter_device_fallback(states[p.pool.name], config,
                                      p.pool.name, "solve-error")
                cpu_solving[p.pool.name] = "solve-error"
        else:
            # one shared device call solved every pool: each
            # participating pool's record carries the full solve wall
            # time (no pool's cycle can finish sooner than the batch).
            # The recorded shape is the PADDED pool axis — the device
            # truth the compile observatory keys programs by
            solve_s = _time.perf_counter() - t_solve
            batch_shape = (len(padded_problems), max_j, max_n)
            backend = (vmap_safe_backend(config.backend) if config.chunk
                       else "exact")
            compiled = False
            if telemetry is not None:
                compiled = telemetry.record_batched_match_solve(
                    [p.pool.name for p in solvable], batch_shape, backend,
                    solve_s)
            for i, p in enumerate(solvable):
                flight = pool_flight(p.pool.name)
                flight.add_phase("solve", solve_s, device=True)
                flight.note_solve(shape_signature(batch_shape), backend,
                                  compiled)
                batch_assignments[p.pool.name] = \
                    assignments[i][: len(p.considerable)]
                # the batch solve doubles as the device probe for any
                # pool whose fallback budget just ran out
                exit_device_fallback(states[p.pool.name], telemetry,
                                     p.pool.name)

    outcomes: dict[str, MatchOutcome] = {}
    for prepared in prepared_list:
        name = prepared.pool.name
        flight = pool_flight(name)
        assignment = np.empty(0, dtype=np.int32)
        if name in batch_assignments:
            assignment = batch_assignments[name]
            # hierarchically-solved pools already went through
            # record_solve_outcome (quality observe + hier record note);
            # re-observing here would double-count the sample
            if name not in hier_solved and telemetry is not None:
                telemetry.quality.observe_cycle(prepared, assignment, name)
            if config.chunk and name not in hier_solved:
                st = states[name]
                st.chunked_solves += 1
                if (config.quality_audit_every
                        and st.chunked_solves
                        % config.quality_audit_every == 0):
                    start_quality_audit(prepared, assignment, name)
        elif name in cpu_solving:
            try:
                with flight.phase("solve", device=False):
                    assignment = cpu_fallback_solve(prepared, config)
            except Exception:  # noqa: BLE001 — the fallback solver
                # failing too must not escape the cycle
                log.exception("cpu fallback solve failed (pool %s)", name)
                outcomes[name] = degrade_to_solve_failed(
                    prepared, config, states[name], flight,
                    record_placement_failure)
                continue
            record_fallback_outcome(prepared, name, states[name], flight,
                                    telemetry, cpu_solving[name])
        with data_plane.activate(flight.dp), flight.phase("launch"):
            outcomes[name] = finalize_pool_match(
                store, prepared, assignment, config, states[name],
                clusters, make_task_id=make_task_id,
                record_placement_failure=record_placement_failure,
                flight=flight,
            )
    return outcomes


def _apply_backoff(config: MatchConfig, state: PoolMatchState,
                   head_matched: bool) -> None:
    if head_matched:
        state.num_considerable = config.max_jobs_considered
        state.iterations_at_floor = 0
    else:
        shrunk = max(1, int(state.num_considerable * config.scaleback))
        if shrunk == state.num_considerable:
            state.iterations_at_floor += 1
            if state.iterations_at_floor >= config.floor_iterations_before_reset:
                state.num_considerable = config.max_jobs_considered
                state.iterations_at_floor = 0
                return
        state.num_considerable = shrunk


def _failure_reason(job: Job, nodes: EncodedNodes,
                    feas_row: np.ndarray) -> str:
    """Machine-readable reason code for an unmatched job; the operator-
    facing text is flight_recorder.REASON_TEXT[code] (one source, so
    /unscheduled_jobs and the cycle record can never diverge)."""
    if nodes.n == 0:
        return flight_codes.NO_OFFERS
    if not feas_row.any():
        return flight_codes.CONSTRAINTS_FILTERED
    return flight_codes.INSUFFICIENT_RESOURCES
