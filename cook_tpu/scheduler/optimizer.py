"""Pluggable optimizer: a planning loop above the matcher.

Reference: cook.scheduler.optimizer (/root/reference/scheduler/src/cook/
scheduler/optimizer.clj + docs/optimizer.md): protocols `HostFeed`
(purchasable host types) and `Optimizer` (`produce_schedule(queue, running,
available, host_infos)` -> {time-offset -> {:suggested-matches ...}}), with
no-op defaults, driven by a periodic cycle.  The output's consumers are
intentionally unspecified (the reference never wired one in production);
autoscaling hints are the natural consumer here.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Sequence

from cook_tpu.models.entities import Job


@dataclass(frozen=True)
class HostInfo:
    host_type: str
    count: int
    cpus: float
    mem: float
    gpus: float = 0.0


class HostFeed(ABC):
    @abstractmethod
    def get_available_host_info(self) -> Sequence[HostInfo]: ...


class Optimizer(ABC):
    @abstractmethod
    def produce_schedule(
        self,
        queue: Sequence[Job],
        running: Sequence[Job],
        available: dict[str, Any],
        host_infos: Sequence[HostInfo],
    ) -> dict[int, dict]:
        """Returns {seconds-from-now: {"suggested-matches": ...,
        "suggested-purchases": ...}}."""


class NoOpHostFeed(HostFeed):
    def get_available_host_info(self) -> Sequence[HostInfo]:
        return []


class NoOpOptimizer(Optimizer):
    def produce_schedule(self, queue, running, available, host_infos):
        return {0: {"suggested-matches": {}, "suggested-purchases": {}}}


class BacklogPurchaseOptimizer(Optimizer):
    """A working optimizer: size purchase suggestions to the pending
    backlog.  For each purchasable host type, suggest enough hosts to
    absorb the queued demand that current capacity can't, greedily
    cheapest-fit by resource volume.  (The reference ships only the no-op;
    this demonstrates the seam with a real planner.)"""

    def __init__(self, *, horizon_s: int = 300, max_hosts_per_type: int = 64):
        self.horizon_s = horizon_s
        self.max_hosts_per_type = max_hosts_per_type

    def produce_schedule(self, queue, running, available, host_infos):
        need_mem = sum(j.resources.mem for j in queue)
        need_cpus = sum(j.resources.cpus for j in queue)
        need_gpus = sum(j.resources.gpus for j in queue)
        have_mem = float(available.get("mem", 0.0))
        have_cpus = float(available.get("cpus", 0.0))
        gap_mem = max(0.0, need_mem - have_mem)
        gap_cpus = max(0.0, need_cpus - have_cpus)
        purchases: dict[str, int] = {}
        for info in sorted(host_infos, key=lambda i: i.mem * i.cpus):
            if gap_mem <= 0 and gap_cpus <= 0 and need_gpus <= 0:
                break
            count = 0
            while (count < min(info.count, self.max_hosts_per_type)
                   and (gap_mem > 0 or gap_cpus > 0
                        or (need_gpus > 0 and info.gpus > 0))):
                gap_mem -= info.mem
                gap_cpus -= info.cpus
                if info.gpus:
                    need_gpus -= info.gpus
                count += 1
            if count:
                purchases[info.host_type] = count
        return {0: {"suggested-matches": {},
                    "suggested-purchases": purchases}}


@dataclass
class OptimizerCycle:
    """optimizer-cycle! (optimizer.clj:90): gather inputs, call the
    optimizer, sanity-check the output shape, publish the latest plan."""

    host_feed: HostFeed = field(default_factory=NoOpHostFeed)
    optimizer: Optimizer = field(default_factory=NoOpOptimizer)
    latest_schedule: dict = field(default_factory=dict)

    def run(self, queue: Sequence[Job], running: Sequence[Job],
            available: dict[str, Any]) -> dict:
        host_infos = self.host_feed.get_available_host_info()
        schedule = self.optimizer.produce_schedule(
            queue, running, available, host_infos
        )
        if not isinstance(schedule, dict) or not all(
            isinstance(k, int) for k in schedule
        ):
            raise ValueError(f"malformed optimizer schedule: {schedule!r}")
        self.latest_schedule = schedule
        return schedule
