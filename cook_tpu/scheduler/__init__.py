"""Scheduler core: rank/match/rebalance cycles over the JAX kernels."""
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig  # noqa: F401
from cook_tpu.scheduler.matcher import MatchConfig  # noqa: F401
from cook_tpu.scheduler.rebalancer import RebalancerParams  # noqa: F401
