"""Batched sandbox-location / exit-code publisher.

Reference: cook.mesos.sandbox (/root/reference/scheduler/src/cook/mesos/
sandbox.clj): executor messages carrying sandbox directories and exit codes
are accumulated and written to the store in batches on a timer, with an
aggregator map keyed by task id (publishing one-by-one would hammer the
transactor).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from cook_tpu.models.store import JobStore


@dataclass
class _Pending:
    sandbox: Optional[str] = None
    exit_code: Optional[int] = None


class SandboxPublisher:
    def __init__(self, store: JobStore, *, batch_size: int = 512):
        self.store = store
        self.batch_size = batch_size
        self._pending: dict[str, _Pending] = {}
        self._lock = threading.Lock()

    def record_sandbox(self, task_id: str, sandbox: str) -> None:
        with self._lock:
            self._pending.setdefault(task_id, _Pending()).sandbox = sandbox

    def record_exit_code(self, task_id: str, exit_code: int) -> None:
        with self._lock:
            self._pending.setdefault(task_id, _Pending()).exit_code = exit_code

    def publish(self) -> int:
        with self._lock:
            batch = list(self._pending.items())[: self.batch_size]
            for task_id, _ in batch:
                del self._pending[task_id]
        for task_id, pending in batch:
            self.store.set_instance_output(
                task_id,
                exit_code=pending.exit_code,
                sandbox_directory=pending.sandbox,
            )
        return len(batch)

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
