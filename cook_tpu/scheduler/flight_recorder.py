"""Scheduler flight recorder: per-cycle structured decision records.

The reference scheduler is operable because every match cycle leaves a
trail — ~200 named metrics, `with-duration` around every hot section,
and per-job "why is this unscheduled" attribution (unscheduled.clj).
This module is the rebuild's equivalent of that trail condensed into one
artifact: every match cycle emits a `CycleRecord` holding

  * per-phase wall durations (rank, tensor_build, solve, launch,
    preemption_search), split into device vs host time — the solve runs
    on the accelerator, everything else is host matchmaking;
  * the jobs considered, matched (with host + task id), and skipped,
    each skip carrying a machine-readable reason code;
  * preemption victims with the DRU score that sentenced them;
  * offer/node/queue counts.

Records sit in a bounded ring served at `GET /debug/cycles` (rest/api.py)
and are dumped by the simulator for offline analysis.  The recorder also
keeps a bounded per-job index of the LAST cycle decision so
`/unscheduled_jobs` can answer with the real reason code instead of a
static guess.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

from cook_tpu.obs import data_plane
from cook_tpu.utils.metrics import global_registry

# ---------------------------------------------------------------- reason codes
# Machine-readable per-job outcomes of one match cycle.  These are the
# matcher's decisions, distinct from instance failure reasons
# (models/reasons.py) which describe how a RUNNING attempt died.

MATCHED = "matched"
NO_OFFERS = "no-offers"
CONSTRAINTS_FILTERED = "all-nodes-filtered-by-constraints"
INSUFFICIENT_RESOURCES = "insufficient-resources"
LAUNCH_CAP = "cluster-launch-cap"
PORTS_EXHAUSTED = "ports-exhausted"
LAUNCH_VETOED = "launch-vetoed"
LAUNCH_FAILED = "launch-failed"
SOLVE_FAILED = "solve-failed"
NOT_CONSIDERED = "not-considered"
EXCEEDS_POOL_CAPACITY = "exceeds-pool-capacity"
CLUSTER_CIRCUIT_OPEN = "cluster-circuit-open"
GANG_INCOMPLETE = "gang-incomplete"

REASON_TEXT = {
    NO_OFFERS: "no offers",
    CONSTRAINTS_FILTERED: "all nodes filtered by constraints",
    INSUFFICIENT_RESOURCES: "insufficient resources on feasible nodes",
    LAUNCH_CAP: "cluster launch rate/cap reached this cycle",
    PORTS_EXHAUSTED: "insufficient free ports on the matched node",
    LAUNCH_VETOED: "launch transaction vetoed (job changed state mid-cycle)",
    LAUNCH_FAILED: "backend launch RPC failed after the match transacted",
    SOLVE_FAILED: "the pool's device solve raised; jobs wait a cycle",
    NOT_CONSIDERED: "not in this cycle's considerable window",
    EXCEEDS_POOL_CAPACITY:
        "the job's resource demands exceed every host in the pool",
    CLUSTER_CIRCUIT_OPEN:
        "the pool's clusters are circuit-open (launch/kill RPCs failing);"
        " jobs wait for the breaker's half-open probe instead of burning"
        " mea-culpa retries",
    GANG_INCOMPLETE:
        "the job's gang could not place whole (all members on distinct"
        " hosts inside one topology block); the matcher's all-or-nothing"
        " rule holds the whole gang back",
}


@dataclass
class PreemptionRecord:
    """One rebalancer decision: who was killed, for whom, and why."""

    job_uuid: str                 # the beneficiary the room was made for
    hostname: str
    task_ids: list[str]           # victims
    min_preempted_dru: float      # the DRU score that justified the kill
    preemptor_user: str = ""      # the beneficiary's user
    # per-victim fairness detail: [{task_id, user, dru, wasted_s, ...}]
    victims: list[dict] = field(default_factory=list)
    wasted_s: float = 0.0         # victim runtime destroyed, seconds

    def to_json(self) -> dict:
        return {
            "job": self.job_uuid,
            "hostname": self.hostname,
            "task_ids": list(self.task_ids),
            "dru": self.min_preempted_dru,
            "preemptor_user": self.preemptor_user,
            "victims": [dict(v) for v in self.victims],
            "wasted_s": self.wasted_s,
        }


@dataclass
class CycleRecord:
    """One match cycle's full decision record."""

    cycle_id: int
    pool: str
    t_ms: int                     # store clock at cycle start (virtual ms)
    wall_time: float              # epoch seconds at cycle start
    batched: bool = False         # solved via the pool-batched device call
    # pipelined-cycle overlap accounting (scheduler/pipeline.py): the
    # pass dispatches pool k's solve asynchronously and runs pool k±1's
    # host phases while the device executes, so the summed per-pool phase
    # time exceeds the pass's wall time.  pipeline_wall_s is the WHOLE
    # pipelined pass's wall (shared by every participating record);
    # overlap_s / overlap_fraction quantify how much host+device time ran
    # concurrently (0 on the serial paths).
    pipelined: bool = False
    pipeline_wall_s: float = 0.0
    overlap_s: float = 0.0
    overlap_fraction: float = 0.0
    # prediction-assisted speculation (scheduler/prediction.py): was this
    # cycle served from a speculative solve dispatched while the PREVIOUS
    # cycle drained?  `speculation` is the commit attempt's outcome
    # ("hit" | "dropped" | "none"; "" on schedulers without a speculator)
    # and `speculation_drop` the drop/skip reason (epoch-stale /
    # prediction-miss / offers-changed / queue-shifted / predictor-cold /
    # disabled / solve-error)
    speculative: bool = False
    speculation: str = ""
    speculation_drop: str = ""
    phases: dict[str, float] = field(default_factory=dict)   # name -> seconds
    device_s: float = 0.0
    host_s: float = 0.0
    total_s: float = 0.0
    # device truth for the cycle's solve (obs/ telemetry): the padded
    # problem shape the kernel actually compiled for ("jobs x nodes"),
    # the candidate-pass backend, and whether THIS solve paid a JIT
    # compile (first-seen shape) — so a slow cycle is attributable to
    # compilation vs execution from the record alone
    solve_shape: str = ""
    backend: str = ""
    compiled: bool = False
    # hierarchical two-level solve accounting (ops/hierarchical.py):
    # set when the cycle's solve decomposed into topology blocks.  The
    # coarse/fine/refine walls live OUTSIDE `phases` on purpose — they
    # are sub-spans of the cycle's one `solve` phase, and folding them
    # into `phases` would double-count device_s/host_s and the pipelined
    # overlap accounting.  block_stats carries per-block {jobs, placed}
    # for the round-0 scatter (bounded: one entry per topology block).
    hierarchical: bool = False
    hier_blocks: int = 0
    # superblock (DCN-domain) count when the mega-scale layer engaged
    # (0 = off/degenerate); the per-level wall split rides in
    # hier_phases ("super_coarse_solve" joins the three classic keys)
    hier_superblocks: int = 0
    hier_phases: dict = field(default_factory=dict)
    hier_spilled: int = 0
    hier_refine_placed: int = 0
    block_stats: list[dict] = field(default_factory=list)
    # gang scheduling (scheduler/gang.py + ops/gang.py): per-cycle gang
    # accounting — gangs in the considerable window, gangs fully placed,
    # gangs blocked, and the blocking-reason split ({reason: count},
    # e.g. "no-block-capacity" / "members-missing") — so /debug/cycles
    # answers "why did the gang wait" without replaying the solve
    gangs_considered: int = 0
    gangs_placed: int = 0
    gangs_blocked: int = 0
    gang_block_reasons: dict = field(default_factory=dict)
    # per-pool capacity snapshot at cycle start ({hosts, mem, cpus,
    # spare_*}) + the elastic plan id in force — so a capacity delta
    # (cook_tpu/elastic/) correlates with match outcomes record-to-record
    pool_capacity: dict = field(default_factory=dict)
    elastic_plan: int = 0
    # data-plane accounting (obs/data_plane.py): logical host<->device
    # bytes this cycle moved, the fraction of encode-row bytes freshly
    # recomputed (1 - this = re-transferred unchanged — the waste a
    # device-resident encode cache removes), the padded-bucket waste of
    # the tensors built, and the per-tensor-family breakdown.  None =
    # the cycle built/encoded nothing (idle pool, speculative hit)
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    rebuild_fraction: Optional[float] = None
    padding_waste: Optional[float] = None
    data_plane: dict = field(default_factory=dict)
    # device-resident match state (scheduler/device_state.py): set when
    # the cycle's tensors came from the resident mirror — resident
    # buffer bytes, delta rows scattered vs full rebuild (+ reason),
    # the update-kernel wall, and whether the cost tensors were bf16
    device_state: dict = field(default_factory=dict)
    offers: int = 0
    queue_len: int = 0
    considered: int = 0
    # queued jobs outside this cycle's considerable window (count only —
    # their uuids go to the per-job reason index, not the record, which
    # would otherwise bloat by O(queue) every cycle)
    not_considered: int = 0
    head_matched: bool = True
    # [{job, host, task_id}] / [{job, code, detail}]
    matched: list[dict] = field(default_factory=list)
    skipped: list[dict] = field(default_factory=list)
    preemptions: list[PreemptionRecord] = field(default_factory=list)
    # fairness rollup for the cycle's rebalance pass (obs/fairness.py):
    # {preemptions, tasks_preempted, wasted_s, jain_index}
    fairness: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "cycle": self.cycle_id,
            "pool": self.pool,
            "t_ms": self.t_ms,
            "wall_time": self.wall_time,
            "batched": self.batched,
            "pipelined": self.pipelined,
            "pipeline_wall_s": self.pipeline_wall_s,
            "overlap_s": self.overlap_s,
            "overlap_fraction": self.overlap_fraction,
            "speculative": self.speculative,
            "speculation": self.speculation,
            "speculation_drop": self.speculation_drop,
            "phases": dict(self.phases),
            "device_s": self.device_s,
            "host_s": self.host_s,
            "total_s": self.total_s,
            "solve_shape": self.solve_shape,
            "backend": self.backend,
            "compiled": self.compiled,
            "hierarchical": self.hierarchical,
            "hier_blocks": self.hier_blocks,
            "hier_superblocks": self.hier_superblocks,
            "hier_phases": dict(self.hier_phases),
            "hier_spilled": self.hier_spilled,
            "hier_refine_placed": self.hier_refine_placed,
            "block_stats": list(self.block_stats),
            "gangs_considered": self.gangs_considered,
            "gangs_placed": self.gangs_placed,
            "gangs_blocked": self.gangs_blocked,
            "gang_block_reasons": dict(self.gang_block_reasons),
            "pool_capacity": dict(self.pool_capacity),
            "elastic_plan": self.elastic_plan,
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "rebuild_fraction": self.rebuild_fraction,
            "padding_waste": self.padding_waste,
            "data_plane": dict(self.data_plane),
            "device_state": dict(self.device_state),
            "offers": self.offers,
            "queue_len": self.queue_len,
            "considered": self.considered,
            "not_considered": self.not_considered,
            "matched_count": len(self.matched),
            "skipped_count": len(self.skipped),
            "head_matched": self.head_matched,
            "matched": list(self.matched),
            "skipped": list(self.skipped),
            "preemptions": [p.to_json() for p in self.preemptions],
            "fairness": dict(self.fairness),
        }


class CycleBuilder:
    """Mutable collector one match cycle writes into.

    Single-threaded by construction: one builder per (pool, cycle), used
    only on the cycle's driving thread.  `FlightRecorder.commit` freezes
    it into a CycleRecord."""

    def __init__(self, cycle_id: int, pool: str, t_ms: int):
        self.record = CycleRecord(cycle_id=cycle_id, pool=pool, t_ms=t_ms,
                                  wall_time=time.time())
        # uuids queued but outside the considerable window; indexed at
        # commit, never stored on the record (O(queue) per cycle)
        self.not_considered: list[str] = []
        # rank context for the per-job history (set by the matcher's
        # prepare step): REFERENCES to the cycle's ranked queue — stable
        # for the cycle's lifetime (rank_cycle replaces, never mutates)
        self.rank_jobs: Optional[list] = None
        self.rank_dru: Optional[dict] = None
        # per-cycle data-plane scope: the match paths activate it around
        # their prepare/solve/launch sections (data_plane.activate) so
        # transfer/residency/padding notes attribute to THIS cycle even
        # under pipelined overlap; finish() folds it into the record
        self.dp = data_plane.CycleDataPlane(pool, cycle_id)
        self._t0 = time.perf_counter()

    @contextmanager
    def phase(self, name: str, device: bool = False):
        """Time one phase; device=True attributes it to accelerator time."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_phase(name, time.perf_counter() - t0, device=device)

    def add_phase(self, name: str, seconds: float,
                  device: bool = False) -> None:
        """Credit an externally-timed duration to a phase (the batched
        multi-pool solve is one shared device call; its wall time is
        attributed to every participating pool's record)."""
        rec = self.record
        rec.phases[name] = rec.phases.get(name, 0.0) + seconds
        if device:
            rec.device_s += seconds
        else:
            rec.host_s += seconds

    def set_counts(self, *, offers: Optional[int] = None,
                   queue_len: Optional[int] = None,
                   considered: Optional[int] = None) -> None:
        if offers is not None:
            self.record.offers = offers
        if queue_len is not None:
            self.record.queue_len = queue_len
        if considered is not None:
            self.record.considered = considered

    def note_solve(self, shape_sig: str, backend: str,
                   compiled: bool) -> None:
        """Record the cycle's device-solve identity (padded shape,
        backend, compile-paid flag) from the obs/ telemetry layer."""
        self.record.solve_shape = shape_sig
        self.record.backend = backend
        self.record.compiled = compiled

    def set_rank_context(self, jobs, dru) -> None:
        """Attach the cycle's ranked queue (jobs list + uuid->DRU map) so
        commit can stamp each job's history entry with its rank position
        and DRU score — the timeline's placement attribution."""
        self.rank_jobs = jobs
        self.rank_dru = dru

    def note_speculation(self, status: str, reason: str = "") -> None:
        """Record the cycle's speculation-commit outcome ("hit" /
        "dropped" / "none") and, for drops/skips, the reason code
        (scheduler/prediction.py DROP_* constants)."""
        self.record.speculation = status
        self.record.speculation_drop = reason
        self.record.speculative = status == "hit"

    def note_hierarchical(self, stats: dict) -> None:
        """Fold a two-level solve's accounting (ops/hierarchical.py
        stats) into the record: block geometry, coarse/fine/refine walls,
        spill/refine counts, per-block jobs/placed."""
        rec = self.record
        rec.hierarchical = True
        rec.hier_blocks = int(stats.get("blocks", 0))
        rec.hier_superblocks = int(stats.get("superblocks", 0))
        rec.hier_phases = {
            "coarse_solve": stats.get("coarse_s", 0.0),
            "fine_solve": stats.get("fine_s", 0.0),
            "refine": stats.get("refine_s", 0.0),
        }
        if rec.hier_superblocks >= 2:
            # the super-coarse wall only exists when the DCN-domain layer
            # engaged; classic two-level records keep their shape
            rec.hier_phases["super_coarse_solve"] = \
                stats.get("super_coarse_s", 0.0)
        rec.hier_spilled = int(stats.get("spilled", 0))
        rec.hier_refine_placed = int(stats.get("refine_placed", 0))
        rec.block_stats = list(stats.get("block_stats", []))

    def note_device_state(self, stats: dict) -> None:
        """Record the cycle's device-resident state outcome
        (scheduler/device_state.py build stats: resident bytes, delta
        rows vs rebuild, update-kernel wall)."""
        self.record.device_state = {
            k: v for k, v in stats.items() if not k.startswith("_")}

    def note_gang(self, *, considered: int, placed: int, blocked: int,
                  reasons: Optional[dict] = None) -> None:
        """Record the cycle's gang outcome (matcher finalize chokepoint):
        gangs considered/fully-placed/blocked plus the blocking-reason
        split ({reason: count})."""
        rec = self.record
        rec.gangs_considered = considered
        rec.gangs_placed = placed
        rec.gangs_blocked = blocked
        rec.gang_block_reasons = dict(reasons or {})

    def note_match(self, job_uuid: str, hostname: str, task_id: str) -> None:
        self.record.matched.append(
            {"job": job_uuid, "host": hostname, "task_id": task_id})

    def note_skip(self, job_uuid: str, code: str, detail: str = "") -> None:
        self.record.skipped.append(
            {"job": job_uuid, "code": code,
             "detail": detail or REASON_TEXT.get(code, "")})

    def note_not_considered(self, job_uuid: str) -> None:
        self.not_considered.append(job_uuid)

    def note_preemption(self, preemption: PreemptionRecord) -> None:
        self.record.preemptions.append(preemption)

    def finish(self) -> CycleRecord:
        rec = self.record
        rec.h2d_bytes = self.dp.h2d_bytes
        rec.d2h_bytes = self.dp.d2h_bytes
        rec.rebuild_fraction = self.dp.rebuild_fraction
        rec.padding_waste = self.dp.padding_waste
        rec.data_plane = self.dp.families_json()
        if self.record.batched or self.record.pipelined:
            # the pool-batched and pipelined paths start every pool's
            # builder before any pool's work begins, so builder-lifetime
            # elapsed would report the whole PASS's wall time for each
            # pool; the sum of this pool's attributed phases (shared or
            # overlapped solve included) is the honest per-pool figure
            # (the pass wall lives in record.pipeline_wall_s)
            self.record.total_s = self.record.device_s + self.record.host_s
            return self.record
        # rank may have been credited via add_phase from BEFORE the
        # builder existed (a separately-triggered rank cycle): total must
        # still cover every attributed phase
        elapsed = time.perf_counter() - self._t0
        self.record.total_s = max(elapsed,
                                  self.record.device_s + self.record.host_s)
        return self.record


class NullCycle:
    """No-op builder so instrumented code never branches on None.
    `record` is None so call sites can uniformly test `flight.record is
    not None` instead of hasattr (`dp` likewise — data_plane.activate
    treats None as a no-op scope)."""

    record = None
    dp = None

    @contextmanager
    def phase(self, name: str, device: bool = False):
        yield

    def add_phase(self, name: str, seconds: float, device: bool = False) -> None:
        pass

    def set_counts(self, **kw) -> None:
        pass

    def note_solve(self, *a) -> None:
        pass

    def note_match(self, *a) -> None:
        pass

    def note_skip(self, *a, **kw) -> None:
        pass

    def note_not_considered(self, *a) -> None:
        pass

    def note_preemption(self, *a) -> None:
        pass

    def set_rank_context(self, *a) -> None:
        pass

    def note_speculation(self, *a, **kw) -> None:
        pass

    def note_hierarchical(self, *a) -> None:
        pass

    def note_device_state(self, *a) -> None:
        pass

    def note_gang(self, *a, **kw) -> None:
        pass


NULL_CYCLE = NullCycle()


class FlightRecorder:
    """Bounded ring of CycleRecords + per-job last-decision index +
    per-job bounded cycle history (the timeline's substrate)."""

    def __init__(self, capacity: int = 512, job_reason_capacity: int = 100_000,
                 history_per_job: int = 64):
        self._ring: collections.deque[CycleRecord] = collections.deque(
            maxlen=capacity)
        self._by_id: collections.OrderedDict[int, CycleRecord] = \
            collections.OrderedDict()
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        # job uuid -> (cycle_id, code, detail); LRU-bounded (job uuids are
        # minted forever on a long-lived leader)
        self._job_reasons: collections.OrderedDict[str, tuple[int, str, str]] \
            = collections.OrderedDict()
        self._job_reason_capacity = job_reason_capacity
        # job uuid -> deque of per-cycle decision entries ({cycle, t_ms,
        # pool, code, detail, rank?, dru?, host?}), newest last.  Bounded
        # twice: per-job deque maxlen AND LRU over jobs (same budget as
        # the last-decision index) — `GET /jobs/{uuid}/timeline` walks it
        self._history_per_job = history_per_job
        self._job_history: collections.OrderedDict[str, collections.deque] \
            = collections.OrderedDict()

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def begin(self, pool: str, t_ms: int) -> CycleBuilder:
        with self._lock:
            cycle_id = next(self._ids)
        return CycleBuilder(cycle_id, pool, t_ms)

    def commit(self, builder: CycleBuilder) -> CycleRecord:
        record = builder.finish()
        # fold the cycle's data-plane scope into the process ledger
        # (per-pool residency surface + /debug/device cycle ring)
        data_plane.LEDGER.finish_cycle(builder.dp)
        record.not_considered = len(builder.not_considered)
        # rank position + DRU score per uuid for the history entries —
        # O(queue), same order as the not_considered indexing below
        positions: dict[str, int] = {}
        dru = builder.rank_dru or {}
        if builder.rank_jobs is not None:
            positions = {job.uuid: i
                         for i, job in enumerate(builder.rank_jobs)}
        with self._lock:
            self._ring.append(record)
            self._by_id[record.cycle_id] = record
            while len(self._by_id) > self._ring.maxlen:
                self._by_id.popitem(last=False)
            for m in record.matched:
                self._note_reason(m["job"], record.cycle_id, MATCHED,
                                  f"matched to {m['host']}",
                                  record=record, host=m["host"],
                                  rank=positions.get(m["job"]),
                                  dru=dru.get(m["job"]))
            for s in record.skipped:
                self._note_reason(s["job"], record.cycle_id, s["code"],
                                  s.get("detail", ""),
                                  record=record,
                                  rank=positions.get(s["job"]),
                                  dru=dru.get(s["job"]))
            for uuid in builder.not_considered:
                self._note_reason(uuid, record.cycle_id, NOT_CONSIDERED, "",
                                  record=record,
                                  rank=positions.get(uuid),
                                  dru=dru.get(uuid))
        global_registry.histogram(
            "cycle.duration", "total wall seconds per match cycle").observe(
            record.total_s, {"pool": record.pool})
        global_registry.gauge(
            "cycle.device_seconds",
            "accelerator time of the last match cycle").set(
            record.device_s, {"pool": record.pool})
        global_registry.gauge(
            "cycle.host_seconds",
            "host matchmaking time of the last match cycle").set(
            record.host_s, {"pool": record.pool})
        if record.pipelined:
            global_registry.gauge(
                "cycle.overlap_fraction",
                "fraction of the last pipelined pass's summed phase time "
                "that ran concurrently (host/device overlap)").set(
                record.overlap_fraction, {"pool": record.pool})
        return record

    def note_job_reason(self, job_uuid: str, cycle_id: int, code: str,
                        detail: str = "") -> None:
        """Update a job's last-decision index entry outside a cycle
        commit — the async launch fan-out's failure path lands after the
        cycle's record may already be committed, and /unscheduled_jobs
        must still answer `launch-failed` rather than a stale
        `matched`."""
        with self._lock:
            self._note_reason(job_uuid, cycle_id, code,
                              detail or REASON_TEXT.get(code, ""))

    def note_async_launch_failure(self, record: Optional[CycleRecord],
                                  job_uuid: str, code: str,
                                  detail: str = "") -> None:
        """Record an async launch-fan-out failure: appends the skip to
        the cycle record AND updates the per-job index, both under the
        recorder lock.  The callback runs on a cluster launch-worker
        thread and may land before OR after the record committed, so it
        must not touch the CycleBuilder directly (single-threaded by
        construction) — this is the same locked mutate-committed-record
        pattern annotate_preemptions uses, serialized against
        records_json renders and commit."""
        detail = detail or REASON_TEXT.get(code, "")
        with self._lock:
            cycle_id = 0
            if record is not None:
                cycle_id = record.cycle_id
                record.skipped.append(
                    {"job": job_uuid, "code": code, "detail": detail})
            self._note_reason(job_uuid, cycle_id, code, detail,
                              record=record)

    def _note_reason(self, job_uuid: str, cycle_id: int, code: str,
                     detail: str, *, record: Optional[CycleRecord] = None,
                     rank: Optional[int] = None,
                     dru: Optional[float] = None,
                     host: Optional[str] = None) -> None:
        self._job_reasons[job_uuid] = (cycle_id, code, detail)
        self._job_reasons.move_to_end(job_uuid)
        while len(self._job_reasons) > self._job_reason_capacity:
            self._job_reasons.popitem(last=False)
        entry: dict = {"cycle": cycle_id,
                       "t_ms": record.t_ms if record is not None else 0,
                       "pool": record.pool if record is not None else "",
                       "code": code, "detail": detail}
        if rank is not None:
            entry["rank"] = rank
        if dru is not None:
            entry["dru"] = dru
        if host is not None:
            entry["host"] = host
        history = self._job_history.get(job_uuid)
        if history is None:
            history = collections.deque(maxlen=self._history_per_job)
            self._job_history[job_uuid] = history
        history.append(entry)
        self._job_history.move_to_end(job_uuid)
        while len(self._job_history) > self._job_reason_capacity:
            self._job_history.popitem(last=False)

    def annotate_preemptions(self, pool: str,
                             preemptions: list[PreemptionRecord],
                             duration_s: float,
                             fairness: Optional[dict] = None) -> None:
        """Attach a rebalance pass to the pool's most recent cycle record
        (the preemption search runs as a phase of the same scheduling
        cycle); falls back to a standalone record when no match cycle has
        run yet for the pool."""
        with self._lock:
            target = None
            for record in reversed(self._ring):
                if record.pool == pool:
                    target = record
                    break
            if target is None:
                builder = CycleBuilder(next(self._ids), pool, 0)
                target = builder.record
                self._ring.append(target)
                self._by_id[target.cycle_id] = target
            target.phases["preemption_search"] = (
                target.phases.get("preemption_search", 0.0) + duration_s)
            target.host_s += duration_s
            target.total_s += duration_s
            target.preemptions.extend(preemptions)
            if fairness:
                target.fairness.update(fairness)

    # ------------------------------------------------------------------ reads

    def records(self, limit: int = 50,
                pool: Optional[str] = None) -> list[CycleRecord]:
        """Live record references — same-thread (scheduler) use only;
        concurrent readers must use records_json/get_json, which
        serialize under the lock (annotate_preemptions mutates records
        in place)."""
        with self._lock:
            out = [r for r in self._ring if pool is None or r.pool == pool]
        return out[-limit:]

    def get(self, cycle_id: int) -> Optional[CycleRecord]:
        with self._lock:
            return self._by_id.get(cycle_id)

    def records_json(self, limit: int = 50,
                     pool: Optional[str] = None,
                     since: int = 0) -> list[dict]:
        """Snapshot for cross-thread consumers (REST, simulator dump):
        serialized under the lock so a concurrent rebalance annotation
        can't tear a record mid-render.  `since` keeps only records with
        cycle_id > since (cheap incremental slicing for pollers,
        timelines, and incident bundles)."""
        with self._lock:
            out = [r for r in self._ring
                   if (pool is None or r.pool == pool)
                   and r.cycle_id > since]
            return [r.to_json() for r in out[-limit:]]

    def get_json(self, cycle_id: int) -> Optional[dict]:
        with self._lock:
            record = self._by_id.get(cycle_id)
            return None if record is None else record.to_json()

    def job_reason(self, job_uuid: str) -> Optional[tuple[int, str, str]]:
        """(cycle_id, code, detail) of the job's last cycle decision."""
        with self._lock:
            return self._job_reasons.get(job_uuid)

    def job_history(self, job_uuid: str) -> list[dict]:
        """Chronological per-cycle decision entries for one job (bounded
        to the newest `history_per_job`); copied under the lock so the
        timeline render can't race a concurrent commit's append."""
        with self._lock:
            history = self._job_history.get(job_uuid)
            return [dict(e) for e in history] if history is not None else []
