"""Incremental host-encode cache for the match cycle's tensor build.

`prepare_pool_problem` historically re-ran `encode_nodes` (O(N × attrs))
and `feasibility_mask` (O(J × N) bitwork) from scratch every cycle, even
when neither the pool's offers nor its considerable window had changed.
At the headline scale that host work is what the device waits on.  This
cache makes the encode incremental, the same store-event-driven pattern
as the columnar job index (models/columnar.py, ranking_columnar.py):

  * the node encoding is keyed by an OFFER-SET FINGERPRINT — the
    structure-relevant fields of the pool's offers (hostname/node id
    order, attributes, gpu-present flag, free-port count, cluster
    location).  Spare mem/cpus amounts are deliberately excluded: the
    resource fit is the kernel's job, so the encoding only changes when
    offer STRUCTURE changes (host added/removed/rescinded, attrs or
    ports changed);
  * feasibility rows are cached per job against that fingerprint — the
    considerable-window fingerprint is implicit: each cycle looks up
    exactly the rows of its window's jobs, so an unchanged pool
    re-encodes O(delta) rows (new jobs only) instead of O(J × N);
  * store events invalidate: an instance status change drops its job's
    rows (the novel-host constraint depends on failed-instance history),
    a job kill / pool move drops rows, quota/share/config/pool mutations
    bump a global epoch (conservative full invalidation — they can
    change which constraints apply).

Jobs in a placement group are never cached: their rows depend on other
members' running placements, which change outside this job's own event
stream.  Rows also bypass the cache entirely while the estimated-
completion constraint is active (rows become clock-dependent).
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, NamedTuple, Optional, Sequence

import numpy as np

from cook_tpu.models.store import Event, JobStore
from cook_tpu.obs import data_plane
from cook_tpu.utils.metrics import global_registry

# events that can change which quota/share/config-derived constraints
# apply; cheap to honor conservatively (an epoch bump = one full
# re-encode, amortized away the next cycle)
_EPOCH_EVENTS = frozenset((
    "quota/set", "quota/retracted", "share/set", "share/retracted",
    "config/updated", "pool/set", "pool/capacity",
))


class _PoolEntry:
    __slots__ = ("nodes_fp", "has_gpus", "attr_codes", "attr_vocab",
                 "hostname_to_idx", "rows", "dropped", "computing")

    def __init__(self):
        self.nodes_fp = None
        self.has_gpus = None
        self.attr_codes = None
        self.attr_vocab = None
        self.hostname_to_idx = None
        # job uuid -> (epoch, [N] bool row); LRU-bounded
        self.rows: collections.OrderedDict[str, tuple[int, np.ndarray]] = \
            collections.OrderedDict()
        # uuids invalidated WHILE the scheduler thread computes rows (the
        # compute read the store before the invalidating event): such a
        # drop must veto the row's write-back, or the stale row would be
        # served until the next event happens to drop it again.  Only
        # populated while a compute is in flight (`computing` > 0) and
        # cleared when it ends — recording every terminal-instance event
        # unconditionally would grow the set by dead jobs that never
        # recompute, and its overflow fallback would wipe the whole cache
        # on a steady churn of completions
        self.dropped: set[str] = set()
        self.computing: int = 0


def offers_fingerprint(cluster_offers: Sequence[tuple]) -> int:
    """Hash of the encode-relevant structure of a pool's (cluster, offer)
    list.  Everything `encode_nodes` + the static feasibility columns
    read, nothing the kernel reads (spare amounts churn every launch)."""
    return hash(tuple(
        (cluster.location, o.node_id, o.hostname, o.attributes,
         o.gpus > 0, o.port_count(), o.disk > 0)
        for cluster, o in cluster_offers
    ))


class RowServe(NamedTuple):
    """How one cacheable job's feasibility row was served this cycle —
    the per-row report consumers (the device mirror) key residency on.
    `cached` is False when the row could not be written back (epoch
    moved mid-compute, open balanced pre-row, mid-compute invalidation):
    such rows must not be treated as stable by any downstream cache."""

    epoch: int
    fresh: bool      # recomputed this cycle (False = served from cache)
    cached: bool     # the row is (still) in the cache at `epoch`


class EncodeCache:
    """Per-pool incremental encode state, invalidated by store events.

    Consumers that mirror this cache (the device-resident state,
    future shards) `subscribe()` a callback and observe invalidations
    as they land — `("row-dropped", job_uuid=...)` when a job's rows
    drop, `("epoch-bumped", epoch=...)` on a conservative full
    invalidation — instead of diffing fingerprints every cycle.
    Callbacks run OUTSIDE the cache lock (they may take their own
    locks) on the event-delivering thread; they must be cheap and must
    not call back into the cache."""

    def __init__(self, store: Optional[JobStore] = None, *,
                 max_rows_per_pool: int = 100_000):
        self._pools: dict[str, _PoolEntry] = {}
        self._epoch = 0
        self._lock = threading.Lock()
        self._max_rows = max_rows_per_pool
        self._subscribers: list[Callable] = []
        self._rows_counter = global_registry.counter(
            "match.encode_cache.rows",
            "feasibility rows served from / recomputed into the host-"
            "encode cache, by result")
        self._nodes_counter = global_registry.counter(
            "match.encode_cache.nodes",
            "node encodings served from / recomputed into the host-"
            "encode cache, by result")
        if store is not None:
            store.add_watcher(self._on_event)
            resync = getattr(store, "add_resync_listener", None)
            if resync is not None:
                resync(self.clear)

    # ------------------------------------------------------ subscribers

    def subscribe(self, callback: Callable) -> None:
        """Register an invalidation observer: callback(kind, **info)
        with kind "row-dropped" (job_uuid=...) or "epoch-bumped"
        (epoch=...)."""
        with self._lock:
            self._subscribers.append(callback)

    def _notify(self, kind: str, **info) -> None:
        # a sick subscriber must never block store-event delivery (the
        # mirror rebuilds from its own staleness checks; losing one
        # notification costs a rebuild, not correctness)
        from cook_tpu.utils.callbacks import notify_all

        notify_all(self._subscribers, f"encode-cache {kind}", kind,
                   **info)

    # ------------------------------------------------------- invalidation

    def _on_event(self, event: Event) -> None:
        kind = event.kind
        if kind in _EPOCH_EVENTS:
            with self._lock:
                self._epoch += 1
                epoch = self._epoch
            self._notify("epoch-bumped", epoch=epoch)
            return
        if kind == "instance/status":
            # failed-instance history feeds the novel-host constraint.
            # (instance/cancelled is deliberately NOT handled: a cancel
            # only marks intent — the row's inputs change at the terminal
            # instance/status transition that follows)
            self._drop_job(event.data.get("job"))
        elif kind in ("job/state", "job/pool-moved"):
            self._drop_job(event.data.get("uuid"))

    def _drop_job(self, job_uuid: Optional[str]) -> None:
        if not job_uuid:
            return
        epoch_bumped = False
        with self._lock:
            for entry in self._pools.values():
                entry.rows.pop(job_uuid, None)
                if not entry.computing:
                    continue  # no in-flight compute to veto
                if len(entry.dropped) < 10_000:
                    entry.dropped.add(job_uuid)
                else:
                    # overflow (event storm within ONE compute): fall
                    # back to a conservative epoch bump rather than
                    # forgetting an invalidation
                    self._epoch += 1
                    epoch_bumped = True
                    entry.dropped.clear()
            epoch = self._epoch
        self._notify("row-dropped", job_uuid=job_uuid)
        if epoch_bumped:
            self._notify("epoch-bumped", epoch=epoch)

    def clear(self) -> None:
        with self._lock:
            self._pools.clear()
            self._epoch += 1
            epoch = self._epoch
        self._notify("epoch-bumped", epoch=epoch)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # ------------------------------------------------------------- encode

    def encoded_nodes(self, pool: str, cluster_offers: Sequence[tuple]):
        """(EncodedNodes, fingerprint) for the pool's current offers,
        reusing the attribute/vocab encoding when the offer structure is
        unchanged (the offers list itself is always refreshed — spare
        amounts feed the kernel tensors and change every cycle)."""
        from cook_tpu.scheduler.constraints import EncodedNodes, encode_nodes

        offers = [o for _, o in cluster_offers]
        fp = offers_fingerprint(cluster_offers)
        with self._lock:
            entry = self._pools.setdefault(pool, _PoolEntry())
            # collision guard: a colliding fingerprint with a DIFFERENT
            # node count must rebuild — serving the cached attr/gpu
            # columns against a differently-sized offer list would
            # corrupt every downstream mask
            hit = (entry.nodes_fp == fp and entry.has_gpus is not None
                   and len(entry.has_gpus) == len(offers))
            if hit:
                nodes = EncodedNodes(
                    offers=offers,
                    hostname_to_idx=entry.hostname_to_idx,
                    has_gpus=entry.has_gpus,
                    attr_codes=entry.attr_codes,
                    attr_vocab=entry.attr_vocab,
                )
        if not hit:
            nodes = encode_nodes(offers)
            with self._lock:
                entry = self._pools.setdefault(pool, _PoolEntry())
                entry.nodes_fp = fp
                entry.hostname_to_idx = nodes.hostname_to_idx
                entry.has_gpus = nodes.has_gpus
                entry.attr_codes = nodes.attr_codes
                entry.attr_vocab = nodes.attr_vocab
                # rows encode against a specific node set; a structural
                # change invalidates every cached row of the pool
                entry.rows.clear()
        self._nodes_counter.inc(1, {"result": "hit" if hit else "miss"})
        # residency ledger: the node tensors are re-transferred every
        # cycle; a fingerprint hit means their encode-relevant content
        # was unchanged — the transfer was residency waste
        node_bytes = data_plane.NODE_ROW_BYTES * len(offers)
        data_plane.note_residency(0 if hit else node_bytes,
                                  node_bytes if hit else 0, kind="nodes")
        return nodes, fp

    # -------------------------------------------------------- feasibility

    @staticmethod
    def cacheable_job(job) -> bool:
        """Group members' rows depend on sibling placements that change
        outside this job's event stream — never cached."""
        return not job.group_uuid

    def feasibility(
        self,
        pool: str,
        jobs: Sequence,
        n_nodes: int,
        nodes_fp: int,
        compute: Callable[[list, dict[int, np.ndarray]], np.ndarray],
        balanced_pre_rows: Optional[dict[int, np.ndarray]] = None,
        served: Optional[dict[str, RowServe]] = None,
    ) -> np.ndarray:
        """Assemble the [J, N] mask from cached rows plus a delta
        computation.

        `compute(subset_jobs, subset_pre_rows)` must return the mask for
        just the uncached jobs (the caller closes over group context
        etc.); its balanced_pre_rows (keyed by subset index) are remapped
        into the caller's dict keyed by full-window index.  Returns a
        FRESH array — callers may mutate it (host reservations) without
        corrupting the cache.

        `served` (out-param) collects a RowServe per CACHEABLE job: how
        its row was obtained this cycle.  The device mirror keys slot
        persistence on it — a row the host cache itself refused to keep
        (mid-compute invalidation, open pre-closure) must not persist on
        device either."""
        j = len(jobs)
        feasible = np.empty((j, n_nodes), dtype=bool)
        with self._lock:
            epoch = self._epoch
            entry = self._pools.setdefault(pool, _PoolEntry())
            rows = entry.rows if entry.nodes_fp == nodes_fp else None
            subset_idx: list[int] = []
            for ji, job in enumerate(jobs):
                cached = (rows.get(job.uuid)
                          if rows is not None and self.cacheable_job(job)
                          else None)
                if (cached is not None and cached[0] == epoch
                        and cached[1].shape[0] == n_nodes):
                    feasible[ji] = cached[1]
                    rows.move_to_end(job.uuid)
                    if served is not None:
                        served[job.uuid] = RowServe(epoch, fresh=False,
                                                    cached=True)
                else:
                    subset_idx.append(ji)
            if subset_idx:
                # open the veto window: drops landing from here until the
                # write-back completes must not be overwritten by a row
                # computed from pre-event store state
                entry.computing += 1
        if subset_idx:
            subset = [jobs[i] for i in subset_idx]
            sub_pre_rows: dict[int, np.ndarray] = {}
            try:
                submask = np.asarray(compute(subset, sub_pre_rows),
                                     dtype=bool)
                with self._lock:
                    entry = self._pools.setdefault(pool, _PoolEntry())
                    store_rows = (entry.rows if entry.nodes_fp == nodes_fp
                                  and self._epoch == epoch else None)
                    for k, ji in enumerate(subset_idx):
                        feasible[ji] = submask[k]
                        cacheable = (store_rows is not None
                                     and self.cacheable_job(jobs[ji])
                                     # a row with an open pre-closure
                                     # variant is cycle-dependent; don't
                                     # cache it
                                     and k not in sub_pre_rows
                                     # an event invalidated this job while
                                     # the row was being computed: the
                                     # compute may predate the event's
                                     # effect — don't cache
                                     and jobs[ji].uuid not in entry.dropped)
                        if cacheable:
                            store_rows[jobs[ji].uuid] = (epoch,
                                                         submask[k].copy())
                        if served is not None \
                                and self.cacheable_job(jobs[ji]):
                            served[jobs[ji].uuid] = RowServe(
                                epoch, fresh=True, cached=cacheable)
                    if store_rows is not None:
                        while len(store_rows) > self._max_rows:
                            store_rows.popitem(last=False)
            finally:
                with self._lock:
                    entry = self._pools.setdefault(pool, _PoolEntry())
                    entry.computing = max(entry.computing - 1, 0)
                    if entry.computing == 0:
                        entry.dropped.clear()
            if balanced_pre_rows is not None:
                for k, row in sub_pre_rows.items():
                    balanced_pre_rows[subset_idx[k]] = row
        hits = j - len(subset_idx)
        if hits:
            self._rows_counter.inc(hits, {"result": "hit"})
        if subset_idx:
            self._rows_counter.inc(len(subset_idx), {"result": "miss"})
        # residency ledger (obs/data_plane.py): a cache-hit row's bytes
        # were re-transferred UNCHANGED — the per-cycle rebuild_fraction
        # is fresh / (fresh + cached) over exactly these row bytes
        data_plane.note_residency(len(subset_idx) * n_nodes,
                                  hits * n_nodes)
        return feasible
