"""Executor heartbeat liveness tracking.

Reference: cook.mesos.heartbeat (/root/reference/scheduler/src/cook/mesos/
heartbeat.clj): executors send periodic heartbeats; a task whose executor
goes silent past the timeout is failed mea-culpa (`heartbeat-lost`) and
killed, so a wedged node can't strand work forever.
"""
from __future__ import annotations

import threading
from typing import Callable

from cook_tpu.models.entities import InstanceStatus
from cook_tpu.models.store import JobStore


class HeartbeatMonitor:
    def __init__(
        self,
        store: JobStore,
        kill_fn: Callable[[str], None],
        *,
        timeout_ms: int = 120_000,
    ):
        self.store = store
        self.kill_fn = kill_fn
        self.timeout_ms = timeout_ms
        self._last: dict[str, int] = {}
        self._lock = threading.Lock()

    def notify(self, task_id: str) -> None:
        """A heartbeat arrived (reference: notify-heartbeat)."""
        with self._lock:
            self._last[task_id] = self.store.clock()

    def track(self, task_id: str) -> None:
        """Start expecting heartbeats for a launched task."""
        self.notify(task_id)

    def untrack(self, task_id: str) -> None:
        with self._lock:
            self._last.pop(task_id, None)

    def check(self) -> list[str]:
        """Kill tasks with stale heartbeats (handle-timeout,
        heartbeat.clj:66)."""
        now = self.store.clock()
        with self._lock:
            stale = [tid for tid, t in self._last.items()
                     if now - t > self.timeout_ms]
            for tid in stale:
                del self._last[tid]
        killed = []
        for task_id in stale:
            inst = self.store.instances.get(task_id)
            if inst is None or inst.status.terminal:
                continue
            self.store.update_instance_state(
                task_id, InstanceStatus.FAILED, "heartbeat-lost"
            )
            self.kill_fn(task_id)
            killed.append(task_id)
        return killed
